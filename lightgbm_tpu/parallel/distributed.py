"""Multi-host distributed runtime: membership + global mesh + placement.

Reference: src/network/linkers_socket.cpp:20-207 (machine-list parsing,
rank discovery, TCP handshake), src/network/network.cpp (Init), and the
per-rank data distribution of src/io/dataset_loader.cpp:505-550.

TPU-first design: membership and transport are `jax.distributed` —
every process calls `initialize(coordinator, num_processes, rank)`, the
mesh spans all global devices, and XLA routes the builder's `lax.psum`
/ `all_gather` over ICI/DCN. The reference's hand-rolled Bruck /
recursive-halving algorithms and socket linkers have no analog: topology
and algorithm selection belong to the compiler. What remains of the
reference's Network class is exactly this file: find my rank, connect,
and expose helpers to build global arrays from per-rank data.

Rank discovery mirrors linkers_socket.cpp:58-86: match a local
hostname/IP against the machine list; the LIGHTGBM_TPU_RANK env var
overrides (needed e.g. for multiple ranks on one host).
"""

import os
import time

import jax
import numpy as np

from ..utils import faults
from ..utils.log import Log
# machine-list parsing + rank discovery live in the jax-free
# parallel/machines.py (the supervisor process reads machine lists
# without importing jax); re-exported here for existing import paths
from .machines import (_local_addresses, _split_host_port,  # noqa: F401
                       find_local_rank, format_machine_list,
                       parse_machine_list)

_initialized = False


def _call_initialize(coordinator, num_processes, rank, timeout_s):
    """One jax.distributed.initialize attempt. Split out so the fault
    harness (`fail_distributed_init`) and tests can intercept it."""
    if faults.consume("fail_distributed_init"):
        raise RuntimeError("injected distributed-init failure")
    try:
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num_processes,
                                   process_id=rank,
                                   initialization_timeout=timeout_s)
    except TypeError:
        # older jax without initialization_timeout
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num_processes,
                                   process_id=rank)


def _initialize_with_retry(coordinator, num_processes, rank, retries=3,
                           backoff_s=1.0, timeout_s=120,
                           collectives="default"):
    """jax.distributed.initialize with a per-attempt timeout and
    exponential-backoff retries (TPU fleets routinely restart the
    coordinator pod first; a transient connect failure must not kill
    every worker). Every structured log line names the chosen
    collectives implementation (gloo vs the backend default) — the
    first thing to check when a multi-host bring-up fails is whether
    the CPU client even HAS cross-process collectives, and the journal
    must answer that without shell access to the dead host. Returns
    True on success, False when the backend was already initialized
    externally; fatal when retries are exhausted."""
    delay = max(0.0, float(backoff_s))
    last_error = None
    for attempt in range(int(retries) + 1):
        try:
            _call_initialize(coordinator, num_processes, rank, timeout_s)
            if attempt:
                Log.info("jax.distributed.initialize succeeded on "
                         "attempt %d (collectives=%s)", attempt + 1,
                         collectives)
            return True
        except RuntimeError as e:
            msg = str(e)
            # jax 0.4.x raises "distributed.initialize should only be
            # called once."; other versions say "already initialized"
            if ("already" in msg.lower()
                    or "only be called once" in msg.lower()):
                # backend already up (e.g. an external launcher
                # initialized distributed itself) — keep going with it
                Log.warning("jax.distributed.initialize skipped "
                            "(collectives=%s): %s", collectives, msg)
                return False
            last_error = msg
        if attempt < retries:
            Log.warning("jax.distributed.initialize failed (attempt "
                        "%d/%d, coordinator %s, rank %d of %d, "
                        "collectives=%s): %s; retrying in %.1fs",
                        attempt + 1, retries + 1, coordinator, rank,
                        num_processes, collectives, last_error, delay)
            if delay > 0:
                time.sleep(delay)
            delay = min(delay * 2 if delay > 0 else 1.0, 30.0)
    Log.fatal("jax.distributed.initialize failed after %d attempts "
              "(coordinator %s, rank %d of %d, collectives=%s): %s",
              retries + 1, coordinator, rank, num_processes, collectives,
              last_error)


def init_from_config(config):
    """Bring up jax.distributed from the reference's network config
    (machine_list_file / num_machines, include/LightGBM/config.h:219-226).
    No-op when already initialized or single-machine."""
    global _initialized
    if _initialized:
        return False
    if config is None or config.num_machines <= 1 or not config.machine_list_file:
        return False
    if not os.path.exists(config.machine_list_file):
        if os.environ.get("LIGHTGBM_TPU_RANK") is not None:
            # explicit multi-process launch: training solo here while
            # peers block in jax.distributed.initialize would deadlock
            # the job — die fast like the reference's socket linker
            Log.fatal("machine_list_file %s not found (rank %s)",
                      config.machine_list_file,
                      os.environ["LIGHTGBM_TPU_RANK"])
        # single-process run of a distributed conf (e.g. the reference's
        # examples/parallel_learning out of the box): model num_machines
        # with local mesh devices (parallel/learners.py make_mesh)
        Log.warning("machine_list_file %s not found; running single-"
                    "process with %d mesh devices",
                    config.machine_list_file, config.num_machines)
        return False
    machines = parse_machine_list(config.machine_list_file)
    if len(machines) < config.num_machines:
        Log.fatal("Machine list file only contains %d machines, but "
                  "num_machines is %d", len(machines), config.num_machines)
    machines = machines[:config.num_machines]
    env_rank = os.environ.get("LIGHTGBM_TPU_RANK")
    rank = int(env_rank) if env_rank is not None else find_local_rank(machines)
    if not 0 <= rank < config.num_machines:
        # a wrong LIGHTGBM_TPU_RANK (or a machine list edited out from
        # under a running job) must die loudly HERE: passing it through
        # would hang every healthy peer in the coordinator handshake
        Log.fatal("rank %d is out of range for num_machines=%d "
                  "(machine list %s has %d usable entries); check "
                  "LIGHTGBM_TPU_RANK against the machine list",
                  rank, config.num_machines, config.machine_list_file,
                  len(machines))
    faults.set_rank(rank)  # rank-targeted fault injection + heartbeats
    Log.set_rank(rank)     # rank-attributable interleaved child logs
    coordinator = f"{machines[0][0]}:{machines[0][1]}"
    # CPU multi-process collectives need an explicit implementation
    # (the default CPU client refuses cross-process computations with
    # "Multiprocess computations aren't implemented"); gloo ships with
    # this jax and is what the 2-process CPU test harness runs on. A
    # TPU backend ignores the knob; absent knob (API drift) means CPU
    # multi-host was unsupported anyway, so best-effort is correct.
    collectives = "default"
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        collectives = "gloo"
    except Exception:
        pass
    # NOTE: must run before anything initializes the XLA backend —
    # do not touch jax.devices()/process_count() above this line
    if not _initialize_with_retry(coordinator, config.num_machines, rank,
                                  retries=getattr(config, "init_retries", 3),
                                  backoff_s=getattr(config, "init_backoff_s",
                                                    1.0),
                                  timeout_s=getattr(config, "time_out", 120),
                                  collectives=collectives):
        return False
    _initialized = True
    Log.info("Distributed: rank %d of %d (coordinator %s), %d global "
             "devices, collectives=%s", rank, config.num_machines,
             coordinator, len(jax.devices()), collectives)
    return True


def process_rank():
    return jax.process_index()


def num_processes():
    return jax.process_count()


def is_multi_host():
    return jax.process_count() > 1


def place_global_rows(sharding, local_array):
    """Assemble a row-sharded global array from each process's local
    block (the analog of per-rank row storage, dataset_loader.cpp:505-550)."""
    return jax.make_array_from_process_local_data(sharding, local_array)


def place_replicated(sharding, full_array):
    """Global array whose value every process holds fully (bin matrices
    for feature-parallel, feature masks, per-feature tables)."""
    full_array = np.asarray(full_array)
    return jax.make_array_from_callback(
        full_array.shape, sharding, lambda idx: full_array[idx])


def partition_rows(n, rank, num_machines, query_boundaries=None):
    """Contiguous per-rank row range, aligned to query boundaries so no
    query is split (dataset_loader.cpp distributes rows; contiguous
    blocks give identical global histograms, hence identical trees).
    Returns (lo, hi)."""
    if query_boundaries is not None:
        qb = np.asarray(query_boundaries)
        nq = len(qb) - 1
        q_lo = (nq * rank) // num_machines
        q_hi = (nq * (rank + 1)) // num_machines
        return int(qb[q_lo]), int(qb[q_hi])
    lo = (n * rank) // num_machines
    hi = (n * (rank + 1)) // num_machines
    return lo, hi
