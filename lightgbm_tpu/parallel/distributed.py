"""Multi-host distributed runtime: membership + global mesh + placement.

Reference: src/network/linkers_socket.cpp:20-207 (machine-list parsing,
rank discovery, TCP handshake), src/network/network.cpp (Init), and the
per-rank data distribution of src/io/dataset_loader.cpp:505-550.

TPU-first design: membership and transport are `jax.distributed` —
every process calls `initialize(coordinator, num_processes, rank)`, the
mesh spans all global devices, and XLA routes the builder's `lax.psum`
/ `all_gather` over ICI/DCN. The reference's hand-rolled Bruck /
recursive-halving algorithms and socket linkers have no analog: topology
and algorithm selection belong to the compiler. What remains of the
reference's Network class is exactly this file: find my rank, connect,
and expose helpers to build global arrays from per-rank data.

Rank discovery mirrors linkers_socket.cpp:58-86: match a local
hostname/IP against the machine list; the LIGHTGBM_TPU_RANK env var
overrides (needed e.g. for multiple ranks on one host).
"""

import os
import socket

import jax
import numpy as np

from ..utils.log import Log

_initialized = False


def parse_machine_list(path):
    """`ip port` (or `ip:port`) lines -> [(ip, port)] (linkers_socket.cpp:36-56)."""
    machines = []
    with open(path) as f:
        for line in f:
            line = line.strip().replace(":", " ")
            if not line:
                continue
            parts = line.split()
            if len(parts) < 2:
                Log.fatal("Machine list file parse error: %s", line)
            machines.append((parts[0], int(parts[1])))
    return machines


def _local_addresses():
    names = {"localhost", "127.0.0.1", socket.gethostname()}
    try:
        host, aliases, ips = socket.gethostbyname_ex(socket.gethostname())
        names.update([host] + aliases + ips)
    except OSError:
        pass
    return names


def find_local_rank(machines):
    """linkers_socket.cpp:58-86: my rank is the first machine-list entry
    matching a local address."""
    local = _local_addresses()
    for i, (ip, _) in enumerate(machines):
        if ip in local:
            return i
    Log.fatal("Machine list file doesn't contain the local machine")


def init_from_config(config):
    """Bring up jax.distributed from the reference's network config
    (machine_list_file / num_machines, include/LightGBM/config.h:219-226).
    No-op when already initialized or single-machine."""
    global _initialized
    if _initialized:
        return False
    if config is None or config.num_machines <= 1 or not config.machine_list_file:
        return False
    if not os.path.exists(config.machine_list_file):
        if os.environ.get("LIGHTGBM_TPU_RANK") is not None:
            # explicit multi-process launch: training solo here while
            # peers block in jax.distributed.initialize would deadlock
            # the job — die fast like the reference's socket linker
            Log.fatal("machine_list_file %s not found (rank %s)",
                      config.machine_list_file,
                      os.environ["LIGHTGBM_TPU_RANK"])
        # single-process run of a distributed conf (e.g. the reference's
        # examples/parallel_learning out of the box): model num_machines
        # with local mesh devices (parallel/learners.py make_mesh)
        Log.warning("machine_list_file %s not found; running single-"
                    "process with %d mesh devices",
                    config.machine_list_file, config.num_machines)
        return False
    machines = parse_machine_list(config.machine_list_file)
    if len(machines) < config.num_machines:
        Log.fatal("Machine list file only contains %d machines, but "
                  "num_machines is %d", len(machines), config.num_machines)
    machines = machines[:config.num_machines]
    env_rank = os.environ.get("LIGHTGBM_TPU_RANK")
    rank = int(env_rank) if env_rank is not None else find_local_rank(machines)
    coordinator = f"{machines[0][0]}:{machines[0][1]}"
    try:
        # NOTE: must run before anything initializes the XLA backend —
        # do not touch jax.devices()/process_count() above this line
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=config.num_machines,
                                   process_id=rank)
    except RuntimeError as e:
        # backend already up (e.g. running under an external launcher
        # that initialized distributed itself) — keep going with it
        Log.warning("jax.distributed.initialize skipped: %s", str(e))
        return False
    _initialized = True
    Log.info("Distributed: rank %d of %d (coordinator %s), %d global devices",
             rank, config.num_machines, coordinator, len(jax.devices()))
    return True


def process_rank():
    return jax.process_index()


def num_processes():
    return jax.process_count()


def is_multi_host():
    return jax.process_count() > 1


def place_global_rows(sharding, local_array):
    """Assemble a row-sharded global array from each process's local
    block (the analog of per-rank row storage, dataset_loader.cpp:505-550)."""
    return jax.make_array_from_process_local_data(sharding, local_array)


def place_replicated(sharding, full_array):
    """Global array whose value every process holds fully (bin matrices
    for feature-parallel, feature masks, per-feature tables)."""
    full_array = np.asarray(full_array)
    return jax.make_array_from_callback(
        full_array.shape, sharding, lambda idx: full_array[idx])


def partition_rows(n, rank, num_machines, query_boundaries=None):
    """Contiguous per-rank row range, aligned to query boundaries so no
    query is split (dataset_loader.cpp distributes rows; contiguous
    blocks give identical global histograms, hence identical trees).
    Returns (lo, hi)."""
    if query_boundaries is not None:
        qb = np.asarray(query_boundaries)
        nq = len(qb) - 1
        q_lo = (nq * rank) // num_machines
        q_hi = (nq * (rank + 1)) // num_machines
        return int(qb[q_lo]), int(qb[q_hi])
    lo = (n * rank) // num_machines
    hi = (n * (rank + 1)) // num_machines
    return lo, hi
