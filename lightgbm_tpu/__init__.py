"""LightGBM-TPU: a TPU-native gradient boosting framework.

A from-scratch reimplementation of the capabilities of LightGBM
(reference: /root/reference, Dec-2016 snapshot) designed TPU-first:

- binned training data lives on device as dense integer arrays
  (features-major), never as floats;
- histogram construction is a batched one-hot contraction on the MXU;
- split finding is a vectorized cumulative scan over (feature, bin);
- the whole tree build is one jitted program (`lax.fori_loop` over
  leaf-wise splits, static shapes throughout);
- distributed training (data/feature/voting parallel) uses
  `jax.lax` collectives (psum / pmax / all_gather) over a
  `jax.sharding.Mesh` instead of sockets/MPI.

Public API mirrors the reference python-package
(`python-package/lightgbm/__init__.py:11-25`).
"""

from .basic import Dataset, Booster, LightGBMError
from .engine import train, cv
from .callback import (
    print_evaluation,
    record_evaluation,
    reset_parameter,
    early_stopping,
    EarlyStopException,
)

try:
    from .sklearn import LGBMModel, LGBMRegressor, LGBMClassifier, LGBMRanker
    SKLEARN_INSTALLED = True
except ImportError:  # pragma: no cover - sklearn is expected in this image
    SKLEARN_INSTALLED = False

__version__ = "0.1.0"

__all__ = [
    "Dataset", "Booster", "LightGBMError",
    "train", "cv",
    "print_evaluation", "record_evaluation", "reset_parameter",
    "early_stopping", "EarlyStopException",
    "LGBMModel", "LGBMRegressor", "LGBMClassifier", "LGBMRanker",
]
