"""graftlint: AST-based invariant linter for the lightgbm_tpu codebase.

No reference equivalent — the reference's correctness rules live in C++
type signatures; here they live in *idioms* (trace-time guards, atomic
write protocols, schema dicts) that no compiler checks. This package
turns the hand-maintained ones into machine-checked rules
(docs/Static-Analysis.md has the catalogue with each rule's
provenance):

- ``callback-in-mesh``      host callbacks reachable from shard_map
                            programs without ``callbacks_disabled()`` /
                            ``meshed_trace_guard()`` (the XLA-CPU
                            deadlock caveat, ops/histogram.py:154)
- ``unguarded-collective``  blocking device syncs in parallel paths
                            outside ``collective_guard`` (watchdog /
                            straggler attribution goes blind otherwise)
- ``non-atomic-shared-write``  shared run artifacts written without the
                            tmp+fsync+rename / manifest-last discipline
- ``precision-contract``    f64 leaking into device-traced builders,
                            f32 accumulation in documented-f64 host
                            reductions, raw ``float()`` on Kahan pairs
- ``nondeterminism``        wall clocks / unseeded RNG in modules under
                            the serial==parallel bit-parity contract
- ``journal-schema``        journal ``.event()`` record types missing
                            from telemetry/journal.py SCHEMA (the
                            static face of tools/check_journal.py)
- ``prometheus-naming``     metric name literals that violate the
                            exposition naming contract
                            (telemetry/prometheus.py lint_family_name —
                            the SAME implementation the runtime page
                            lint uses)
- ``config-doc-drift``      config.py knobs without a docs/Parameters.md
                            row or without any read site

Zero third-party deps (stdlib ``ast`` only), runs in well under 10s.
Suppression: inline ``# graftlint: disable=<rule>`` pragmas (same or
preceding line) and the committed baseline ``tools/lint_baseline.json``
(every entry carries a justification). CLI:

    python -m lightgbm_tpu.analysis [--json out.json] [--self-check]
    python tools/graftlint.py ...      # same, without importing jax

``make verify-lint`` gates both the fixture corpus (--self-check) and
the live tree (clean modulo the baseline) in CI.
"""

from .core import (REGISTRY, Fixture, ParsedFile, Project, Rule,
                   Severity, Violation, register)
from .engine import lint_project, load_rules
from .baseline import Baseline

__all__ = ["REGISTRY", "Fixture", "ParsedFile", "Project", "Rule",
           "Severity", "Violation", "register", "lint_project",
           "load_rules", "Baseline"]
