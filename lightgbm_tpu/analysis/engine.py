"""graftlint engine: load rules, run them, apply suppression.

Suppression precedence (pinned by tests/test_graftlint.py): an inline
``# graftlint: disable=<rule>`` pragma wins first (the suppression
lives next to the code, visible in review), then the committed
baseline (tools/lint_baseline.json). A violation suppressed by a
pragma never consumes a baseline entry, so baselines can't mask code
that already carries (or later gains) a pragma — the unused-entry
report stays truthful.
"""

import time
from dataclasses import dataclass, field

from .baseline import Baseline
from .core import REGISTRY, Project, Severity


def load_rules():
    """Import every rule module (populating REGISTRY) and return it."""
    from . import rules  # noqa: F401  (import side effect: @register)
    return REGISTRY


@dataclass
class LintResult:
    violations: list = field(default_factory=list)   # active
    suppressed: list = field(default_factory=list)   # pragma/baseline
    baseline_unused: list = field(default_factory=list)
    parse_errors: list = field(default_factory=list)
    files: int = 0
    elapsed_s: float = 0.0
    rules: tuple = ()

    @property
    def errors(self):
        return [v for v in self.violations
                if v.severity == Severity.ERROR]

    @property
    def warnings(self):
        return [v for v in self.violations
                if v.severity == Severity.WARNING]

    def as_dict(self):
        return {
            "version": 1,
            "files": self.files,
            "elapsed_s": round(self.elapsed_s, 3),
            "rules": list(self.rules),
            "violations": [v.as_dict() for v in self.violations],
            "suppressed": [v.as_dict() for v in self.suppressed],
            "baseline_unused": self.baseline_unused,
            "parse_errors": [{"file": f, "message": m}
                             for f, m in self.parse_errors],
            "error_count": len(self.errors),
            "warning_count": len(self.warnings),
        }


def lint_project(root, rule_names=None, use_baseline=True, project=None):
    """Run the (selected) rules over the project at ``root``.

    Returns a LintResult; raises BaselineError on a malformed baseline
    (a bad baseline must fail CI loudly, not silently un-suppress)."""
    t0 = time.perf_counter()
    registry = load_rules()
    names = tuple(rule_names) if rule_names else tuple(sorted(registry))
    unknown = [n for n in names if n not in registry]
    if unknown:
        raise ValueError(f"unknown rule(s): {', '.join(unknown)}; "
                         f"known: {', '.join(sorted(registry))}")
    proj = project if project is not None else Project(root)
    baseline = Baseline.load(proj.root) if use_baseline else Baseline()

    result = LintResult(files=len(proj.files), rules=names,
                        parse_errors=list(proj.errors))
    raw = []
    for name in names:
        raw.extend(registry[name].check(proj))
    raw.sort(key=lambda v: (v.path, v.line, v.rule))
    for v in raw:
        pf = proj.get(v.path)
        if pf is not None and pf.suppressed(v.line, v.rule):
            v.suppressed_by = "pragma"
            result.suppressed.append(v)
        elif baseline.suppresses(v):
            v.suppressed_by = "baseline"
            result.suppressed.append(v)
        else:
            result.violations.append(v)
    # a partial --rule run can only judge its own rules' entries:
    # entries for rules that didn't run are NOT unused, just untested
    result.baseline_unused = [e for e in baseline.unused()
                              if e["rule"] in names]
    result.elapsed_s = time.perf_counter() - t0
    return result
