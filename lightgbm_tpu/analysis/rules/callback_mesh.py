"""callback-in-mesh: host callbacks must not be traceable into
multi-device shard_map programs without a trace-time guard.

Provenance: host callbacks embedded in multi-device ``shard_map``
programs deadlock this image's XLA CPU runtime — the dispatching
thread blocks in a sharded execute while the callback worker threads
park on the GIL it holds (ops/histogram.py:154 ``callbacks_disabled``,
parallel/mesh.py:78 ``meshed_trace_guard``). The meshed learners must
therefore TRACE their builders under one of those guards, which makes
``chunk_mode()`` resolve "bincount" to the pure-XLA segment kernel.

Static model (over-approximate by design; see docs/Static-Analysis.md):

1. compute the set of functions from which ``jax.pure_callback`` /
   ``io_callback`` is reachable over UNGUARDED call edges
   (analysis/callgraph.py);
2. find every ``shard_map(fn, ...)`` site whose traced ``fn`` resolves
   to a callback-reaching function;
3. such a site is GUARDED when any of
   (a) the site itself is lexically under a guard ``with``;
   (b) some call site of the function containing it (transitively,
       over name-resolved callers) is under a guard ``with``;
   (c) the containing class hierarchy guards its builder dispatch: a
       method somewhere in the hierarchy wraps a call to another
       hierarchy method in a guard ``with`` (the meshed-learner family
       guards once in ``_MeshedTreeLearner.train_device`` and every
       subclass inherits it);
   otherwise it is flagged.

Sites whose traced fn cannot be resolved (a parameter, a lambda from
elsewhere) are skipped — the rule prefers silence to noise there; the
dynamic deadlock still has the runtime caveat comments.
"""

import ast

from ..callgraph import CB_GUARDS, CallGraph
from ..core import Fixture, Rule, Severity, register


def _is_shard_map_call(call, name):
    return name.rsplit(".", 1)[-1] == "shard_map" or \
        name.endswith("_exp_shard_map")


@register
class CallbackInMeshRule(Rule):
    name = "callback-in-mesh"
    doc = ("shard_map-traced program can reach jax.pure_callback "
           "without callbacks_disabled()/meshed_trace_guard()")
    severity = Severity.ERROR

    def check(self, project):
        graph = CallGraph(project)
        reaches = graph.reaches_callback()
        out = []
        for fi in graph.functions:
            for name, _, call in fi.calls:
                if not _is_shard_map_call(call, name):
                    continue
                traced = self._traced_fn(graph, fi, call)
                if traced is None or traced not in reaches:
                    continue
                if self._guarded(graph, fi, call):
                    continue
                out.append(self.violation(
                    fi.pf, call,
                    f"shard_map traces {traced.name!r}, which can reach "
                    f"jax.pure_callback, and no callbacks_disabled()/"
                    f"meshed_trace_guard() encloses the trace path — "
                    f"host callbacks in multi-device shard_map programs "
                    f"deadlock the XLA CPU runtime "
                    f"(ops/histogram.py callbacks_disabled)"))
        return out

    # ------------------------------------------------------- resolution

    def _traced_fn(self, graph, fi, call):
        """FunctionInfo of the traced callable: first positional arg
        (or ``fn=`` keyword), resolved as a Name against defs in the
        same file first, then uniquely across the project."""
        arg = None
        if call.args:
            arg = call.args[0]
        else:
            for kw in call.keywords:
                if kw.arg == "fn":
                    arg = kw.value
        if not isinstance(arg, ast.Name):
            return None
        cands = [c for c in graph.by_name.get(arg.id, ())
                 if c.pf is fi.pf]
        if not cands:
            cands = graph.by_name.get(arg.id, [])
        # ambiguous resolution (same name defined more than once at the
        # chosen scope) would attribute an arbitrary function's
        # callback-reachability — skip instead (silence over noise)
        return cands[0] if len(cands) == 1 else None

    # ----------------------------------------------------------- guards

    def _guarded(self, graph, fi, call):
        # (a) lexical guard at the trace site, or at the dispatch of
        # the shard_map result (tracing happens at first CALL of the
        # wrapped fn, so `fn = shard_map(...); with guard(): fn(x)`
        # is the common guarded shape)
        if getattr(call, "_g_guards", frozenset()) & CB_GUARDS:
            return True
        parent = getattr(call, "_g_parent", None)
        while isinstance(parent, ast.Call):   # jax.jit(shard_map(...))
            parent = getattr(parent, "_g_parent", None)
        if isinstance(parent, ast.Assign) and len(parent.targets) == 1 \
                and isinstance(parent.targets[0], ast.Name):
            target = parent.targets[0].id
            for sub in ast.walk(fi.node):
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Name) \
                        and sub.func.id == target \
                        and getattr(sub, "_g_guards",
                                    frozenset()) & CB_GUARDS:
                    return True
        # (b) a caller chain wraps the containing function in a guard
        seen = set()
        frontier = {fi.node.name}
        for _ in range(8):   # bounded caller-chain walk
            next_frontier = set()
            for name in frontier:
                if name in seen:
                    continue
                seen.add(name)
                for caller, cb_guarded, _node in graph.callers_of(name):
                    if cb_guarded:
                        return True
                    next_frontier.add(caller.node.name)
            if not next_frontier - seen:
                break
            frontier = next_frontier
        # (c) the class hierarchy guards its dispatch somewhere
        if fi.cls is not None:
            hier = graph.hierarchy_of(fi.cls)
            method_names = {m.name for m in graph.methods_of(hier)}
            for m in graph.methods_of(hier):
                for name, cb_guarded, _node in m.calls:
                    if cb_guarded and \
                            name.rsplit(".", 1)[-1] in method_names:
                        return True
        return False

    # --------------------------------------------------------- fixtures

    def fixtures(self):
        common = {
            "lightgbm_tpu/ops/kern.py": (
                "import jax\n"
                "def chunk_kernel(x):\n"
                "    return jax.pure_callback(lambda a: a, x, x)\n"
            ),
        }
        bad = dict(common)
        bad["lightgbm_tpu/parallel/newlearner.py"] = (
            "import jax\n"
            "from jax.experimental.shard_map import shard_map\n"
            "from ..ops.kern import chunk_kernel\n"
            "def build(bins):\n"
            "    return chunk_kernel(bins)\n"
            "def train(mesh, bins):\n"
            "    fn = shard_map(build, mesh=mesh, in_specs=None,\n"
            "                   out_specs=None)\n"
            "    return fn(bins)\n"
        )
        good = dict(common)
        good["lightgbm_tpu/parallel/newlearner.py"] = (
            "import jax\n"
            "from jax.experimental.shard_map import shard_map\n"
            "from .mesh import meshed_trace_guard\n"
            "from ..ops.kern import chunk_kernel\n"
            "def build(bins):\n"
            "    return chunk_kernel(bins)\n"
            "def train(mesh, bins):\n"
            "    fn = shard_map(build, mesh=mesh, in_specs=None,\n"
            "                   out_specs=None)\n"
            "    with meshed_trace_guard():\n"
            "        return fn(bins)\n"
        )
        # guard applied one caller up the chain, not at the site
        good_caller = dict(common)
        good_caller["lightgbm_tpu/parallel/newlearner.py"] = (
            "from jax.experimental.shard_map import shard_map\n"
            "from .mesh import meshed_trace_guard\n"
            "from ..ops.kern import chunk_kernel\n"
            "def build(bins):\n"
            "    return chunk_kernel(bins)\n"
            "def dispatch(mesh, bins):\n"
            "    fn = shard_map(build, mesh=mesh, in_specs=None,\n"
            "                   out_specs=None)\n"
            "    return fn(bins)\n"
            "def train(mesh, bins):\n"
            "    with meshed_trace_guard():\n"
            "        return dispatch(mesh, bins)\n"
        )
        # traced fn holds no callback path at all -> nothing to flag
        good_nocb = {
            "lightgbm_tpu/parallel/newlearner.py": (
                "from jax.experimental.shard_map import shard_map\n"
                "def build(bins):\n"
                "    return bins + 1\n"
                "def train(mesh, bins):\n"
                "    fn = shard_map(build, mesh=mesh, in_specs=None,\n"
                "                   out_specs=None)\n"
                "    return fn(bins)\n"
            ),
        }
        return [
            Fixture("unguarded-mesh-callback", bad, expect=1),
            Fixture("guarded-at-site", good, expect=0),
            Fixture("guarded-in-caller", good_caller, expect=0),
            Fixture("no-callback-path", good_nocb, expect=0),
        ]
