"""non-atomic-shared-write: shared run artifacts must be written with
the tmp+fsync+rename (or append-only / manifest-last) discipline.

Provenance: crash-safety across the checkpoint store
(utils/checkpoint.py ``atomic_open``: sibling tmp -> flush -> fsync ->
``os.replace`` -> dir fsync), the fleet registry (staged version dir,
MANIFEST.json written last, ``os.rename``), the block store, the
heartbeat/marker files (tmp+``os.replace``; fsync deliberately
skipped — losing a beat is harmless, a torn concurrent read is not)
and the journal (single ``os.write`` of a full line to an O_APPEND
fd). A plain ``open(path, "w")`` of any of these artifacts reverts a
kill-at-any-instant guarantee to "sometimes a torn file that a peer
then reads".

Scope: the modules that own shared on-disk artifacts (snapshot /
registry / journal / block-store / heartbeat / profile / binary-cache
writers). Detection is per enclosing function: a write-mode ``open``
(or ``np.save*`` / ``json.dump`` / ``Path.write_text``) is accepted
when
  (a) it goes through ``atomic_open`` / ``atomic_write_*``; or
  (b) the target expression (or the local Name it was assigned from)
      mentions a tmp path AND the same function pairs it with
      ``os.replace`` / ``os.rename``; or
  (c) it's an append (``"a"`` modes; O_APPEND fds are handled by
      ``os.open``, which the rule doesn't flag); or
  (d) it writes into an in-memory buffer, not a path.
Everything else is flagged.
"""

import ast
import re

from ..core import (Fixture, Rule, Severity, call_name, node_source,
                    register)

SCOPE_RES = tuple(re.compile(p) for p in (
    r"^lightgbm_tpu/utils/checkpoint\.py$",
    r"^lightgbm_tpu/parallel/heartbeat\.py$",
    r"^lightgbm_tpu/supervisor\.py$",
    r"^lightgbm_tpu/data/block_store\.py$",
    r"^lightgbm_tpu/telemetry/(journal|export|history)\.py$",
    r"^lightgbm_tpu/fleet/",
    r"^lightgbm_tpu/io/(dataset|profile)\.py$",
    r"^lightgbm_tpu/models/gbdt\.py$",
))

WRITE_MODES = ("w", "wb", "w+", "wb+", "wt", "xb", "x")
ATOMIC_HELPERS = frozenset({"atomic_open", "atomic_write_bytes",
                            "atomic_write_text", "atomic_write_json",
                            "atomic_save_npy", "_atomic_write_bytes",
                            "_atomic_save_npy"})
RENAMES = frozenset({"os.replace", "os.rename"})


def _in_scope(rel):
    return any(p.match(rel) for p in SCOPE_RES)


@register
class NonAtomicSharedWriteRule(Rule):
    name = "non-atomic-shared-write"
    doc = ("shared artifact written without tmp+fsync+rename / "
           "append-only discipline")
    severity = Severity.ERROR

    def check(self, project):
        out = []
        for pf in project.files:
            if not _in_scope(pf.rel):
                continue
            for func in pf.functions():
                out.extend(self._check_function(pf, func))
        return out

    def _check_function(self, pf, func):
        has_rename = False
        tmp_names = set()     # local Names assigned from tmp-ish exprs
        handles = set()       # `with <call>(...) as f:` handle Names —
        #                       the opening call is where atomicity is
        #                       checked; writes INTO the handle aren't
        writes = []           # (call, target_expr, kind)
        for node in ast.walk(func):
            if isinstance(node, ast.With):
                for item in node.items:
                    if isinstance(item.context_expr, ast.Call) and \
                            isinstance(item.optional_vars, ast.Name):
                        handles.add(item.optional_vars.id)
            # nested defs are visited as their own functions
            if getattr(node, "_g_func", None) is not func and node is not func:
                continue
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                src = node_source(pf, node.value)
                if "tmp" in src.lower() or "mkstemp" in src \
                        or "TemporaryDirectory" in src:
                    tmp_names.add(node.targets[0].id)
                if "BytesIO" in src or "StringIO" in src:
                    handles.add(node.targets[0].id)   # in-memory buffer
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if not name:
                continue
            if name in RENAMES:
                has_rename = True
            last = name.rsplit(".", 1)[-1]
            if last in ATOMIC_HELPERS:
                continue
            target = self._write_target(node, name, last)
            if target is not None and not (
                    isinstance(target, ast.Name) and target.id in handles):
                writes.append((node, target, name))

        out = []
        for call, target, name in writes:
            src = node_source(pf, target)
            tmpish = ("tmp" in src.lower()
                      or (isinstance(target, ast.Name)
                          and target.id in tmp_names))
            if tmpish and has_rename:
                continue
            out.append(self.violation(
                pf, call,
                f"{name}(...) writes a shared artifact non-atomically "
                f"— use utils/checkpoint.py atomic_open/atomic_write_* "
                f"or the tmp+os.replace idiom (a kill mid-write leaves "
                f"a torn file peers will read)"))
        return out

    def _write_target(self, call, name, last):
        """The path expression being written, or None when this call is
        not a path write (read mode, append, in-memory buffer)."""
        if last == "open" and name in ("open", "io.open"):
            if not call.args:
                return None
            mode = "r"
            if len(call.args) >= 2 and isinstance(call.args[1],
                                                  ast.Constant):
                mode = str(call.args[1].value)
            for kw in call.keywords:
                if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                    mode = str(kw.value.value)
            if mode not in WRITE_MODES:
                return None
            return call.args[0]
        if last in ("save", "savez", "savez_compressed") and \
                name.startswith(("np.", "numpy.")):
            if not call.args:
                return None
            target = call.args[0]
            if isinstance(target, ast.Call) and \
                    "BytesIO" in call_name(target):
                return None   # in-memory archive
            return target
        if last in ("write_text", "write_bytes"):
            return call.func.value if isinstance(call.func,
                                                 ast.Attribute) else None
        if name == "json.dump":
            # file target is the 2nd positional; writing into a handle
            # opened atomically is caught at the open() site instead,
            # so only flag dumps straight into open(...) write modes
            if len(call.args) >= 2 and isinstance(call.args[1], ast.Call):
                inner = call.args[1]
                return self._write_target(inner, call_name(inner),
                                          call_name(inner).rsplit(".", 1)[-1])
            return None
        return None

    def fixtures(self):
        bad = {
            "lightgbm_tpu/fleet/registry.py": (
                "import json, os\n"
                "def write_pointer(directory, version):\n"
                "    path = os.path.join(directory, 'CURRENT')\n"
                "    with open(path, 'w') as f:\n"
                "        f.write(str(version))\n"
            ),
        }
        good_tmp = {
            "lightgbm_tpu/fleet/registry.py": (
                "import json, os\n"
                "def write_pointer(directory, version):\n"
                "    path = os.path.join(directory, 'CURRENT')\n"
                "    tmp = f'{path}.tmp.{os.getpid()}'\n"
                "    with open(tmp, 'w') as f:\n"
                "        f.write(str(version))\n"
                "        f.flush()\n"
                "        os.fsync(f.fileno())\n"
                "    os.replace(tmp, path)\n"
            ),
        }
        good_helper = {
            "lightgbm_tpu/fleet/registry.py": (
                "from ..utils.checkpoint import atomic_write_text\n"
                "def write_pointer(directory, version):\n"
                "    atomic_write_text(directory + '/CURRENT', "
                "str(version))\n"
            ),
        }
        good_out_of_scope = {
            "lightgbm_tpu/io/parser.py": (
                "def dump_debug(path, text):\n"
                "    with open(path, 'w') as f:\n"
                "        f.write(text)\n"
            ),
        }
        good_read = {
            "lightgbm_tpu/fleet/registry.py": (
                "import json\n"
                "def read_pointer(path):\n"
                "    with open(path) as f:\n"
                "        return json.load(f)\n"
            ),
        }
        return [
            Fixture("plain-write", bad, expect=1),
            Fixture("tmp-replace-idiom", good_tmp, expect=0),
            Fixture("atomic-helper", good_helper, expect=0),
            Fixture("out-of-scope-module", good_out_of_scope, expect=0),
            Fixture("read-mode", good_read, expect=0),
        ]
