"""prometheus-naming: metric name literals must survive the exposition
naming contract.

Provenance: telemetry/prometheus.py maps internal registry names to
canonical exposition names at the render boundary (`canonical_name`:
``_s``/``_ms`` -> ``_seconds`` with value scaling, ``_pct`` ->
``_ratio``, counters forced ``*_total``) and `lint_names` audits every
served page. But the runtime audit only sees pages a test actually
renders — a metric minted on a rarely-scraped path (or behind a knob)
ships unchecked. This rule runs the SAME per-family check statically
over every metric-name string literal at registry call sites:
``.inc("...")`` (counter), ``.observe("...")`` (summary),
``.set("...", v)`` / ``.counter/.gauge/.histogram("...")``. Each
literal is passed through the real ``sanitize_name`` +
``canonical_name`` + ``lint_family_name`` — imported from
telemetry/prometheus.py itself (loaded by file path, so the linter
never imports jax), which is what makes the static and runtime lint a
single implementation (tests/test_graftlint.py pins the identity).

Names built dynamically (f-strings over feature names, etc.) are
skipped; the runtime page audit still covers those.
"""

import ast
import importlib.util
import os

from ..core import Fixture, Rule, Severity, register

# call attr -> metric kind for the canonical mapping
_KINDS = {"inc": "counter", "counter": "counter",
          "observe": "summary", "histogram": "summary",
          "set": "gauge", "gauge": "gauge"}

PROM_REL = "lightgbm_tpu/telemetry/prometheus.py"

_PROM_CACHE = {}


def _prometheus(project=None):
    """The real telemetry/prometheus.py, loaded by file path (its only
    import is `re`, so this works without the parent package/jax).

    Resolution order: the LINTED project's copy (so linting another
    checkout applies THAT tree's contract, same as journal-schema
    reading the linted tree's SCHEMA), falling back to the copy shipped
    next to this rule (fixture projects carry no prometheus.py but
    still lint against the real contract)."""
    path = None
    if project is not None:
        pf = project.get(PROM_REL)
        if pf is not None:
            path = pf.path
    if path is None:
        path = os.path.normpath(os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            os.pardir, os.pardir, "telemetry", "prometheus.py"))
    mod = _PROM_CACHE.get(path)
    if mod is None:
        try:
            spec = importlib.util.spec_from_file_location(
                "_graftlint_prometheus", path)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            for attr in ("sanitize_name", "canonical_name",
                         "lint_family_name"):
                getattr(mod, attr)
        except Exception:
            # the linted tree's copy is broken/incomplete: fall back
            # to the shipped contract rather than crashing the run
            if project is not None:
                return _prometheus(None)
            raise
        _PROM_CACHE[path] = mod
    return mod


@register
class PrometheusNamingRule(Rule):
    name = "prometheus-naming"
    doc = ("metric name literal violates the exposition naming "
           "contract (telemetry/prometheus.py lint_family_name)")
    severity = Severity.ERROR

    def check(self, project):
        prom = _prometheus(project)
        out = []
        for pf in project.in_package():
            if pf.rel.startswith("lightgbm_tpu/analysis/"):
                continue   # rule fixtures carry deliberate violations
            for call in pf.calls():
                hit = self._metric_literal(call)
                if hit is None:
                    continue
                literal, kind = hit
                base, _scale = prom.canonical_name(
                    prom.sanitize_name(literal), kind)
                for msg in prom.lint_family_name(base, kind):
                    out.append(self.violation(
                        pf, call,
                        f"metric name {literal!r} renders as {base!r}: "
                        f"{msg} (naming contract, "
                        f"telemetry/prometheus.py)"))
        return out

    def _metric_literal(self, call):
        if not isinstance(call.func, ast.Attribute):
            return None
        attr = call.func.attr
        kind = _KINDS.get(attr)
        if kind is None or not call.args:
            return None
        first = call.args[0]
        if not (isinstance(first, ast.Constant)
                and isinstance(first.value, str)):
            return None
        # .set() is too generic a method name to trust on arity != 2
        if attr == "set" and len(call.args) != 2:
            return None
        return first.value, kind

    def fixtures(self):
        bad = {
            "lightgbm_tpu/telemetry/consumers.py": (
                "def account(m, dt):\n"
                "    m.observe('request_millis', dt)\n"
                "    m.inc('swap!!count')\n"
            ),
        }
        good = {
            "lightgbm_tpu/telemetry/consumers.py": (
                "def account(m, dt):\n"
                "    m.observe('request_ms', dt)\n"
                "    m.inc('swap_count')\n"
                "    m.set('queue_depth', 3)\n"
            ),
        }
        good_dynamic = {
            "lightgbm_tpu/telemetry/consumers.py": (
                "def account(m, feature, v):\n"
                "    m.set(f'drift_psi_{feature}', v)\n"
            ),
        }
        return [
            # 'request_millis' keeps its legacy suffix through
            # canonical_name (only _ms/_s/_secs/_pct/_per_s are
            # mapped); 'swap!!count' sanitizes to a __-run name
            Fixture("bad-literals", bad, expect=2),
            Fixture("canonical-internal-names", good, expect=0),
            Fixture("dynamic-name-skipped", good_dynamic, expect=0),
        ]
