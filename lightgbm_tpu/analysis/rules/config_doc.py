"""config-doc-drift: every knob in config.py must have a
docs/Parameters.md row and at least one read site.

Provenance: PRs 6-12 added ~30 knobs by hand, each time editing three
places — the ``Config`` dataclass, the Parameters table, and the code
that reads the knob. Drift modes this rule catches:

- a knob with no Parameters.md row (users can't discover it);
- a knob no code ever reads (``cfg.<name>`` attribute access or
  ``getattr(cfg, "<name>")`` anywhere outside the Config class body) —
  either dead, or its wiring was lost in a refactor;
- (warning) a Parameters.md row naming a knob that doesn't exist in
  config.py — rows marked ``*(serving)*`` are serve-CLI flags with no
  Config field by design and are exempt.

Derived (non-knob) Config fields carry an inline
``# graftlint: disable=config-doc-drift`` pragma in config.py.
"""

import ast
import os
import re

from ..core import Fixture, Rule, Severity, call_name, register

CONFIG_REL = "lightgbm_tpu/config.py"
PARAMS_REL = "docs/Parameters.md"
_ROW_RE = re.compile(r"^\|\s*`([a-z0-9_]+)`(?P<rest>[^|]*)\|", re.M)


def config_fields(pf):
    """[(name, lineno)] of Config dataclass AnnAssign fields."""
    for node in pf.tree.body:
        if isinstance(node, ast.ClassDef) and node.name == "Config":
            return [(s.target.id, s.lineno) for s in node.body
                    if isinstance(s, ast.AnnAssign)
                    and isinstance(s.target, ast.Name)], node
    return [], None


def doc_rows(text):
    """{name: is_cli_only} from Parameters.md table rows. Rows whose
    first cell carries a ``*(serving)*`` marker are serve-CLI flags."""
    rows = {}
    for m in _ROW_RE.finditer(text):
        rows[m.group(1)] = "(serving)" in m.group("rest")
    return rows


@register
class ConfigDocDriftRule(Rule):
    name = "config-doc-drift"
    doc = ("config.py knob without a docs/Parameters.md row or without "
           "any read site")
    severity = Severity.ERROR

    def check(self, project):
        cfg = project.get(CONFIG_REL)
        if cfg is None:
            return []
        fields, cls_node = config_fields(cfg)
        if not fields:
            return []
        params_path = None
        cand = os.path.join(project.root, PARAMS_REL)
        if os.path.exists(cand):
            params_path = cand
        rows = {}
        if params_path:
            with open(params_path, "r", encoding="utf-8") as f:
                rows = doc_rows(f.read())

        reads = self._read_sites(project, cfg, cls_node,
                                 {name for name, _ in fields})
        out = []

        class _Loc:
            def __init__(self, lineno):
                self.lineno = lineno
                self._g_func = None

        for name, lineno in fields:
            if params_path and name not in rows:
                out.append(self.violation(
                    cfg, _Loc(lineno),
                    f"knob {name!r} has no row in docs/Parameters.md — "
                    f"every key=value parameter must be documented "
                    f"there"))
            if name not in reads:
                out.append(self.violation(
                    cfg, _Loc(lineno),
                    f"knob {name!r} is never read (no `.{name}` "
                    f"attribute access or getattr(_, '{name}') outside "
                    f"the Config class) — dead knob or lost wiring"))
        field_names = {name for name, _ in fields}
        for row, cli_only in sorted(rows.items()):
            if row not in field_names and not cli_only:
                out.append(self.violation(
                    cfg, _Loc(1),
                    f"docs/Parameters.md documents {row!r} but "
                    f"config.py has no such knob (stale row? mark "
                    f"serve-CLI-only flags with *(serving)*)",
                    severity=Severity.WARNING))
        return out

    def _read_sites(self, project, cfg_pf, cls_node, names):
        """Knob names with >=1 read: attribute access ``x.<name>`` or
        ``getattr(x, "<name>")`` anywhere in the project except the
        Config class body (validate()/check_param_conflict() reading
        their own fields is bookkeeping, not wiring)."""
        cls_range = (cls_node.lineno, cls_node.end_lineno) \
            if cls_node is not None else (0, -1)
        reads = set()
        for pf in project.files:
            if pf.rel.startswith(("tests/", "lightgbm_tpu/analysis/")):
                continue   # tests/fixtures don't count as wiring
            for node in ast.walk(pf.tree):
                in_cfg_cls = (pf is cfg_pf
                              and cls_range[0] <= getattr(node, "lineno", 0)
                              <= cls_range[1])
                if in_cfg_cls:
                    continue
                if isinstance(node, ast.Attribute) and node.attr in names \
                        and isinstance(node.ctx, ast.Load):
                    reads.add(node.attr)
                elif isinstance(node, ast.Call):
                    if call_name(node) == "getattr" and \
                            len(node.args) >= 2 and \
                            isinstance(node.args[1], ast.Constant) and \
                            node.args[1].value in names:
                        reads.add(node.args[1].value)
        return reads

    def fixtures(self):
        doc = ("# Parameters\n\n"
               "| Parameter | Default | Aliases |\n"
               "|---|---|---|\n"
               "| `num_leaves` | 127 |  |\n"
               "| `serving_precision` *(serving)* | f32 |  |\n")
        bad = {
            "lightgbm_tpu/config.py": (
                "from dataclasses import dataclass\n"
                "@dataclass\n"
                "class Config:\n"
                "    num_leaves: int = 127\n"
                "    mystery_knob: int = 0\n"
            ),
            "docs/Parameters.md": doc,
            "lightgbm_tpu/engine.py": (
                "def train(cfg):\n"
                "    return cfg.num_leaves\n"
            ),
        }
        good = {
            "lightgbm_tpu/config.py": (
                "from dataclasses import dataclass\n"
                "@dataclass\n"
                "class Config:\n"
                "    num_leaves: int = 127\n"
            ),
            "docs/Parameters.md": doc,
            "lightgbm_tpu/engine.py": (
                "def train(cfg):\n"
                "    return cfg.num_leaves\n"
            ),
        }
        bad_stale_row = {
            "lightgbm_tpu/config.py": (
                "from dataclasses import dataclass\n"
                "@dataclass\n"
                "class Config:\n"
                "    num_leaves: int = 127\n"
            ),
            "docs/Parameters.md": doc + "| `retired_knob` | 1 |  |\n",
            "lightgbm_tpu/engine.py": (
                "def train(cfg):\n"
                "    return cfg.num_leaves\n"
            ),
        }
        good_pragma = {
            "lightgbm_tpu/config.py": (
                "from dataclasses import dataclass\n"
                "@dataclass\n"
                "class Config:\n"
                "    num_leaves: int = 127\n"
                "    # derived, not a user knob\n"
                "    is_parallel: bool = False  "
                "# graftlint: disable=config-doc-drift\n"
            ),
            "docs/Parameters.md": doc,
            "lightgbm_tpu/engine.py": (
                "def train(cfg):\n"
                "    return cfg.num_leaves and cfg.is_parallel\n"
            ),
        }
        return [
            # mystery_knob: no doc row AND no read site -> 2
            Fixture("undocumented-unread-knob", bad, expect=2),
            Fixture("documented-read-knob", good, expect=0),
            Fixture("stale-doc-row", bad_stale_row, expect=1),
            # the derived field's missing doc row is pragma-suppressed
            Fixture("derived-field-pragma", good_pragma, expect=0),
        ]
