"""Rule modules. Importing this package registers every rule
(``@register`` in each module populates ``core.REGISTRY``). New rules:
drop a module here, import it below, ship fixtures — see
docs/Static-Analysis.md "Adding a rule"."""

from . import (atomic_writes, callback_mesh, collectives, config_doc,
               determinism, journal_schema, precision,
               prom_naming, trace_context, unbounded_io)  # noqa: F401
