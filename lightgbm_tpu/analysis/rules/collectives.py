"""unguarded-collective: blocking device syncs in parallel paths must
be armed by ``collective_guard``.

Provenance: the collective watchdog (parallel/heartbeat.py
``collective_guard`` / ``CollectiveWatchdog.armed``) only sees syncs
it brackets — an unguarded blocking sync in a parallel path means a
dead/straggling peer wedges the process with the watchdog blind (no
named abort, no straggler attribution, exit-117 path never fires), and
since PR 12 an unguarded sync is also invisible to the comm profiler's
wait/overlap accounting even on healthy runs.

Scope: ``lightgbm_tpu/{parallel,models,data}/`` — the modules that run
training-path device programs. Flagged sync calls:
``jax.block_until_ready(...)`` / ``x.block_until_ready()``,
``jax.device_get(...)``, and zero-arg ``.item()`` (a scalar device
pull). A call is fine when lexically inside ``with
collective_guard(...)`` / ``WATCHDOG.armed(...)`` (any with-item).
``np.asarray`` on device values is a sync too but indistinguishable
from host-array plumbing statically — the rule stays silent there and
the guard-at-the-enclosing-sync discipline covers it in practice.
"""

import ast
import re

from ..core import Fixture, Rule, Severity, register

SCOPE_RE = re.compile(r"^lightgbm_tpu/(parallel|models|data)/")
SYNC_GUARDS = frozenset({"collective_guard", "armed"})
SYNC_LAST = frozenset({"block_until_ready", "device_get", "item"})


@register
class UnguardedCollectiveRule(Rule):
    name = "unguarded-collective"
    doc = ("blocking device sync in a parallel path outside "
           "collective_guard — watchdog/straggler attribution is blind "
           "to it")
    severity = Severity.ERROR

    def check(self, project):
        out = []
        for pf in project.files:
            if not SCOPE_RE.match(pf.rel):
                continue
            if pf.rel.endswith("parallel/heartbeat.py"):
                continue  # the guard machinery itself
            for call in pf.calls():
                name = self._sync_name(pf, call)
                if name is None:
                    continue
                if getattr(call, "_g_guards", frozenset()) & SYNC_GUARDS:
                    continue
                out.append(self.violation(
                    pf, call,
                    f"blocking device sync {name!r} outside "
                    f"collective_guard — wrap it so the watchdog can "
                    f"name a hang and the comm profiler can attribute "
                    f"the wait (parallel/heartbeat.py)"))
        return out

    def _sync_name(self, pf, call):
        from ..core import call_name
        name = call_name(call)
        if not name:
            return None
        last = name.rsplit(".", 1)[-1]
        if last not in SYNC_LAST:
            return None
        if last == "item":
            # zero-arg method call: the device-scalar pull shape
            # (dict.items() is 'items', so it never matches here)
            if call.args or call.keywords or \
                    not isinstance(call.func, ast.Attribute):
                return None
        if last in ("device_get", "block_until_ready"):
            # jax.device_get / jax.block_until_ready / x.block_until_ready()
            if last == "device_get" and not name.startswith("jax."):
                return None
        return name

    def fixtures(self):
        bad = {
            "lightgbm_tpu/parallel/sync.py": (
                "import jax\n"
                "def fetch(out):\n"
                "    host = jax.device_get(out)\n"
                "    jax.block_until_ready(host)\n"
                "    return out['n'].item()\n"
            ),
        }
        good = {
            "lightgbm_tpu/parallel/sync.py": (
                "import jax\n"
                "from .heartbeat import collective_guard\n"
                "def fetch(out):\n"
                "    with collective_guard('leaf_value_fetch'):\n"
                "        host = jax.device_get(out)\n"
                "        jax.block_until_ready(host)\n"
                "        return out['n'].item()\n"
            ),
        }
        out_of_scope = {
            "lightgbm_tpu/serving/sync.py": (
                "import jax\n"
                "def fetch(out):\n"
                "    return jax.device_get(out)\n"
            ),
        }
        not_sync = {
            "lightgbm_tpu/models/clean.py": (
                "def walk(d):\n"
                "    return sorted(d.items())\n"
            ),
        }
        return [
            Fixture("unguarded-syncs", bad, expect=3),
            Fixture("guarded-syncs", good, expect=0),
            Fixture("serving-out-of-scope", out_of_scope, expect=0),
            Fixture("dict-items-not-flagged", not_sync, expect=0),
        ]
