"""unbounded-io: outbound network calls in the serving/fleet stack
must carry an explicit timeout.

Provenance: every hang the resilience layer defends against
(docs/Resilience.md) re-enters through one unbounded socket — a
health probe against a wedged replica, an aggregator scrape of a dead
rank, a router proxy call into a stalled batcher. The stdlib defaults
are INFINITE (`urllib.request.urlopen`, `http.client.HTTPConnection`,
`socket.create_connection` all block forever without a timeout), so a
single forgotten kwarg turns "one replica is slow" into "the router's
handler pool is gone". The RegistryFollower and aggregator polled over
HTTP for two PRs with nothing guarding this; now the front door
multiplies the number of outbound calls, the invariant gets a lint.

Scope: ``lightgbm_tpu/serving/``, ``lightgbm_tpu/fleet/`` and
``lightgbm_tpu/telemetry/aggregate.py`` — the processes that talk to
other processes. Flagged calls:

- ``urlopen(...)`` without a ``timeout=`` kwarg (or third positional);
- ``HTTPConnection(...)`` / ``HTTPSConnection(...)`` without a
  ``timeout=`` kwarg;
- ``socket.create_connection(...)`` without a timeout (second
  positional or kwarg).

A timeout passed positionally counts — the rule wants the bound to
exist, not a style. Genuinely inherited timeouts (a connection object
configured elsewhere) go in the baseline with a justification.
"""

import re

from ..core import Fixture, Rule, Severity, register

SCOPE_RE = re.compile(
    r"^lightgbm_tpu/(serving|fleet)/|^lightgbm_tpu/telemetry/aggregate\.py$")

# last dotted segment -> how many positionals until the timeout slot
# (urlopen(url, data, timeout) / create_connection(addr, timeout) /
# HTTP(S)Connection(host, port, timeout))
TIMEOUT_POSITION = {
    "urlopen": 2,
    "create_connection": 1,
    "HTTPConnection": 2,
    "HTTPSConnection": 2,
}


@register
class UnboundedIoRule(Rule):
    name = "unbounded-io"
    doc = ("outbound network call in serving/fleet without an explicit "
           "timeout — the stdlib default blocks forever")
    severity = Severity.ERROR

    def check(self, project):
        out = []
        for pf in project.files:
            if not SCOPE_RE.match(pf.rel):
                continue
            for call in pf.calls():
                name = self._unbounded_name(call)
                if name is None:
                    continue
                out.append(self.violation(
                    pf, call,
                    f"{name!r} without an explicit timeout — the "
                    f"stdlib default blocks forever; one wedged peer "
                    f"would pin this thread (pass timeout=..., "
                    f"docs/Resilience.md)"))
        return out

    def _unbounded_name(self, call):
        from ..core import call_name
        name = call_name(call)
        if not name:
            return None
        last = name.rsplit(".", 1)[-1]
        slot = TIMEOUT_POSITION.get(last)
        if slot is None:
            return None
        if last == "create_connection" and "." in name \
                and not name.endswith("socket.create_connection"):
            return None   # some other module's create_connection
        if any(kw.arg == "timeout" for kw in call.keywords):
            return None
        if len(call.args) > slot:
            return None   # timeout passed positionally
        return name

    def fixtures(self):
        bad = {
            "lightgbm_tpu/serving/probe.py": (
                "import socket\n"
                "import urllib.request\n"
                "from http.client import HTTPConnection\n"
                "def poke(url, host, port):\n"
                "    urllib.request.urlopen(url)\n"
                "    HTTPConnection(host, port)\n"
                "    socket.create_connection((host, port))\n"
            ),
        }
        good = {
            "lightgbm_tpu/fleet/probe.py": (
                "import socket\n"
                "import urllib.request\n"
                "from http.client import HTTPConnection\n"
                "def poke(url, host, port):\n"
                "    urllib.request.urlopen(url, timeout=5.0)\n"
                "    HTTPConnection(host, port, 5.0)\n"
                "    socket.create_connection((host, port), 5.0)\n"
            ),
        }
        out_of_scope = {
            "lightgbm_tpu/models/probe.py": (
                "import urllib.request\n"
                "def poke(url):\n"
                "    return urllib.request.urlopen(url)\n"
            ),
        }
        not_network = {
            "lightgbm_tpu/fleet/clean.py": (
                "def create_connection(pool):\n"
                "    return pool.create_connection()\n"
            ),
        }
        return [
            Fixture("unbounded-calls", bad, expect=3),
            Fixture("bounded-calls", good, expect=0),
            Fixture("out-of-scope", out_of_scope, expect=0),
            Fixture("non-network-name", not_network, expect=0),
        ]
