"""journal-schema: every journal ``.event("<type>", ...)`` call must
name a record type declared in telemetry/journal.py ``SCHEMA``.

Provenance: the journal is the machine-readable training timeline;
``tools/check_journal.py`` lints *produced* journals against SCHEMA at
runtime ("unknown event names are not [allowed]"). A writer emitting an
undeclared event therefore produces journals that fail the runtime
lint — but only on the code path that actually ran. This rule is the
static face of the same contract: it reads the SCHEMA dict *from the
linted tree's own source* (AST extraction, no imports, so the linter
stays jax-free and the two can't diverge) and checks every event-name
string literal at the write sites.

Write-site heuristic: attribute calls ``<recv>.event("lit", ...)``
where the receiver text looks journal-ish (contains ``journal``, or is
the conventional one-letter handle ``j``). ``RunJournal.iteration()``
is schema-valid by construction. Dynamically computed event names are
skipped — the runtime lint still covers those.
"""

import ast
import re

from ..core import Fixture, Rule, Severity, node_source, register

JOURNAL_REL = "lightgbm_tpu/telemetry/journal.py"
_RECV_RE = re.compile(r"(journal|(^|\.)j$)", re.I)


def extract_schema_keys(pf):
    """Top-level ``SCHEMA = {...}`` string keys of journal.py, by AST.
    None when the module or the dict is missing (rule then skips —
    there is no contract to check against)."""
    for node in pf.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "SCHEMA" \
                and isinstance(node.value, ast.Dict):
            keys = set()
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    keys.add(k.value)
            return keys
    return None


@register
class JournalSchemaRule(Rule):
    name = "journal-schema"
    doc = ("journal .event() record type not declared in "
           "telemetry/journal.py SCHEMA")
    severity = Severity.ERROR

    def check(self, project):
        jf = project.get(JOURNAL_REL)
        if jf is None:
            return []
        keys = extract_schema_keys(jf)
        if not keys:
            return []
        out = []
        for pf in project.files:
            for call in pf.calls():
                if not isinstance(call.func, ast.Attribute) \
                        or call.func.attr != "event" or not call.args:
                    continue
                first = call.args[0]
                if not (isinstance(first, ast.Constant)
                        and isinstance(first.value, str)):
                    continue
                recv = node_source(pf, call.func.value)
                if not _RECV_RE.search(recv):
                    continue
                if first.value not in keys:
                    out.append(self.violation(
                        pf, call,
                        f"journal event {first.value!r} is not declared "
                        f"in telemetry/journal.py SCHEMA — "
                        f"check_journal.py will reject every journal "
                        f"this path writes; add the record type to "
                        f"SCHEMA (and docs/Observability.md) first"))
        return out

    def fixtures(self):
        schema_src = (
            "SCHEMA = {\n"
            "    'run_start': {'required': {}, 'optional': {}},\n"
            "    'iteration': {'required': {}, 'optional': {}},\n"
            "}\n"
        )
        bad = {
            "lightgbm_tpu/telemetry/journal.py": schema_src,
            "lightgbm_tpu/models/writer.py": (
                "def note(journal, n):\n"
                "    journal.event('leaf_stats', leaves=n)\n"
            ),
        }
        good = {
            "lightgbm_tpu/telemetry/journal.py": schema_src,
            "lightgbm_tpu/models/writer.py": (
                "def note(journal, n):\n"
                "    journal.event('iteration', iteration=n)\n"
            ),
        }
        good_nonjournal = {
            "lightgbm_tpu/telemetry/journal.py": schema_src,
            "lightgbm_tpu/models/writer.py": (
                "def fire(bus):\n"
                "    bus.event('leaf_stats')\n"
            ),
        }
        return [
            Fixture("undeclared-event", bad, expect=1),
            Fixture("declared-event", good, expect=0),
            Fixture("non-journal-receiver", good_nonjournal, expect=0),
        ]
