"""nondeterminism: wall clocks and unseeded RNG must stay out of the
modules under the bit-parity / byte-identical-resume contracts.

Provenance: trees must be bit-identical across serial / data-parallel /
out-of-core engines (ops/, models/, data/) and byte-identical across
checkpoint resume — which also pins the sampling RNG streams
(utils/random.py Random wraps a SEEDED np.random.RandomState; the
config seed fan-out in config.py feeds it). A stray
``np.random.rand()`` (process-global stream), an unseeded
``default_rng()``, or a ``time.time()`` feeding computation breaks
those contracts in ways the parity tests only catch for the paths they
exercise.

Checks (scope ``lightgbm_tpu/{ops,models,io,data,parallel}/`` +
``lightgbm_tpu/utils/random.py``):

- unseeded constructors: ``np.random.RandomState()`` /
  ``np.random.default_rng()`` with no arguments;
- process-global numpy draws/seeding: ``np.random.rand`` / ``randn`` /
  ``randint`` / ``random`` / ``choice`` / ``shuffle`` /
  ``permutation`` / ``uniform`` / ``normal`` / ``seed``;
- stdlib ``random`` module draws (the module, not a local named
  ``random``: only flagged when the file ``import random``s);
- ``time.time()`` in ``ops/`` / ``models/`` / ``io/`` only — wall
  clock as *data* in an engine path (``time.perf_counter`` for
  durations and telemetry wall stamps in parallel/data are
  legitimate and unflagged).
"""

import ast
import re

from ..core import Fixture, Rule, Severity, call_name, register

SCOPE_RE = re.compile(
    r"^lightgbm_tpu/(ops|models|io|data|parallel)/|"
    r"^lightgbm_tpu/utils/random\.py$")
TIME_SCOPE_RE = re.compile(r"^lightgbm_tpu/(ops|models|io)/")

_GLOBAL_DRAWS = frozenset({"rand", "randn", "randint", "random", "choice",
                           "shuffle", "permutation", "uniform", "normal",
                           "seed"})
_STDLIB_DRAWS = frozenset({"random", "randint", "randrange", "choice",
                           "shuffle", "sample", "uniform", "seed",
                           "gauss"})


@register
class NondeterminismRule(Rule):
    name = "nondeterminism"
    doc = ("wall clock / unseeded or process-global RNG in a module "
           "under the bit-parity or byte-identical-resume contract")
    severity = Severity.ERROR

    def check(self, project):
        out = []
        for pf in project.files:
            if not SCOPE_RE.match(pf.rel):
                continue
            imports_random = self._imports_stdlib_random(pf)
            for call in pf.calls():
                name = call_name(call)
                if not name:
                    continue
                v = self._classify(pf, call, name, imports_random)
                if v:
                    out.append(self.violation(pf, call, v))
        return out

    def _imports_stdlib_random(self, pf):
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "random" and alias.asname is None:
                        return True
        return False

    def _classify(self, pf, call, name, imports_random):
        if name in ("np.random.RandomState", "np.random.default_rng",
                    "numpy.random.RandomState",
                    "numpy.random.default_rng"):
            if not call.args and not call.keywords:
                return (f"{name}() without a seed — every RNG stream in "
                        f"parity/resume-contract modules must derive "
                        f"from the config seed fan-out (config.py)")
            return None
        parts = name.split(".")
        if len(parts) == 3 and parts[0] in ("np", "numpy") \
                and parts[1] == "random" and parts[2] in _GLOBAL_DRAWS:
            return (f"{name}() uses the process-global numpy RNG stream "
                    f"— draws are order-dependent across the whole "
                    f"process, breaking bit-parity and resume; use a "
                    f"seeded utils/random.py Random")
        if imports_random and len(parts) == 2 and parts[0] == "random" \
                and parts[1] in _STDLIB_DRAWS:
            return (f"{name}() uses the process-global stdlib RNG — "
                    f"use a seeded utils/random.py Random")
        if name == "time.time" and TIME_SCOPE_RE.match(pf.rel):
            return ("time.time() in an engine module — wall clock as "
                    "data breaks reproducibility; use "
                    "time.perf_counter() for durations, or journal "
                    "timestamps at the telemetry layer")
        return None

    def fixtures(self):
        bad = {
            "lightgbm_tpu/models/sampler.py": (
                "import random\n"
                "import time\n"
                "import numpy as np\n"
                "def draw(n):\n"
                "    rng = np.random.default_rng()\n"
                "    np.random.seed(0)\n"
                "    t = time.time()\n"
                "    return random.randint(0, n), t\n"
            ),
        }
        good = {
            "lightgbm_tpu/models/sampler.py": (
                "import time\n"
                "import numpy as np\n"
                "from ..utils.random import Random\n"
                "def draw(n, seed):\n"
                "    rng = np.random.default_rng(seed)\n"
                "    r = Random(seed)\n"
                "    t0 = time.perf_counter()\n"
                "    return r.next_int(0, n), time.perf_counter() - t0\n"
            ),
        }
        good_parallel_wallclock = {
            # heartbeat-style wall stamps in parallel/ are protocol
            # data, not engine data — time.time is only flagged in
            # ops/models/io
            "lightgbm_tpu/parallel/beats.py": (
                "import time\n"
                "def beat():\n"
                "    return {'time': time.time()}\n"
            ),
        }
        return [
            Fixture("unseeded-and-global", bad, expect=4),
            Fixture("seeded-and-perf-counter", good, expect=0),
            Fixture("parallel-wallclock-ok", good_parallel_wallclock,
                    expect=0),
        ]
