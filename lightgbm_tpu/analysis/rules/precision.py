"""precision-contract: the f32-Kahan / host-f64 split must not blur.

Provenance: the histogram engine's serial==parallel bit-parity
contract (Mitchell & Frank-style deterministic building,
arXiv:1806.11248) rests on chunked *f32* Kahan-pair arithmetic on
device (ops/histogram.py) with *f64* accumulation only inside the
host bincount callbacks, and the prediction/serving reference path
reduces leaf values in host f64 (models/gbdt.py, serving). Three ways
code has tried to blur that line:

- ``jnp.float64`` in device-traced builder code: jax runs with x64
  disabled — the cast silently produces f32 on device but f64 under
  ``JAX_ENABLE_X64`` debugging, i.e. a parity break that only shows in
  the one place you can't reproduce it;
- f32 accumulation inside a host reduction whose docstring *documents*
  f64 (``np.sum(..., dtype=np.float32)`` in a "reduces in f64"
  function);
- raw ``float(...)`` on a Kahan pair value: collapsing (value,
  residual) by truncation instead of through the documented fold
  helpers (``hist_pair_fold_collapse``, ``kahan_fold``) drops the
  compensation term.

Scope: ``lightgbm_tpu/{ops,models,parallel,data}/``.
"""

import ast
import re

from ..core import Fixture, Rule, Severity, call_name, node_source, register

SCOPE_RE = re.compile(r"^lightgbm_tpu/(ops|models|parallel|data)/")
_F64_DOC = re.compile(r"\bf64\b|float64", re.I)
_PAIRISH = re.compile(r"pair|kahan", re.I)
_HOST_REDUCERS = frozenset({"sum", "cumsum", "dot", "einsum", "add.reduce"})


@register
class PrecisionContractRule(Rule):
    name = "precision-contract"
    doc = ("f64 in device-traced builders, f32 accumulation in "
           "documented-f64 host reductions, or raw float() on Kahan "
           "pairs")
    severity = Severity.ERROR

    def check(self, project):
        out = []
        for pf in project.files:
            if not SCOPE_RE.match(pf.rel):
                continue
            out.extend(self._check_file(pf))
        return out

    def _check_file(self, pf):
        out = []
        for node in ast.walk(pf.tree):
            # (1) jnp.float64 anywhere in traced-builder scope
            if isinstance(node, ast.Attribute) and node.attr == "float64":
                base = node_source(pf, node.value)
                if base in ("jnp", "jax.numpy"):
                    out.append(self.violation(
                        pf, node,
                        "jnp.float64 in device-traced builder scope — "
                        "device arithmetic is f32 by contract (x64 is "
                        "disabled; under JAX_ENABLE_X64 this silently "
                        "changes the traced program and breaks "
                        "serial==parallel bit-parity)"))
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            # (3) raw float() on a Kahan pair expression
            if name == "float" and len(node.args) == 1:
                src = node_source(pf, node.args[0])
                if _PAIRISH.search(src):
                    out.append(self.violation(
                        pf, node,
                        f"raw float() on a Kahan pair expression "
                        f"({src[:40]!r}) — collapse through the fold "
                        f"helpers (hist_pair_fold_collapse / "
                        f"kahan_fold) or the compensation term is "
                        f"silently dropped"))
        # (2) f32 accumulation in documented-f64 host reductions
        for func in pf.functions():
            doc = ast.get_docstring(func) or ""
            if not _F64_DOC.search(doc):
                continue
            for node in ast.walk(func):
                if getattr(node, "_g_func", None) is not func:
                    continue
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                last = name.rsplit(".", 1)[-1]
                if last not in _HOST_REDUCERS or \
                        not name.startswith(("np.", "numpy.")):
                    continue
                for kw in node.keywords:
                    if kw.arg == "dtype" and \
                            "float32" in node_source(pf, kw.value):
                        out.append(self.violation(
                            pf, node,
                            f"{name}(dtype=float32) inside a function "
                            f"whose docstring documents f64 "
                            f"accumulation — the reduction no longer "
                            f"matches its contract"))
        return out

    def fixtures(self):
        bad = {
            "lightgbm_tpu/ops/newkern.py": (
                "import jax.numpy as jnp\n"
                "import numpy as np\n"
                "def fold(x):\n"
                "    return x.astype(jnp.float64)\n"
                "def collapse(hist_pair):\n"
                "    return float(hist_pair[0])\n"
                "def reduce_host(x):\n"
                "    \"\"\"Reduces leaf values in f64.\"\"\"\n"
                "    return np.sum(x, dtype=np.float32)\n"
            ),
        }
        good = {
            "lightgbm_tpu/ops/newkern.py": (
                "import jax.numpy as jnp\n"
                "import numpy as np\n"
                "def fold(x):\n"
                "    return x.astype(jnp.float32)\n"
                "def collapse(hist_pair):\n"
                "    hi, lo = hist_pair\n"
                "    return hi + lo\n"
                "def reduce_host(x):\n"
                "    \"\"\"Reduces leaf values in f64.\"\"\"\n"
                "    return np.sum(x, dtype=np.float64)\n"
            ),
        }
        good_host_f64 = {
            # np.float64 on HOST (outside jnp) is the contract, not a
            # violation
            "lightgbm_tpu/models/hostpath.py": (
                "import numpy as np\n"
                "def gather(leaves):\n"
                "    return np.asarray(leaves, dtype=np.float64)\n"
            ),
        }
        # the linear-leaf solver (models/linear_leaves.py) accumulates
        # per-leaf normal equations in host f64 over the canonical fit
        # chunk grid — ITS serial==out-of-core bit-parity contract.
        # Pin that an f32 downgrade of a documented-f64 accumulation in
        # leaf-solver-shaped code is caught.
        leaf_solver_bad = {
            "lightgbm_tpu/models/linsolve.py": (
                "import numpy as np\n"
                "def accumulate_normal_eq(xw, g):\n"
                "    \"\"\"Accumulates the per-leaf normal equations in\n"
                "    host f64 over canonical fit chunks (the\n"
                "    linear_leaves.py serial==streamed contract).\"\"\"\n"
                "    return np.einsum('ni,nj->ij', xw, xw,\n"
                "                     dtype=np.float32)\n"
            ),
        }
        leaf_solver_good = {
            "lightgbm_tpu/models/linsolve.py": (
                "import numpy as np\n"
                "def accumulate_normal_eq(xw, g):\n"
                "    \"\"\"Accumulates the per-leaf normal equations in\n"
                "    host f64 over canonical fit chunks (the\n"
                "    linear_leaves.py serial==streamed contract).\"\"\"\n"
                "    return np.einsum('ni,nj->ij', xw, xw,\n"
                "                     dtype=np.float64)\n"
            ),
        }
        return [
            Fixture("f64-trace-f32-doc-float-pair", bad, expect=3),
            Fixture("contract-respected", good, expect=0),
            Fixture("host-f64-legit", good_host_f64, expect=0),
            Fixture("leaf-solver-f32-downgrade", leaf_solver_bad,
                    expect=1),
            Fixture("leaf-solver-f64-contract", leaf_solver_good,
                    expect=0),
        ]
