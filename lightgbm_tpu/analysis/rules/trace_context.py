"""trace-context-propagation: outbound HTTP calls in the serving/fleet
stack that set headers must route them through the trace helper.

Provenance: the distributed-tracing layer (telemetry/disttrace.py,
docs/Observability.md) only works when EVERY hop forwards the
`X-Trace-Ctx` header — one call site that builds its own header dict
and skips `disttrace.inject_headers(...)` silently severs the trace
tree at that hop, and the break is invisible until an incident needs
exactly the trace that no longer stitches. The fleet router forwards
the context, the replicas continue it, the load generator originates
it; this rule keeps the invariant as new hops appear.

Scope: ``lightgbm_tpu/fleet/`` and ``lightgbm_tpu/serving/`` — the
processes that forward requests to other processes. Flagged calls:

- ``conn.request(method, path, body, headers=...)`` (http.client)
  passing headers, in a function that never calls ``inject_headers``;
- ``urllib.request.Request(url, data, headers)`` passing headers, in
  a function that never calls ``inject_headers``;
- ``conn.putheader(...)`` under the same condition.

`inject_headers` passes header dicts through UNSTAMPED when no trace
context is active, so routing every outbound header dict through it
costs one dict copy and never forces tracing on — there is no reason
for a header-setting hop to skip it. A genuinely trace-free protocol
(none today) goes in the baseline with a justification.
"""

import re

from ..core import Fixture, Rule, Severity, register, call_name

SCOPE_RE = re.compile(r"^lightgbm_tpu/(fleet|serving)/")

# callee last-segment -> index of the headers positional
# (HTTPConnection.request(method, url, body, headers) / urllib
# Request(url, data, headers)); putheader always sets a header
HEADERS_POSITION = {"request": 3, "Request": 2}


@register
class TraceContextRule(Rule):
    name = "trace-context-propagation"
    doc = ("outbound HTTP call sets headers without routing them "
           "through disttrace.inject_headers — the trace tree severs "
           "at this hop")
    severity = Severity.ERROR

    def check(self, project):
        out = []
        for pf in project.files:
            if not SCOPE_RE.match(pf.rel):
                continue
            injected = self._injecting_funcs(pf)
            for call in pf.calls():
                name = self._header_setting_name(call)
                if name is None:
                    continue
                func = getattr(call, "_g_func", None)
                if (func or pf.tree) in injected:
                    continue
                out.append(self.violation(
                    pf, call,
                    f"{name!r} sets outbound headers but the "
                    f"enclosing function never calls "
                    f"disttrace.inject_headers(...) — the X-Trace-Ctx "
                    f"hop breaks here (docs/Observability.md)"))
        return out

    @staticmethod
    def _injecting_funcs(pf):
        """Set of function nodes (plus the module tree for top-level
        code) containing an ``inject_headers`` call."""
        import ast
        found = set()
        for call in pf.calls():
            nm = call_name(call)
            if nm == "inject_headers" or nm.endswith(".inject_headers"):
                found.add(getattr(call, "_g_func", None) or pf.tree)
        # a nested helper's call also covers its enclosing function:
        # walk up so `def outer(): def _send(): inject_headers(...)`
        # marks both (the outbound call may sit in either)
        for node in list(found):
            cur = getattr(node, "_g_parent", None)
            while cur is not None:
                if isinstance(cur, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    found.add(cur)
                cur = getattr(cur, "_g_parent", None)
        return found

    @staticmethod
    def _header_setting_name(call):
        name = call_name(call)
        if not name:
            return None
        last = name.rsplit(".", 1)[-1]
        if last == "putheader" and "." in name:
            return name
        slot = HEADERS_POSITION.get(last)
        if slot is None:
            return None
        if last == "request" and "." not in name:
            return None   # bare request() is not an HTTP client call
        has_headers = any(kw.arg == "headers" for kw in call.keywords) \
            or len(call.args) > slot
        return name if has_headers else None

    def fixtures(self):
        bad = {
            "lightgbm_tpu/fleet/hop.py": (
                "import urllib.request\n"
                "from http.client import HTTPConnection\n"
                "def forward(url, host, port, body, hdrs):\n"
                "    req = urllib.request.Request(url, data=body,\n"
                "                                 headers=hdrs)\n"
                "    conn = HTTPConnection(host, port, timeout=5.0)\n"
                "    conn.request('POST', '/predict', body,\n"
                "                 headers=hdrs)\n"
            ),
        }
        good = {
            "lightgbm_tpu/serving/hop.py": (
                "import urllib.request\n"
                "from ..telemetry import disttrace\n"
                "def forward(url, body, hdrs):\n"
                "    hdrs = disttrace.inject_headers(hdrs)\n"
                "    return urllib.request.Request(url, data=body,\n"
                "                                  headers=hdrs)\n"
            ),
        }
        no_headers = {
            "lightgbm_tpu/fleet/probe.py": (
                "from http.client import HTTPConnection\n"
                "def probe(host, port):\n"
                "    conn = HTTPConnection(host, port, timeout=2.0)\n"
                "    conn.request('GET', '/healthz')\n"
            ),
        }
        out_of_scope = {
            "lightgbm_tpu/telemetry/pull.py": (
                "import urllib.request\n"
                "def pull(url, hdrs):\n"
                "    return urllib.request.Request(url, headers=hdrs)\n"
            ),
        }
        nested_helper = {
            "lightgbm_tpu/fleet/nested.py": (
                "from http.client import HTTPConnection\n"
                "from ..telemetry import disttrace\n"
                "def forward(host, port, body, hdrs):\n"
                "    def _stamp(h):\n"
                "        return disttrace.inject_headers(h)\n"
                "    conn = HTTPConnection(host, port, timeout=5.0)\n"
                "    conn.request('POST', '/p', body, _stamp(hdrs))\n"
            ),
        }
        return [
            Fixture("headers-without-helper", bad, expect=2),
            Fixture("headers-through-helper", good, expect=0),
            Fixture("no-headers-set", no_headers, expect=0),
            Fixture("out-of-scope", out_of_scope, expect=0),
            Fixture("nested-helper-counts", nested_helper, expect=0),
        ]
