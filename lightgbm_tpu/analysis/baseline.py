"""Committed suppression baseline (tools/lint_baseline.json).

Existing accepted violations must not block CI, but every acceptance
must be *explained*: each entry carries a mandatory non-empty
``justification`` string (the engine refuses a baseline without one).
Entries match on (rule, file, stripped flagged-line text) — line
CONTENT, not line numbers, so surrounding edits don't invalidate the
baseline while any change to the flagged line itself (the thing that
was actually reviewed) does. One entry suppresses every identical
occurrence in its file. Unused entries are reported so the file can't
silently rot; ``--update-baseline`` rewrites it from the current tree
(justifications of surviving entries are preserved, new entries get a
FIXME placeholder the engine then rejects until a human fills it in).
"""

import json
import os

BASELINE_REL = os.path.join("tools", "lint_baseline.json")
PLACEHOLDER = "FIXME: justify or fix"


class BaselineError(Exception):
    """The baseline file is malformed (bad JSON, missing fields, or an
    entry without a justification)."""


class Baseline:
    def __init__(self, entries=None, path=None):
        self.entries = list(entries or [])
        self.path = path
        self._used = [False] * len(self.entries)

    @classmethod
    def load(cls, root, strict=True):
        """Load tools/lint_baseline.json. ``strict`` (the lint path)
        rejects malformed entries and placeholder justifications;
        ``strict=False`` (the --update-baseline path, which exists to
        REWRITE a rotten baseline) keeps whatever well-formed entries
        it can so their justifications survive the rewrite."""
        path = os.path.join(root, BASELINE_REL)
        if not os.path.exists(path):
            return cls(path=path)
        try:
            with open(path, "r", encoding="utf-8") as f:
                data = json.load(f)
        except ValueError as e:
            if not strict:
                return cls(path=path)
            raise BaselineError(f"{path}: not valid JSON: {e}")
        entries = data.get("entries")
        if not isinstance(entries, list):
            if not strict:
                return cls(path=path)
            raise BaselineError(f"{path}: top-level 'entries' list missing")
        kept = []
        for i, e in enumerate(entries):
            ok = isinstance(e, dict) and all(
                isinstance(e.get(key), str) and e[key].strip()
                for key in ("rule", "file", "line_text", "justification"))
            if not ok:
                if strict:
                    raise BaselineError(
                        f"{path}: entry {i} missing a non-empty "
                        f"rule/file/line_text/justification")
                continue
            if e["justification"].startswith("FIXME"):
                if strict:
                    raise BaselineError(
                        f"{path}: entry {i} ({e['rule']} {e['file']}) "
                        f"still carries the placeholder justification — "
                        f"write a real one or fix the violation")
                continue   # a placeholder is not worth preserving
            kept.append(e)
        return cls(kept, path=path)

    def suppresses(self, violation):
        hit = False
        for i, e in enumerate(self.entries):
            if (e["rule"] == violation.rule and e["file"] == violation.path
                    and e["line_text"] == violation.line_text
                    and violation.line_text):
                self._used[i] = True
                hit = True
        return hit

    def unused(self):
        return [e for i, e in enumerate(self.entries) if not self._used[i]]

    @staticmethod
    def render(violations, old=None, carry=()):
        """Baseline JSON text for ``violations`` (the still-unsuppressed
        ones), inheriting justifications from ``old`` when the same
        (rule, file, line_text) key survives. ``carry`` entries are
        preserved verbatim — a partial (``--rule``) regeneration passes
        the non-selected rules' entries through so their justifications
        are never dropped by a run that didn't re-derive them."""
        inherit = {}
        for e in (old.entries if old else []):
            inherit[(e["rule"], e["file"], e["line_text"])] = \
                e["justification"]
        entries = []
        seen = set()
        for e in carry:
            key = (e["rule"], e["file"], e["line_text"])
            if key not in seen:
                seen.add(key)
                entries.append(dict(e))
        for v in sorted(violations, key=lambda v: (v.path, v.line, v.rule)):
            key = (v.rule, v.path, v.line_text)
            if key in seen:
                continue
            seen.add(key)
            entries.append({
                "rule": v.rule, "file": v.path, "line_text": v.line_text,
                "justification": inherit.get(key, PLACEHOLDER)})
        entries.sort(key=lambda e: (e["file"], e["rule"], e["line_text"]))
        return json.dumps({"version": 1, "entries": entries}, indent=1) + "\n"
