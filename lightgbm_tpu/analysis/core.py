"""graftlint core: parsed-file model, rule registry, pragmas.

Stdlib-``ast`` only. Every source file is parsed once into a
``ParsedFile`` that annotates each node with (a) its parent chain,
(b) the enclosing function, and (c) the set of context-manager *guard
names* lexically wrapping it (``with collective_guard(...):`` marks
every node in its body with ``"collective_guard"``) — the three facts
most rules are made of. Rules are small classes in
``lightgbm_tpu/analysis/rules/`` registered via ``@register``; each
ships its own known-bad/known-good fixture corpus (``Fixture``) that
``--self-check`` and tests/test_graftlint.py replay against the engine.
"""

import ast
import os
import re
from dataclasses import dataclass, field


class Severity:
    ERROR = "error"
    WARNING = "warning"


@dataclass
class Violation:
    rule: str
    path: str           # repo-relative, forward slashes
    line: int
    message: str
    severity: str = Severity.ERROR
    symbol: str = ""    # enclosing function qualname, when known
    line_text: str = ""  # stripped source of the flagged line
    suppressed_by: str = ""  # "", "pragma", or "baseline"

    def format(self):
        return f"{self.path}:{self.line} {self.rule} {self.message}"

    def as_dict(self):
        return {"rule": self.rule, "file": self.path, "line": self.line,
                "severity": self.severity, "message": self.message,
                "symbol": self.symbol, "line_text": self.line_text,
                "suppressed_by": self.suppressed_by}


@dataclass
class Fixture:
    """One self-check case: a mini project tree and the number of
    violations the owning rule must raise on it (0 for known-good)."""
    name: str
    files: dict          # relpath -> source text
    expect: int          # exact violation count for the owning rule


# ------------------------------------------------------------- pragmas

_PRAGMA_RE = re.compile(r"#\s*graftlint:\s*disable=([a-z0-9_,\- ]+)")


def parse_pragmas(source):
    """{lineno: set(rule names)} for every ``# graftlint: disable=...``
    comment. A pragma suppresses matching violations on its OWN line
    and on the LINE BELOW it (so it can sit above a long statement)."""
    pragmas = {}
    for lineno, text in enumerate(source.splitlines(), 1):
        m = _PRAGMA_RE.search(text)
        if m:
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            pragmas[lineno] = rules
    return pragmas


# -------------------------------------------------------- parsed files

def dotted_name(node):
    """Best-effort dotted name of an expression: ``jax.pure_callback``,
    ``heartbeat.collective_guard``, ``name``; '' when not a name
    chain. Call nodes resolve through their func (``super().train()``
    -> ``super.train``)."""
    parts = []
    while True:
        if isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        elif isinstance(node, ast.Name):
            parts.append(node.id)
            break
        elif isinstance(node, ast.Call):
            node = node.func
        else:
            break
    return ".".join(reversed(parts))


def call_name(call):
    """Dotted name of a Call node's callee ('' when not a name)."""
    return dotted_name(call.func)


def node_source(pf, node):
    """Source text of a node, sliced straight off the parsed file's
    line table (ast.get_source_segment re-splits the whole file per
    call — 17s over this tree)."""
    try:
        l0, c0 = node.lineno - 1, node.col_offset
        l1, c1 = node.end_lineno - 1, node.end_col_offset
    except AttributeError:
        return ""
    lines = pf.lines
    if not (0 <= l0 <= l1 < len(lines)):
        return ""
    if l0 == l1:
        return lines[l0][c0:c1]
    parts = [lines[l0][c0:]]
    parts.extend(lines[l0 + 1:l1])
    parts.append(lines[l1][:c1])
    return "\n".join(parts)


# Guard context-manager names rules care about. A ``with`` whose item is
# a call (or attribute) whose dotted name ENDS with one of these marks
# its body as guarded by that name.
GUARD_NAMES = ("collective_guard", "meshed_trace_guard",
               "callbacks_disabled", "armed")


class ParsedFile:
    """One parsed source file with node annotations.

    Node attributes set by the annotation pass:
      ``_g_parent``  parent AST node
      ``_g_func``    nearest enclosing FunctionDef/AsyncFunctionDef
      ``_g_guards``  frozenset of guard names lexically wrapping the node
    """

    def __init__(self, root, rel):
        self.rel = rel.replace(os.sep, "/")
        self.path = os.path.join(root, rel)
        with open(self.path, "r", encoding="utf-8", errors="replace") as f:
            self.source = f.read()
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=self.rel)
        self.pragmas = parse_pragmas(self.source)
        self._annotate()

    def _annotate(self):
        def withs_guards(node):
            names = set()
            for item in node.items:
                nm = dotted_name(item.context_expr)
                for g in GUARD_NAMES:
                    if nm == g or nm.endswith("." + g):
                        names.add(g)
            return names

        def walk(node, func, guards):
            for child in ast.iter_child_nodes(node):
                child._g_parent = node
                child._g_func = func
                child._g_guards = guards
                nf = child if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)) else func
                ng = guards
                if isinstance(child, ast.With):
                    extra = withs_guards(child)
                    if extra:
                        ng = guards | extra
                walk(child, nf, ng)

        self.tree._g_parent = None
        self.tree._g_func = None
        self.tree._g_guards = frozenset()
        walk(self.tree, None, frozenset())

    # ------------------------------------------------------- accessors

    def calls(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                yield node

    def functions(self):
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node

    def classes(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ClassDef):
                yield node

    def enclosing_class(self, node):
        cur = getattr(node, "_g_parent", None)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur
            cur = getattr(cur, "_g_parent", None)
        return None

    def qualname(self, node):
        """Dotted Class.func qualname of a function node."""
        parts = []
        cur = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                parts.append(cur.name)
            cur = getattr(cur, "_g_parent", None)
        return ".".join(reversed(parts))

    def line_text(self, lineno):
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def suppressed(self, lineno, rule):
        """Inline-pragma check: same line or the line above."""
        for ln in (lineno, lineno - 1):
            rules = self.pragmas.get(ln)
            if rules and (rule in rules or "all" in rules):
                return True
        return False


# ------------------------------------------------------------- project

DEFAULT_SCOPE = ("lightgbm_tpu", "tools", "tests")
DEFAULT_FILES = ("bench.py",)


class Project:
    """The file set one lint run covers: every ``*.py`` under
    lightgbm_tpu/, tools/ and tests/ plus bench.py, rooted at the repo
    checkout (or a fixture temp dir)."""

    def __init__(self, root, scope_dirs=DEFAULT_SCOPE,
                 scope_files=DEFAULT_FILES):
        self.root = os.path.abspath(os.fspath(root))
        self.files = []
        self.errors = []    # (rel, message) for unparseable files
        rels = []
        for d in scope_dirs:
            base = os.path.join(self.root, d)
            for dirpath, dirnames, filenames in os.walk(base):
                dirnames[:] = sorted(
                    n for n in dirnames
                    if n != "__pycache__" and not n.startswith("."))
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        rels.append(os.path.relpath(
                            os.path.join(dirpath, fn), self.root))
        for fn in scope_files:
            if os.path.exists(os.path.join(self.root, fn)):
                rels.append(fn)
        for rel in rels:
            try:
                self.files.append(ParsedFile(self.root, rel))
            except (SyntaxError, ValueError) as e:
                self.errors.append((rel.replace(os.sep, "/"), str(e)))
        self._by_rel = {pf.rel: pf for pf in self.files}

    def get(self, rel):
        return self._by_rel.get(rel)

    def in_package(self):
        return [pf for pf in self.files
                if pf.rel.startswith("lightgbm_tpu/")]


# ------------------------------------------------------- rule registry

REGISTRY = {}


class Rule:
    """Base rule. Subclasses set ``name``/``doc``/``severity`` and
    implement ``check(project) -> [Violation]`` (whole-project; rules
    that are per-file just loop). ``fixtures()`` returns the self-check
    corpus."""

    name = ""
    doc = ""
    severity = Severity.ERROR

    def check(self, project):
        raise NotImplementedError

    def fixtures(self):
        return []

    # helper for subclasses
    def violation(self, pf, node, message, severity=None):
        lineno = getattr(node, "lineno", 1)
        func = getattr(node, "_g_func", None)
        return Violation(
            rule=self.name, path=pf.rel, line=lineno, message=message,
            severity=severity or self.severity,
            symbol=pf.qualname(func) if func is not None else "",
            line_text=pf.line_text(lineno))


def register(cls):
    inst = cls()
    if not inst.name:
        raise ValueError(f"rule {cls.__name__} has no name")
    if inst.name in REGISTRY:
        raise ValueError(f"duplicate rule name {inst.name}")
    REGISTRY[inst.name] = inst
    return cls
