"""Package-wide call graph with guard-aware reachability.

Built once per lint run and shared by the rules that need
interprocedural facts (callback-in-mesh). Resolution is *name-based*:
a call site ``foo(...)`` / ``x.foo(...)`` links to every function
DEFINED as ``foo`` anywhere in the project. That over-approximates
(aliasing, shadowing) — which is the right bias for a linter guarding
against deadlocks: a false edge can only make the rule more demanding,
and the pragma/baseline machinery absorbs reviewed false positives.

Guard-awareness: a call edge whose call site is lexically inside a
``with callbacks_disabled():`` / ``with meshed_trace_guard():`` block
is a *guarded* edge — the trace-time guard makes ops/histogram.py's
``chunk_mode()`` resolve "bincount" to the pure-XLA segment kernel, so
host callbacks are unreachable through it (ops/histogram.py:154).
Reachability of ``jax.pure_callback`` is computed over UNGUARDED edges
only.
"""

import ast

from .core import call_name

# the trace-time guards that cut callback reachability (the watchdog's
# collective_guard does NOT — it arms a timer, it doesn't change which
# kernel is traced)
CB_GUARDS = frozenset({"callbacks_disabled", "meshed_trace_guard"})

# direct host-callback entry points (seeds)
CALLBACK_CALLS = ("pure_callback", "io_callback")


class FunctionInfo:
    __slots__ = ("pf", "node", "name", "qual", "cls",
                 "calls", "direct_callback")

    def __init__(self, pf, node):
        self.pf = pf
        self.node = node
        self.name = node.name
        self.qual = f"{pf.rel}:{pf.qualname(node)}"
        cls = pf.enclosing_class(node)
        self.cls = cls.name if cls is not None else None
        # [(dotted_name, cb_guarded, call_node)]
        self.calls = []
        self.direct_callback = False


class CallGraph:
    def __init__(self, project):
        self.project = project
        self.functions = []       # every FunctionInfo
        self.by_name = {}         # simple def name -> [FunctionInfo]
        self.by_node = {}         # id(ast node) -> FunctionInfo
        self._build()
        self._reaches_cb = None

    def _build(self):
        for pf in self.project.files:
            for node in pf.functions():
                fi = FunctionInfo(pf, node)
                self.functions.append(fi)
                self.by_name.setdefault(fi.name, []).append(fi)
                self.by_node[id(node)] = fi
        for fi in self.functions:
            base_guards = getattr(fi.node, "_g_guards", frozenset())
            for sub in ast.walk(fi.node):
                if not isinstance(sub, ast.Call):
                    continue
                # attribute calls to the *nearest* enclosing function:
                # nested defs own their call sites
                owner = self._owning_function(sub)
                if owner is not fi.node:
                    continue
                name = call_name(sub)
                if not name:
                    continue
                last = name.rsplit(".", 1)[-1]
                guards = getattr(sub, "_g_guards", frozenset())
                # guards inherited from OUTSIDE the function don't
                # guard the trace happening inside it at call time
                local_guards = guards - base_guards
                cb_guarded = bool(local_guards & CB_GUARDS)
                fi.calls.append((name, cb_guarded, sub))
                if last in CALLBACK_CALLS:
                    fi.direct_callback = True

    def _owning_function(self, node):
        fn = getattr(node, "_g_func", None)
        return fn

    # ------------------------------------------------------ reachability

    def reaches_callback(self):
        """{FunctionInfo} from which a host callback is reachable over
        unguarded call edges (fixpoint over the name-resolved graph)."""
        if self._reaches_cb is not None:
            return self._reaches_cb
        reaches = {fi for fi in self.functions if fi.direct_callback}
        changed = True
        while changed:
            changed = False
            for fi in self.functions:
                if fi in reaches:
                    continue
                for name, cb_guarded, _ in fi.calls:
                    if cb_guarded:
                        continue
                    last = name.rsplit(".", 1)[-1]
                    for cand in self.by_name.get(last, ()):
                        if cand in reaches:
                            reaches.add(fi)
                            changed = True
                            break
                    if fi in reaches:
                        break
        self._reaches_cb = reaches
        return reaches

    # ------------------------------------------------------- callers

    def callers_of(self, name):
        """[(caller FunctionInfo, cb_guarded, call node)] for call sites
        whose last name segment is ``name``."""
        out = []
        for fi in self.functions:
            for cname, cb_guarded, node in fi.calls:
                if cname.rsplit(".", 1)[-1] == name:
                    out.append((fi, cb_guarded, node))
        return out

    # -------------------------------------------------- class hierarchy

    def hierarchy_of(self, cls_name):
        """Names of every class connected to ``cls_name`` through
        base-class links (either direction), name-resolved across the
        project. The meshed-learner family guards its builder dispatch
        in ONE base-class override; the whole family inherits it."""
        edges = {}
        for pf in self.project.files:
            for cls in pf.classes():
                bases = set()
                for b in cls.bases:
                    if isinstance(b, ast.Name):
                        bases.add(b.id)
                    elif isinstance(b, ast.Attribute):
                        bases.add(b.attr)
                edges.setdefault(cls.name, set()).update(bases)
                for b in bases:
                    edges.setdefault(b, set()).add(cls.name)
        seen = set()
        frontier = [cls_name]
        while frontier:
            cur = frontier.pop()
            if cur in seen:
                continue
            seen.add(cur)
            frontier.extend(edges.get(cur, ()))
        return seen

    def methods_of(self, cls_names):
        return [fi for fi in self.functions if fi.cls in cls_names]
