"""graftlint CLI.

    python -m lightgbm_tpu.analysis [ROOT] [options]
    python tools/graftlint.py [ROOT] [options]      # jax-free shim

Options:
    --json [PATH]       machine-readable report (stdout when PATH is -)
    --rule NAME         run only this rule (repeatable)
    --list-rules        print the rule catalogue and exit
    --no-baseline       ignore tools/lint_baseline.json
    --update-baseline   rewrite the baseline from the current tree
                        (preserves surviving justifications; new
                        entries get a FIXME placeholder the loader
                        rejects until a human justifies them)
    --self-check        replay every rule's known-bad/known-good
                        fixture corpus against the engine and exit
                        (the `tools/sentinel.py --self-check` shape;
                        `make verify-lint` runs it before the tree)
    --strict            warnings fail too

Exit codes: 0 clean (errors all suppressed), 1 violations (or fixture
failures under --self-check), 2 usage / malformed baseline.
"""

import argparse
import json
import os
import shutil
import sys
import tempfile

from .baseline import Baseline, BaselineError
from .core import Project, Severity
from .engine import lint_project, load_rules


def repo_root():
    """The checkout containing this package (two levels up)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def self_check(out=sys.stdout):
    """Replay the fixture corpus: every rule must flag its known-bad
    snippets (exact count) and stay silent on its known-good ones —
    through the full engine, so pragma handling is exercised too.
    Returns 0/1."""
    registry = load_rules()
    failures = []
    total = 0
    for name in sorted(registry):
        rule = registry[name]
        for fx in rule.fixtures():
            total += 1
            tmp = tempfile.mkdtemp(prefix="graftlint_fx_")
            try:
                for rel, text in fx.files.items():
                    path = os.path.join(tmp, rel)
                    os.makedirs(os.path.dirname(path), exist_ok=True)
                    with open(path, "w", encoding="utf-8") as f:
                        f.write(text)
                result = lint_project(tmp, rule_names=[name],
                                      use_baseline=False)
                got = len([v for v in result.violations if v.rule == name])
                if got != fx.expect:
                    failures.append(
                        f"{name}/{fx.name}: expected {fx.expect} "
                        f"violation(s), got {got}: "
                        + "; ".join(v.format() for v in result.violations))
            finally:
                shutil.rmtree(tmp, ignore_errors=True)
    if failures:
        print(f"graftlint self-check: FAIL "
              f"({len(failures)}/{total} fixtures)", file=out)
        for f in failures:
            print("  " + f, file=out)
        return 1
    print(f"graftlint self-check: OK ({total} fixtures, "
          f"{len(registry)} rules)", file=out)
    return 0


def list_rules(out=sys.stdout):
    registry = load_rules()
    for name in sorted(registry):
        r = registry[name]
        print(f"{name:26s} [{r.severity}] {r.doc}", file=out)
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="graftlint",
        description="AST-based invariant linter for the lightgbm_tpu "
                    "codebase (docs/Static-Analysis.md)")
    ap.add_argument("root", nargs="?", default=None,
                    help="repo root to lint (default: this checkout)")
    ap.add_argument("--json", nargs="?", const="-", default=None,
                    metavar="PATH", help="write the JSON report")
    ap.add_argument("--rule", action="append", default=None,
                    help="run only this rule (repeatable)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--no-baseline", action="store_true")
    ap.add_argument("--update-baseline", action="store_true")
    ap.add_argument("--self-check", action="store_true")
    ap.add_argument("--strict", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        return list_rules()
    if args.self_check:
        return self_check()

    root = os.path.abspath(args.root or repo_root())
    try:
        # the update path must be able to rewrite a ROTTEN baseline,
        # so it lints baseline-free and loads the old file leniently
        result = lint_project(
            root, rule_names=args.rule,
            use_baseline=not (args.no_baseline or args.update_baseline))
    except BaselineError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2
    except ValueError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2

    if args.update_baseline:
        # lenient load: keep whatever well-formed, justified entries
        # the old file has so their justifications survive the rewrite
        old = Baseline.load(root, strict=False)
        # regenerate from EVERYTHING not pragma-suppressed
        keep = result.violations + [v for v in result.suppressed
                                    if v.suppressed_by == "baseline"]
        carried = []
        if args.rule:
            # a partial run only re-derives the selected rules'
            # entries — rules that didn't run keep theirs verbatim
            # (and their justifications)
            carried = [e for e in old.entries
                       if e["rule"] not in set(args.rule)]
        text = Baseline.render(keep, old, carry=carried)
        path = os.path.join(root, "tools", "lint_baseline.json")
        with open(path, "w", encoding="utf-8") as f:
            f.write(text)
        n = len(json.loads(text)["entries"])
        print(f"graftlint: baseline rewritten: {path} "
              f"({n} entr{'y' if n == 1 else 'ies'}; "
              f"fill in any FIXME justifications)")
        return 0

    if args.json is not None:
        payload = json.dumps(result.as_dict(), indent=1)
        if args.json == "-":
            print(payload)
        else:
            with open(args.json, "w", encoding="utf-8") as f:
                f.write(payload + "\n")

    for v in result.violations:
        print(v.format())
    for rel, msg in result.parse_errors:
        print(f"{rel}:0 parse-error {msg}")
    for e in result.baseline_unused:
        print(f"tools/lint_baseline.json: unused entry "
              f"({e['rule']} {e['file']}: {e['line_text'][:60]!r}) — "
              f"the violation is gone, drop the entry")
    n_err = len(result.errors)
    n_warn = len(result.warnings)
    print(f"graftlint: {result.files} files, "
          f"{n_err} error(s), {n_warn} warning(s), "
          f"{len(result.suppressed)} suppressed "
          f"(baseline+pragma), {result.elapsed_s:.2f}s")
    failed = bool(n_err or result.parse_errors
                  or (args.strict and (n_warn or result.baseline_unused)))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
