"""``python -m lightgbm_tpu.analysis`` — see cli.py / tools/graftlint.py."""

import sys

from .cli import main

sys.exit(main())
