"""Elastic-restart supervisor: `python -m lightgbm_tpu.supervisor ...`.

No reference equivalent — the reference's recovery story for a dead
worker is "rerun the whole job by hand". Here worker loss is routine
(TPU preemptions), so every machine in a distributed job runs ONE
supervisor that launches the local training process
(`python -m lightgbm_tpu`, same arguments) and babysits it:

- exit 0: done.
- any failure — an injected/real crash, a collective-watchdog abort
  (exit 117), a peer-loss abort (exit 118), a signal — is restartable:
  the supervisor relaunches the child, which auto-resumes from the
  newest valid shared snapshot (`snapshot_freq`/`snapshot_resume`,
  PR 2's checkpoint machinery), up to `max_restarts` times.
- before each relaunch the supervisors meet at a file-based RESTART
  BARRIER in the shared snapshot directory: each posts a marker for
  attempt k and waits for its peers' markers. Ranks that never post
  (machine gone for good) are dropped — the survivors rewrite the
  machine list (shrunken world, ports shifted by the attempt number so
  lingering sockets can't collide), renumber their ranks, and relaunch
  with `num_machines=<survivors>`; the per-rank row partition
  (`partition_rows`) and the snapshot's GLOBAL train score
  (models/gbdt.py capture) re-slice to the new topology automatically.

The training child is told its rank via LIGHTGBM_TPU_RANK and the
attempt via LIGHTGBM_TPU_RESTART_ATTEMPT (which also disarms one-shot
rank fault injections, utils/faults.py — an injected preemption models
one failure event, not a permanently broken rank).

Single-machine jobs work too: the supervisor is then a plain
crash-restart wrapper around the CLI with no barrier to wait on.
"""

import os
import subprocess
import sys
import time

from .config import load_config_file, str2map
from .parallel import heartbeat
from .parallel.machines import (find_local_rank, format_machine_list,
                                parse_machine_list)
from .utils.faults import HARD_CRASH_EXIT_CODE
from .utils.log import Log

SUPERVISOR_SUBDIR = "supervisor"
_BARRIER_POLL_S = 0.25


def _load_parameters(argv):
    """Command line `k=v` tokens override config-file entries — the
    CLI's own layering (application.py), duplicated here so the
    supervisor never imports the jax-heavy application module."""
    cmd_params = str2map(" ".join(argv))
    params = {}
    config_path = cmd_params.get("config_file", "")
    if config_path:
        params.update(load_config_file(config_path))
    params.update(cmd_params)
    params.pop("config_file", None)
    return params


def describe_exit(code):
    """Human-readable child exit diagnosis for the restart log."""
    if code == heartbeat.EXIT_WATCHDOG:
        return "collective watchdog abort (a peer hung mid-collective)"
    if code == heartbeat.EXIT_PEER_LOST:
        return "peer-loss abort (a rank's heartbeat went stale)"
    if code == HARD_CRASH_EXIT_CODE:
        return "hard crash (injected preemption)"
    if code < 0:
        return f"killed by signal {-code}"
    return "training failure"


def _barrier_dir(shared_dir):
    return os.path.join(shared_dir, SUPERVISOR_SUBDIR)


def _marker_path(shared_dir, attempt, rank):
    return os.path.join(_barrier_dir(shared_dir),
                        f"restart.attempt{attempt:04d}.rank{rank:04d}.json")


def _post_marker(shared_dir, attempt, rank, exit_code):
    path = _marker_path(shared_dir, attempt, rank)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    heartbeat.atomic_write_json(
        path, {"rank": rank, "attempt": attempt, "time": time.time(),
               "exit_code": exit_code})


def restart_barrier(shared_dir, attempt, my_rank, member_ranks, wait_s,
                    exit_code=0):
    """Post this rank's restart marker for `attempt` and wait up to
    `wait_s` for the other members'. Returns the sorted survivor ranks
    (always including my_rank): members whose marker never appears are
    gone — their machine will be dropped from the relaunch topology."""
    _post_marker(shared_dir, attempt, my_rank, exit_code)
    members = set(member_ranks)
    deadline = time.monotonic() + wait_s
    while True:
        present = {r for r in members
                   if os.path.exists(_marker_path(shared_dir, attempt, r))}
        if present == members or time.monotonic() >= deadline:
            break
        time.sleep(_BARRIER_POLL_S)
    survivors = sorted(present | {my_rank})
    missing = sorted(members - set(survivors))
    if missing:
        Log.warning("restart barrier (attempt %d): rank(s) %s did not "
                    "report within %.1fs — shrinking the world to %d "
                    "rank(s)", attempt, missing, wait_s, len(survivors))
    return survivors


def returned_ranks(shared_dir, attempt, original_members, current_members):
    """Grow-back scan: original ranks that were pruned in an earlier
    shrink but whose supervisor posted a marker for THIS attempt — the
    machine came back (operator restarted its supervisor) and is
    waiting at the same barrier. Survivor re-admission costs nothing
    extra: the barrier directory is already shared state, and ownership
    (rows, feature shards, out-of-core block ranges) re-derives from
    whatever world count the relaunch passes down."""
    current = set(current_members)
    returned = []
    for r in original_members:
        if r in current:
            continue
        if os.path.exists(_marker_path(shared_dir, attempt, r)):
            returned.append(int(r))
    return sorted(returned)


def _shift_ports(machines, attempt):
    """Fresh ports per attempt: the previous incarnation's coordinator
    socket may linger in TIME_WAIT on the same host."""
    return [(host, port + attempt) for host, port in machines]


class Supervisor:
    """One machine's restart loop (see module docstring)."""

    def __init__(self, argv):
        self.argv = list(argv)
        params = _load_parameters(argv)
        self.restart_on_failure = str(
            params.get("restart_on_failure", "true")).lower() not in (
                "false", "-", "0")
        self.max_restarts = int(params.get("max_restarts", 2))
        # a supervised job without an explicit detection knob would
        # have failure detection OFF in the child (config default 0)
        # and hang forever in a collective — defeating the supervisor.
        # Default the child's heartbeat timeout to the same 60s this
        # supervisor's barrier math assumes.
        self.inject_heartbeat_knob = "heartbeat_timeout_s" not in params
        self.heartbeat_timeout_s = float(params.get("heartbeat_timeout_s", 60))
        collective = float(params.get("collective_timeout_s", 0))
        # peers enter the barrier only after their own detection fires:
        # allow one full detection window plus generous slack
        self.barrier_wait_s = 2.0 * max(self.heartbeat_timeout_s,
                                        collective, 5.0)
        self.snapshot_freq = int(params.get("snapshot_freq", 0))
        self.shared_dir = (params.get("snapshot_dir")
                           or params.get("output_model",
                                         "LightGBM_model.txt")
                           + ".snapshots")
        # restart/shrink events land in the SAME rank journal file the
        # training child writes (telemetry/journal.py: O_APPEND single-
        # line writes interleave safely across processes), so the
        # merged timeline shows abort -> restart -> resume in order
        self.telemetry = str(params.get("telemetry", "false")).lower() \
            in ("true", "+", "1")
        self.telemetry_dir = params.get("telemetry_dir") or self.shared_dir
        self._journal = None
        mlist = params.get("machine_list_file", "")
        self.machines = parse_machine_list(mlist) if mlist and \
            os.path.exists(mlist) else []
        self.num_machines = int(params.get("num_machines",
                                           len(self.machines) or 1))
        self.machines = self.machines[:self.num_machines]
        env_rank = os.environ.get("LIGHTGBM_TPU_RANK")
        if env_rank is not None:
            self.rank = int(env_rank)
        elif len(self.machines) > 1:
            self.rank = find_local_rank(self.machines)
        else:
            self.rank = 0
        # identity is the ORIGINAL rank; membership shrinks across
        # restarts but original ids keep the barrier unambiguous.
        # original_members stays fixed so a pruned rank whose machine
        # comes back can be re-admitted at a later barrier (grow-back)
        self.members = list(range(max(len(self.machines), 1)))
        self.original_members = list(self.members)
        # a reused snapshot dir may hold THIS rank's restart markers
        # from a previous incarnation; left in place they would count a
        # later-dead rank as a barrier survivor and block the shrunken-
        # world path. Each supervisor cleans only its OWN rank's
        # markers (no cross-host races), so a rank whose machine dies
        # mid-run leaves nothing stale behind.
        self._clean_own_markers()
        if self.restart_on_failure and self.snapshot_freq <= 0:
            Log.warning("supervisor: snapshot_freq is 0 — a restart "
                        "will COLD-START training (set snapshot_freq>0 "
                        "to resume from shared snapshots)")

    def _journal_event(self, event, **fields):
        """Append one supervisor-sourced record to this rank's run
        journal (no-op unless `telemetry=true`)."""
        if not self.telemetry:
            return
        if self._journal is None:
            from .telemetry.journal import RunJournal
            self._journal = RunJournal(self.telemetry_dir, rank=self.rank,
                                       emit_run_start=False,
                                       source="supervisor")
        self._journal.event(event, **fields)

    def _clean_own_markers(self):
        import glob
        pattern = os.path.join(
            _barrier_dir(self.shared_dir),
            f"restart.attempt*.rank{self.rank:04d}.json")
        for stale in glob.glob(pattern):
            try:
                os.unlink(stale)
            except OSError:
                pass

    def _child_command(self, machines, mlist_override):
        cmd = [sys.executable, "-m", "lightgbm_tpu"] + self.argv
        # trailing k=v tokens override earlier ones (str2map)
        if self.inject_heartbeat_knob and len(self.machines) > 1:
            cmd.append(f"heartbeat_timeout_s={self.heartbeat_timeout_s:g}")
        if mlist_override is not None:
            cmd += [f"machine_list_file={mlist_override}",
                    f"num_machines={len(machines)}"]
        return cmd

    def _child_env(self, attempt, new_rank):
        env = dict(os.environ)
        env["LIGHTGBM_TPU_RANK"] = str(new_rank)
        env["LIGHTGBM_TPU_RESTART_ATTEMPT"] = str(attempt)
        return env

    def _write_shrunk_mlist(self, machines, attempt):
        """Every surviving supervisor derives the SAME list (survivor
        set + attempt are shared state), so concurrent writes of the
        identical bytes are benign."""
        path = os.path.join(_barrier_dir(self.shared_dir),
                            f"mlist.attempt{attempt:04d}.txt")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(format_machine_list(machines))
        os.replace(tmp, path)
        return path

    def run(self):
        attempt = 0
        machines = list(self.machines)
        new_rank = self.rank
        mlist_override = None
        while True:
            cmd = self._child_command(machines, mlist_override)
            Log.info("supervisor: launching rank %d (attempt %d/%d): %s",
                     new_rank, attempt, self.max_restarts, " ".join(cmd))
            child = subprocess.Popen(cmd,
                                     env=self._child_env(attempt, new_rank))
            code = child.wait()
            if code == 0:
                Log.info("supervisor: rank %d finished cleanly", new_rank)
                return 0
            Log.warning("supervisor: rank %d exited with code %d — %s",
                        new_rank, code, describe_exit(code))
            if not self.restart_on_failure or attempt >= self.max_restarts:
                Log.warning("supervisor: not restarting (%s)",
                            "restart_on_failure=false"
                            if not self.restart_on_failure
                            else f"max_restarts={self.max_restarts} "
                                 f"exhausted")
                return code
            attempt += 1
            prev_world = len(self.members)
            if len(self.original_members) > 1:
                survivors = restart_barrier(
                    self.shared_dir, attempt, self.rank, self.members,
                    self.barrier_wait_s, exit_code=code)
                # grow-back: a previously pruned rank whose supervisor
                # posted a marker for THIS attempt rejoins — ownership
                # widens back at relaunch exactly the way it shrank
                returned = returned_ranks(self.shared_dir, attempt,
                                          self.original_members, survivors)
                if returned:
                    Log.info("restart barrier (attempt %d): rank(s) %s "
                             "returned — growing the world back to %d "
                             "rank(s)", attempt, returned,
                             len(survivors) + len(returned))
                members = sorted(set(survivors) | set(returned))
                if members != self.members:
                    self.members = members
                machines = _shift_ports(
                    [self.machines[r] for r in self.members], attempt)
                new_rank = self.members.index(self.rank)
                mlist_override = self._write_shrunk_mlist(machines, attempt)
            shrunk = len(self.members) < prev_world
            grown = len(self.members) > prev_world
            self._journal_event("restart", attempt=attempt,
                                exit_code=int(code),
                                reason=describe_exit(code),
                                survivors=list(self.members),
                                new_rank=int(new_rank),
                                mesh_reshard=bool(shrunk or grown))
            Log.info("supervisor: restarting rank %d as rank %d of %d "
                     "(%sresume from newest snapshot under %s)", self.rank,
                     new_rank, max(len(machines), 1),
                     "mesh re-shards feature ownership; " if shrunk
                     else "", self.shared_dir)


def main(argv=None):
    if argv is None:
        argv = sys.argv[1:]
    if not argv:
        print("usage: python -m lightgbm_tpu.supervisor <lightgbm "
              "params: task=train data=... machine_list_file=... "
              "num_machines=N snapshot_freq=K ...>", file=sys.stderr)
        return 2
    return Supervisor(argv).run()


if __name__ == "__main__":
    sys.exit(main())
