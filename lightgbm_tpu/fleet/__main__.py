"""`python -m lightgbm_tpu.fleet` — fleet CLI (docs/Fleet.md).

Registry administration (jax-free, instant):

    python -m lightgbm_tpu.fleet list     --registry DIR
    python -m lightgbm_tpu.fleet publish  --registry DIR model.txt [--promote]
    python -m lightgbm_tpu.fleet promote  --registry DIR --version N [--force]
    python -m lightgbm_tpu.fleet rollback --registry DIR
    python -m lightgbm_tpu.fleet verify   --registry DIR [--version N]

The pipeline supervisor (drift -> retrain -> validate -> promote):

    python -m lightgbm_tpu.fleet watch --registry DIR \
        --serving-url http://127.0.0.1:8099 \
        --fresh fresh.csv --holdout holdout.csv \
        --param objective=binary --param num_leaves=31 \
        [--interval 30] [--once] [--journal-dir DIR] [--min-improvement X]

`watch` polls the serving fleet's /driftz; on a psi_warn excursion it
retrains on the fresh CSV (label in column 0), validates against the
incumbent on the holdout CSV, and promotes or quarantines through the
registry — a serving process started with `--registry DIR --follow`
hot-swaps to the promotion on its next poll.

The front-door router (fleet/router.py, docs/Resilience.md):

    python -m lightgbm_tpu.fleet route \
        --targets 127.0.0.1:8099,127.0.0.1:8100 [--port 8800] \
        [--breaker-failures N] [--retry-budget X] [--hedge-quantile Q]

One endpoint over N serving replicas: least-in-flight dispatch,
per-replica circuit breakers, strict-health ejection, budgeted
retries and optional hedging.
"""

import argparse
import http.client
import json
import sys
import time

import numpy as np

from ..utils.log import Log
from .registry import ModelRegistry, RegistryError


def _load_xy(path):
    """CSV/TSV rows, label in column 0 (the CLI data convention)."""
    first = open(path).readline()
    sep = "\t" if "\t" in first else ","
    data = np.genfromtxt(path, delimiter=sep, dtype=np.float64)
    data = np.atleast_2d(data)
    return data[:, 1:], data[:, 0]


def _coerce(value):
    for cast in (int, float):
        try:
            return cast(value)
        except ValueError:
            pass
    if value.lower() in ("true", "false"):
        return value.lower() == "true"
    return value


def _params(pairs):
    out = {}
    for pair in pairs or []:
        if "=" not in pair:
            raise SystemExit(f"--param wants key=value, got {pair!r}")
        k, v = pair.split("=", 1)
        out[k.strip()] = _coerce(v.strip())
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.fleet",
        description="Model registry + drift-triggered retraining "
                    "pipeline (docs/Fleet.md)")
    sub = ap.add_subparsers(dest="cmd", required=True)

    def common(p):
        p.add_argument("--registry", required=True,
                       help="registry directory")
        return p

    common(sub.add_parser("list", help="registry summary"))
    p = common(sub.add_parser("publish", help="publish a model file"))
    p.add_argument("model")
    p.add_argument("--profile", default=None,
                   help="profile sidecar (default: <model>.profile.json "
                        "when present)")
    p.add_argument("--promote", action="store_true",
                   help="promote the new version immediately")
    p = common(sub.add_parser("promote", help="move CURRENT"))
    p.add_argument("--version", type=int, required=True)
    p.add_argument("--force", action="store_true",
                   help="promote even a quarantined version")
    p = common(sub.add_parser("rollback",
                              help="restore the prior live version"))
    p = common(sub.add_parser("verify", help="re-checksum versions"))
    p.add_argument("--version", type=int, default=None)

    p = sub.add_parser(
        "route", help="front-door router over serving replicas "
                      "(fleet/router.py, docs/Resilience.md)")
    p.add_argument("--targets", required=True,
                   help="comma-separated replica host:port list")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8800)
    p.add_argument("--breaker-failures", type=int, default=5,
                   help="consecutive upstream failures that open a "
                        "replica's circuit breaker (mirrors the "
                        "breaker_failures config knob)")
    p.add_argument("--breaker-reset-s", type=float, default=1.0,
                   help="how long an open breaker waits before its "
                        "half-open probe")
    p.add_argument("--retry-budget", type=float, default=0.1,
                   help="retry tokens granted per client request; caps "
                        "error amplification at 1 + budget (mirrors "
                        "retry_budget)")
    p.add_argument("--hedge-quantile", type=float, default=0.0,
                   help="duplicate a request still unanswered after "
                        "this latency quantile (e.g. 0.99); 0 = off "
                        "(mirrors hedge_quantile)")
    p.add_argument("--upstream-timeout-s", type=float, default=10.0,
                   help="hard cap on any single upstream call")
    p.add_argument("--health-poll-s", type=float, default=0.5,
                   help="strict /healthz probe interval")
    p.add_argument("--trace-dir", default=None,
                   help="arm distributed tracing: journal tail-sampled "
                        "trace records here (telemetry/disttrace.py, "
                        "docs/Observability.md)")
    p.add_argument("--trace-rank", type=int, default=0,
                   help="journal rank suffix for this router's trace "
                        "records (keep distinct from the replicas "
                        "sharing --trace-dir)")
    p.add_argument("--trace-sample-rate", type=float, default=0.01,
                   help="deterministic hash(trace_id) fraction of "
                        "non-error, non-slow traces to keep (mirrors "
                        "the trace_sample_rate config knob)")
    p.add_argument("--trace-slow-only", action="store_true",
                   help="drop even hash-sampled healthy traces; keep "
                        "only error/slow ones (mirrors trace_slow_only)")
    p.add_argument("--trace-slow-ms", type=float, default=1000.0,
                   help="traces spanning longer than this are always "
                        "kept (mirrors slow_request_ms)")

    p = common(sub.add_parser(
        "watch", help="drift -> retrain -> validate -> promote loop"))
    p.add_argument("--serving-url", required=True,
                   help="base URL of the serving fleet (/driftz source)")
    p.add_argument("--fresh", required=True,
                   help="fresh training data CSV (label in column 0)")
    p.add_argument("--holdout", required=True,
                   help="validation holdout CSV (label in column 0)")
    p.add_argument("--param", action="append", default=[],
                   help="training param key=value (repeatable)")
    p.add_argument("--num-boost-round", type=int, default=None,
                   help="challenger boosting rounds (default: the "
                        "num_iterations training param, else 100)")
    p.add_argument("--interval", type=float, default=30.0,
                   help="seconds between /driftz polls")
    p.add_argument("--once", action="store_true",
                   help="one poll+action pass, then exit (CI rungs)")
    p.add_argument("--force", action="store_true",
                   help="skip the drift gate: retrain now")
    p.add_argument("--min-improvement", type=float, default=0.0,
                   help="challenger must beat the incumbent's metric "
                        "by at least this much to promote")
    p.add_argument("--psi-warn", type=float, default=None,
                   help="excursion threshold (default: mirror the "
                        "serving monitor's)")
    p.add_argument("--snapshot-dir", default=None,
                   help="checkpoint directory: an interrupted retrain "
                        "resumes from the newest snapshot")
    p.add_argument("--journal-dir", default=None,
                   help="PR-5 run journal directory for transition "
                        "records")
    args = ap.parse_args(argv)

    if args.cmd == "route":
        # registry-free: the router only needs replica addresses
        from .router import main as route_main
        return route_main(args)

    registry = ModelRegistry(args.registry)
    try:
        if args.cmd == "list":
            print(json.dumps(registry.describe(), indent=1, default=str))
        elif args.cmd == "publish":
            version = registry.publish(args.model,
                                       profile_path=args.profile)
            print(f"published v{version}")
            if args.promote:
                registry.promote(version, reason="cli publish --promote")
                print(f"promoted v{version}")
        elif args.cmd == "promote":
            pointer = registry.promote(args.version, reason="cli",
                                       force=args.force)
            print(f"promoted v{pointer['version']} "
                  f"(generation {pointer['generation']})")
        elif args.cmd == "rollback":
            pointer = registry.rollback(reason="cli")
            print(f"rolled back to v{pointer['version']} "
                  f"(generation {pointer['generation']})")
        elif args.cmd == "verify":
            versions = ([args.version] if args.version is not None
                        else registry.versions())
            for v in versions:
                registry.verify(v)
                print(f"v{v}: OK")
            if not versions:
                print("no published versions")
        elif args.cmd == "watch":
            return watch(args, registry)
    except RegistryError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    return 0


def watch(args, registry):
    from .pipeline import DEFAULT_PSI_WARN, FleetPipeline, fetch_driftz
    journal = None
    if args.journal_dir:
        from ..telemetry.journal import RunJournal
        journal = RunJournal(args.journal_dir, source="fleet",
                             meta={"source": "fleet"})
    fresh_x, fresh_y = _load_xy(args.fresh)
    hold_x, hold_y = _load_xy(args.holdout)
    pipeline = FleetPipeline(
        registry, _params(args.param),
        psi_warn=(args.psi_warn if args.psi_warn is not None
                  else DEFAULT_PSI_WARN),
        min_improvement=args.min_improvement,
        snapshot_dir=args.snapshot_dir, journal=journal)
    Log.info("fleet watch: %s every %.0fs (registry %s)",
             args.serving_url, args.interval, args.registry)
    try:
        while True:
            try:
                driftz = fetch_driftz(args.serving_url)
            except (OSError, ValueError,
                    http.client.HTTPException) as e:
                # unreachable, a non-JSON body (a proxy's HTML error
                # page) or a connection dropped mid-read
                # (IncompleteRead/BadStatusLine) — the always-on
                # supervisor must outlive a flaky serving endpoint
                Log.warning("fleet watch: /driftz unreadable: %s", e)
                driftz = None
            if driftz is not None or args.force:
                result = pipeline.run_once(
                    driftz, fresh_x, fresh_y, hold_x, hold_y,
                    num_boost_round=args.num_boost_round,
                    force=args.force)
                print("WATCH_RESULT " + json.dumps(result, default=str),
                      flush=True)
                if args.once:
                    return 0
                if args.force:
                    args.force = False   # forced retrain happens once
            elif args.once:
                print('WATCH_RESULT {"action": "noop", '
                      '"reason": "driftz unreachable"}', flush=True)
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    finally:
        if journal is not None:
            journal.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
