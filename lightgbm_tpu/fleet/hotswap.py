"""Hot-swap serving: load + AOT-warm a challenger behind the incumbent,
flip atomically under the batcher, follow a registry.

The flip discipline (docs/Fleet.md):

1. the challenger `CompiledPredictor` is built and `warm_up()`-ed
   BEFORE the incumbent sees any change — every row bucket AOT-compiles
   (riding the persistent compile cache, so a version the process has
   served before warms from disk in milliseconds) while the incumbent
   keeps serving;
2. the batcher's predictor reference swaps under the batcher lock —
   the worker snapshots the predictor once per coalesced batch, so a
   batch is scored ENTIRELY by one model version, never mixed;
3. the handler-facing surfaces (health/metricz stats, drift + skew
   monitors) follow after the flip: monitoring lag is cosmetic, score
   provenance is not.

`RegistryFollower` is the polling thread behind `python -m
lightgbm_tpu.serve MODEL --registry DIR --follow`: a promotion (or
rollback — it's just another pointer move) lands in the running fleet
without a restart, in-flight requests drain onto the new model, and
`cold_dispatches` stays 0 across the flip.
"""

import os
import threading
import time

from ..utils.log import Log
from .registry import RegistryError

DEFAULT_POLL_S = 2.0


class HotSwapper:
    """Loads registry versions into warmed CompiledPredictors and flips
    a live server to them. One per serving process."""

    def __init__(self, srv, registry, serving_precision=None,
                 max_batch_rows=None, num_iteration=None,
                 monitor_settings=None):
        self.srv = srv
        self.registry = registry
        incumbent = srv.predictor
        self.serving_precision = (serving_precision
                                  or getattr(incumbent,
                                             "serving_precision", "f32"))
        self.max_batch_rows = int(max_batch_rows
                                  or getattr(incumbent, "max_batch_rows",
                                             0) or 4096)
        # the server's --num-iteration knob must survive a swap: a
        # fleet serving truncated ensembles keeps serving truncated
        # ensembles across promotions
        self.num_iteration = int(
            num_iteration if num_iteration is not None
            else getattr(srv, "num_iteration", -1))
        # the drift/skew knobs the server was started with — a swapped
        # model gets monitors rebuilt against ITS baseline profile
        self.monitor_settings = dict(monitor_settings
                                     or getattr(srv, "monitor_settings",
                                                None) or {})
        self._lock = threading.Lock()
        self.stats = {"swap_count": 0, "last_swap_s": 0.0,
                      "last_warmup_s": 0.0, "failed_swaps": 0}

    def load_version(self, version):
        """Build + AOT-warm a CompiledPredictor for one registry
        version (manifest verified first). Pure load — the incumbent
        is untouched."""
        from ..serving.compiled_model import CompiledPredictor
        self.registry.verify(version)
        model_path = self.registry.model_path(version)
        return CompiledPredictor.from_model_file(
            model_path, num_iteration=self.num_iteration,
            max_batch_rows=self.max_batch_rows,
            serving_precision=self.serving_precision)

    def swap_to(self, version, reason=""):
        """Load, warm, and atomically flip the server to `version`.
        Returns the retired predictor. Raises RegistryError on a
        version that fails verification."""
        from ..serving.server import build_monitors, swap_model
        t0 = time.monotonic()
        with self._lock:   # one swap in flight at a time
            predictor = self.load_version(version)
            drift, skew = build_monitors(predictor,
                                         **self.monitor_settings)
            old = swap_model(self.srv, predictor, drift=drift, skew=skew,
                             version=int(version))
            self.stats["swap_count"] += 1
            self.stats["last_warmup_s"] = predictor.stats["warmup_s"]
            self.stats["last_swap_s"] = round(time.monotonic() - t0, 3)
        Log.structured(
            "Info", "hot_swap", version=int(version),
            reason=str(reason or ""),
            swap_s=self.stats["last_swap_s"],
            warmup_s=self.stats["last_warmup_s"],
            compile_cache_hits=predictor.stats["compile_cache_hits"])
        return old


class RegistryFollower:
    """Background thread that polls the registry CURRENT pointer and
    hot-swaps the server whenever the live version (or generation —
    a rollback re-promotes an older version) changes."""

    def __init__(self, swapper, poll_s=DEFAULT_POLL_S):
        self.swapper = swapper
        self.poll_s = float(poll_s)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="registry-follower",
                                        daemon=True)
        self._seen_generation = None
        # a permanently-broken promotion (parser-rejected model, local
        # bit rot) must not re-verify + re-warm every poll forever:
        # after MAX_ATTEMPTS failures on one generation the follower
        # parks until the pointer moves again
        self._failed_generation = None
        self._failed_attempts = 0

    MAX_ATTEMPTS = 5

    def start(self):
        # seed with the CURRENT generation so following a registry the
        # server was just started from does not immediately re-swap
        cur = self.swapper.registry.current()
        if cur is not None and self.swapper.srv.model_version == int(
                cur["version"]):
            self._seen_generation = int(cur.get("generation", 0))
        self._thread.start()
        return self

    def stop(self, timeout=5.0):
        self._stop.set()
        self._thread.join(timeout=timeout)

    def poll_once(self):
        """One poll step (the thread loop body; tests call it
        directly). Returns the version swapped to, or None."""
        cur = self.swapper.registry.current()
        if cur is None:
            return None
        generation = int(cur.get("generation", 0))
        if generation == self._seen_generation:
            return None
        if (generation == self._failed_generation
                and self._failed_attempts >= self.MAX_ATTEMPTS):
            return None   # parked until the pointer moves again
        version = int(cur["version"])
        try:
            self.swapper.swap_to(version,
                                 reason=cur.get("reason", "registry"))
        except Exception as e:
            # ANY load/verify failure (torn publish, CRC mismatch, a
            # model file the parser rejects) must not kill the
            # follower — it counts as a failed swap, the incumbent
            # keeps serving, and the next poll retries (bounded by
            # MAX_ATTEMPTS per generation)
            self.swapper.stats["failed_swaps"] += 1
            if generation != self._failed_generation:
                self._failed_generation, self._failed_attempts = \
                    generation, 0
            self._failed_attempts += 1
            Log.warning(
                "registry follower: swap to v%d failed (attempt %d/%d"
                "%s): %s", version, self._failed_attempts,
                self.MAX_ATTEMPTS,
                "; parked until the pointer moves"
                if self._failed_attempts >= self.MAX_ATTEMPTS else "",
                e)
            return None
        self._seen_generation = generation
        self._failed_generation, self._failed_attempts = None, 0
        return version

    def _run(self):
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as e:   # never die; the server outlives us
                Log.warning("registry follower poll failed: %s", e)
            self._stop.wait(self.poll_s)


def attach_follower(srv, registry_dir, poll_s=DEFAULT_POLL_S,
                    serving_precision=None):
    """Wire a HotSwapper + RegistryFollower onto a running server
    (the `--registry --follow` path). Returns the started follower."""
    from .registry import ModelRegistry
    registry = (registry_dir if hasattr(registry_dir, "current")
                else ModelRegistry(os.fspath(registry_dir)))
    swapper = HotSwapper(srv, registry,
                         serving_precision=serving_precision)
    follower = RegistryFollower(swapper, poll_s=poll_s).start()
    srv.follower = follower
    return follower
