"""Model registry: versioned, CRC-manifested on-disk model store.

Layout (one directory, nothing else writes into it):

    registry/
      CURRENT                    # atomic JSON pointer: live version,
                                 # generation counter, promote history
      versions/
        v00000001/
          model.txt              # the text model format
          model.txt.profile.json # dataset-profile sidecar (optional)
          metadata.json          # train config, eval metrics, lineage
          MANIFEST.json          # crc32 + byte count per file

Version directories are IMMUTABLE after publish: `publish` stages
everything in a sibling tmp directory (each file fsynced), writes the
CRC manifest last, then `os.rename`s the whole directory into place —
the same crash-atomicity discipline as the PR-7 block store, so a
kill at any instant leaves either no version or a complete, verified
one, never a torn one. Promotion only moves the CURRENT pointer
(atomic_write_text: tmp+fsync+rename), so `rollback` restores the
prior version BYTE-identically — the files never moved.

`quarantine` marks a rejected challenger without deleting it (the
evidence of a failed validation is operationally valuable); a
quarantined version cannot be promoted without `force=True`.

Every transition (promote / reject / rollback) is journaled through
the PR-5 run journal when one is attached — the fleet supervisor's
timeline shows model generations next to training progress, and the
Perfetto export renders them as instant markers.

jax-free: stdlib + the checkpoint module's atomic-write helpers only,
so the pipeline supervisor and tests import it without touching the
accelerator runtime.
"""

import json
import os
import shutil
import time

from ..data.mmap_io import crc32_file
from ..utils import faults
from ..utils.checkpoint import _fsync_dir, atomic_write_text
from ..utils.log import Log

REGISTRY_FORMAT_VERSION = 1
CURRENT_NAME = "CURRENT"
VERSIONS_DIR = "versions"
MANIFEST_NAME = "MANIFEST.json"
METADATA_NAME = "metadata.json"
MODEL_NAME = "model.txt"
QUARANTINE_NAME = "QUARANTINED"
# how many promote generations the CURRENT pointer remembers — the
# rollback depth (each entry is ~40 bytes; 50 is weeks of promotions)
HISTORY_DEPTH = 50


class RegistryError(Exception):
    """A registry operation failed validation (missing/corrupt version,
    illegal transition)."""


def _version_dirname(version):
    return f"v{int(version):08d}"


class ModelRegistry:
    """One registry directory (module docstring). Safe for concurrent
    READERS in other processes (a serving follower polling CURRENT
    while the pipeline promotes); writers are expected to be a single
    fleet supervisor — publishes allocate versions by directory scan,
    which two concurrent writers could race."""

    def __init__(self, directory, journal=None):
        self.directory = os.fspath(directory)
        self.versions_dir = os.path.join(self.directory, VERSIONS_DIR)
        self.journal = journal
        os.makedirs(self.versions_dir, exist_ok=True)

    # ------------------------------------------------------------ helpers
    def _journal(self, event, **fields):
        if self.journal is not None:
            self.journal.event(event, **fields)

    def version_dir(self, version):
        return os.path.join(self.versions_dir, _version_dirname(version))

    def model_path(self, version):
        return os.path.join(self.version_dir(version), MODEL_NAME)

    def profile_path(self, version):
        """The profile sidecar path, or None when the version was
        published without one."""
        p = os.path.join(self.version_dir(version),
                         MODEL_NAME + ".profile.json")
        return p if os.path.exists(p) else None

    def versions(self):
        """Sorted list of published version numbers (complete
        directories only — a crash-abandoned tmp stage is invisible)."""
        out = []
        try:
            names = os.listdir(self.versions_dir)
        except OSError:
            return out
        for name in names:
            if name.startswith("v") and name[1:].isdigit() \
                    and os.path.exists(os.path.join(
                        self.versions_dir, name, MANIFEST_NAME)):
                out.append(int(name[1:]))
        out.sort()
        return out

    def metadata(self, version):
        path = os.path.join(self.version_dir(version), METADATA_NAME)
        try:
            with open(path, "r", encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError) as e:
            raise RegistryError(f"unreadable metadata for v{version}: {e}")

    def is_quarantined(self, version):
        return os.path.exists(os.path.join(self.version_dir(version),
                                           QUARANTINE_NAME))

    # ------------------------------------------------------------ publish
    def publish(self, model_path, profile_path=None, metadata=None):
        """Stage model (+ optional profile sidecar) + metadata into the
        next version directory and land it atomically. Returns the new
        version number. The model file must exist; a missing profile
        next to it is allowed (drift monitoring is then off for this
        version). Publish does NOT promote — the new version is a
        candidate until `promote`."""
        model_path = os.fspath(model_path)
        if not os.path.exists(model_path):
            raise RegistryError(f"no model file at {model_path}")
        if profile_path is None:
            from ..io.profile import model_profile_path
            sidecar = model_profile_path(model_path)
            profile_path = sidecar if os.path.exists(sidecar) else None
        existing = self.versions()
        version = (existing[-1] + 1) if existing else 1
        final_dir = self.version_dir(version)
        tmp_dir = os.path.join(self.versions_dir,
                               f".tmp.{_version_dirname(version)}."
                               f"{os.getpid()}")
        try:
            os.makedirs(tmp_dir)
            files = {MODEL_NAME: model_path}
            if profile_path:
                files[MODEL_NAME + ".profile.json"] = os.fspath(
                    profile_path)
            manifest_files = {}
            for name, src in files.items():
                dst = os.path.join(tmp_dir, name)
                shutil.copyfile(src, dst)
                with open(dst, "rb") as f:
                    os.fsync(f.fileno())
                manifest_files[name] = {
                    "bytes": os.path.getsize(dst),
                    "crc32": int(crc32_file(dst)),
                }
            meta = dict(metadata or {})
            meta.setdefault("published_ts", time.time())
            meta_path = os.path.join(tmp_dir, METADATA_NAME)
            with open(meta_path, "w", encoding="utf-8") as f:
                json.dump(meta, f, indent=1, default=str)
                f.flush()
                os.fsync(f.fileno())
            manifest_files[METADATA_NAME] = {
                "bytes": os.path.getsize(meta_path),
                "crc32": int(crc32_file(meta_path)),
            }
            # the manifest is written LAST: its presence is what marks
            # the stage complete (versions() requires it)
            man_path = os.path.join(tmp_dir, MANIFEST_NAME)
            with open(man_path, "w", encoding="utf-8") as f:
                json.dump({"format_version": REGISTRY_FORMAT_VERSION,
                           "version": version,
                           "files": manifest_files}, f, indent=1)
                f.flush()
                os.fsync(f.fileno())
            # the staged dir's own dirents must be durable BEFORE the
            # rename: without this a power loss could surface the
            # renamed version with a file's directory entry missing
            _fsync_dir(tmp_dir)
            os.rename(tmp_dir, final_dir)
        except BaseException:
            shutil.rmtree(tmp_dir, ignore_errors=True)
            raise
        _fsync_dir(self.versions_dir)
        Log.info("registry: published v%d (%s%s)", version, model_path,
                 ", with profile" if profile_path else "")
        return version

    def verify(self, version):
        """Re-checksum every manifested file of a version; raises
        RegistryError on any mismatch (bit rot, truncation, tamper).
        Returns the parsed manifest."""
        if faults.consume("corrupt_registry_version"):
            # chaos: a torn publish — verify must fail exactly as if a
            # checksum mismatched, so followers refuse the swap and the
            # incumbent keeps serving (tests/test_resilience.py)
            raise RegistryError(
                f"v{version}: injected fault corrupt_registry_version")
        vdir = self.version_dir(version)
        man_path = os.path.join(vdir, MANIFEST_NAME)
        try:
            with open(man_path, "r", encoding="utf-8") as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            raise RegistryError(f"v{version} has no readable manifest: {e}")
        for name, rec in manifest.get("files", {}).items():
            path = os.path.join(vdir, name)
            if not os.path.exists(path):
                raise RegistryError(f"v{version} is missing {name}")
            size = os.path.getsize(path)
            if size != int(rec["bytes"]):
                raise RegistryError(
                    f"v{version}/{name}: {size} bytes, manifest says "
                    f"{rec['bytes']}")
            crc = int(crc32_file(path))
            if crc != int(rec["crc32"]):
                raise RegistryError(
                    f"v{version}/{name}: crc32 {crc:#010x} != manifest "
                    f"{int(rec['crc32']):#010x}")
        return manifest

    # ------------------------------------------------------------ pointer
    def current(self):
        """The CURRENT pointer dict ({version, generation, ts,
        history}) or None before the first promotion. A torn/corrupt
        pointer reads as None (the writer is atomic, so this only
        happens on foreign interference)."""
        path = os.path.join(self.directory, CURRENT_NAME)
        try:
            with open(path, "r", encoding="utf-8") as f:
                cur = json.load(f)
        except OSError:
            return None
        except ValueError:
            Log.warning("registry: unreadable CURRENT pointer at %s", path)
            return None
        return cur if isinstance(cur, dict) and "version" in cur else None

    def current_version(self):
        cur = self.current()
        return int(cur["version"]) if cur else None

    def _write_pointer(self, version, prev, reason, history=None):
        """Atomically write CURRENT. `history` defaults to the promote
        rule (append the previously live version); rollback passes its
        own popped history."""
        generation = (int(prev["generation"]) + 1) if prev else 1
        if history is None:
            history = list(prev.get("history", [])) if prev else []
            if prev:
                history.append(int(prev["version"]))
                history = history[-HISTORY_DEPTH:]
        pointer = {"version": int(version), "generation": generation,
                   "ts": time.time(), "reason": str(reason or ""),
                   "history": history}
        atomic_write_text(os.path.join(self.directory, CURRENT_NAME),
                          json.dumps(pointer, separators=(",", ":"))
                          + "\n")
        return pointer

    def promote(self, version, reason="", force=False, **journal_fields):
        """Verify a version's manifest and move the CURRENT pointer to
        it (atomic). Quarantined versions need `force=True`. Returns
        the new pointer dict and journals a `promote` record."""
        version = int(version)
        self.verify(version)
        if self.is_quarantined(version) and not force:
            raise RegistryError(
                f"v{version} is quarantined; promote(force=True) to "
                "override")
        prev = self.current()
        if prev and int(prev["version"]) == version:
            Log.info("registry: v%d already live", version)
            return prev
        pointer = self._write_pointer(version, prev, reason)
        self._journal("promote", version=version,
                      from_version=int(prev["version"]) if prev else None,
                      generation=pointer["generation"],
                      reason=str(reason or ""), **journal_fields)
        Log.structured("Info", "fleet_promote", version=version,
                       from_version=prev["version"] if prev else None,
                       generation=pointer["generation"])
        return pointer

    def quarantine(self, version, reason="", **journal_fields):
        """Mark a candidate as rejected (a failed validation). The
        files stay — evidence, not garbage. Journals a `reject`
        record. Quarantining the LIVE version is refused: roll back
        first."""
        version = int(version)
        if version not in self.versions():
            raise RegistryError(f"no published v{version} to quarantine")
        cur = self.current()
        if cur and int(cur["version"]) == version:
            raise RegistryError(
                f"v{version} is live; rollback before quarantining")
        marker = os.path.join(self.version_dir(version), QUARANTINE_NAME)
        atomic_write_text(marker, json.dumps(
            {"ts": time.time(), "reason": str(reason or "")}) + "\n")
        self._journal("reject", version=version,
                      reason=str(reason or ""), **journal_fields)
        Log.structured("Warning", "fleet_reject", version=version,
                       reason=str(reason or ""))

    def rollback(self, reason="", **journal_fields):
        """Move CURRENT back to the previously live version (pointer
        history). The restored version's files never moved, so the
        restore is byte-identical; the manifest is re-verified anyway.
        Returns the new pointer dict and journals a `rollback`
        record."""
        cur = self.current()
        if not cur:
            raise RegistryError("nothing is live; cannot roll back")
        history = list(cur.get("history", []))
        if not history:
            raise RegistryError("no prior version in pointer history")
        target = int(history[-1])
        self.verify(target)
        pointer = self._write_pointer(target, cur,
                                      reason or "rollback",
                                      history=history[:-1])
        self._journal("rollback", version=target,
                      from_version=int(cur["version"]),
                      generation=pointer["generation"],
                      reason=str(reason or ""), **journal_fields)
        Log.structured("Warning", "fleet_rollback", version=target,
                       from_version=int(cur["version"]))
        return pointer

    # ------------------------------------------------------------ summary
    def describe(self):
        """JSON-ready registry summary (the CLI's `list` view)."""
        cur = self.current()
        out = {"directory": self.directory,
               "current": cur, "versions": []}
        for v in self.versions():
            rec = {"version": v,
                   "live": bool(cur and int(cur["version"]) == v),
                   "quarantined": self.is_quarantined(v),
                   "has_profile": self.profile_path(v) is not None}
            try:
                meta = self.metadata(v)
                for key in ("published_ts", "metric", "metric_name",
                            "parent_version", "train_rows", "source"):
                    if key in meta:
                        rec[key] = meta[key]
            except RegistryError:
                rec["metadata_error"] = True
            out["versions"].append(rec)
        return out
