"""Load generator: sustained-QPS /predict traffic with timestamped
latency capture — the instrument that prices a hot-swap.

A swap's cost is invisible to whole-run percentiles (a 50 ms blip
inside a 10 s run moves p99 by nothing), so every request keeps its
START timestamp and `report()` slices the timeline into
[steady | swap window | steady], emitting p50/p99 for the steady
phases and p99 *inside* the marked window — `serving.p99_during_swap_ms`
is the number BENCH_BASELINE.json tracks and `make verify-fleet`
gates.

Open-loop pacing: each worker owns every k-th tick of a global
`start + i / qps` schedule and sleeps until its tick, so a slow
response DELAYS later requests rather than silently lowering the
offered rate (closed-loop generators hide exactly the stall a swap
would cause). Errors never raise out of a worker: 5xx/timeouts are
counted (`errors`) and the run continues — the assertion that a swap
causes zero 5xx belongs to the caller.

stdlib-only (threading + urllib), same floor as the serving stack.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np

from ..telemetry import disttrace


class LoadGenerator:
    """Drive `POST <url>/predict` at `qps` requests/s with `workers`
    concurrent threads for `duration_s`. Rows per request cycle
    through `row_batches` (a list of (n, F) arrays), so responses stay
    checkable against per-model expectations."""

    def __init__(self, url, row_batches, qps=100.0, workers=4,
                 duration_s=5.0, timeout_s=30.0, path="/predict",
                 deadline_ms=None, trace=False):
        self.url = url.rstrip("/") + path
        self.bodies = [json.dumps({"rows": np.asarray(b).tolist()})
                       .encode() for b in row_batches]
        self.qps = float(qps)
        self.workers = int(workers)
        self.duration_s = float(duration_s)
        self.timeout_s = float(timeout_s)
        # deadline propagation (docs/Resilience.md): every request
        # carries `X-Deadline-Ms: deadline_ms` so the serving side can
        # deadline-drop/shed; None = header omitted (legacy behavior)
        self.deadline_ms = deadline_ms
        # trace=True makes the generator the TRACE HEAD: each request
        # carries a fresh sampled X-Trace-Ctx so the whole synthetic
        # flow shows up on /tracez (docs/Observability.md); trace=False
        # still routes headers through inject_headers, which passes
        # them through unstamped when no context is active
        self.trace = bool(trace)
        self.samples = []      # (t_start_rel, latency_s, ok)
        self.responses = []    # (t_start_rel, predictions) when kept
        self.errors = []       # repr strings, bounded
        self.status_counts = {}   # HTTP status -> count (0 = transport)
        self.keep_responses = False
        self._lock = threading.Lock()
        self._marks = {}       # name -> (t0_rel, t1_rel)
        self.t0 = None

    # ------------------------------------------------------------- marks
    def mark_start(self, name):
        with self._lock:
            self._marks[name] = [time.monotonic() - self.t0, None]

    def mark_end(self, name):
        with self._lock:
            if name in self._marks:
                self._marks[name][1] = time.monotonic() - self.t0

    # --------------------------------------------------------------- run
    def _worker(self, wid):
        n_total = int(self.qps * self.duration_s)
        i = wid
        while i < n_total:
            sched = self.t0 + i / self.qps
            delay = sched - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            body = self.bodies[i % len(self.bodies)]
            t_req = time.monotonic()
            ok, preds, status = True, None, 200
            headers = {"Content-Type": "application/json"}
            if self.deadline_ms is not None:
                headers["X-Deadline-Ms"] = str(float(self.deadline_ms))
            ctx = (disttrace.TraceContext(disttrace.new_trace_id(),
                                          disttrace.new_span_id(),
                                          flags=disttrace.FLAG_SAMPLED)
                   if self.trace else None)
            headers = disttrace.inject_headers(headers, ctx=ctx)
            try:
                req = urllib.request.Request(
                    self.url, data=body, headers=headers)
                with urllib.request.urlopen(
                        req, timeout=self.timeout_s) as r:
                    status = r.status
                    out = json.loads(r.read())
                if self.keep_responses:
                    preds = out.get("predictions")
            except Exception as e:   # count, never raise (module doc)
                ok = False
                # keep the real status: "zero 5xx under chaos" must
                # distinguish a refusal (429/504, correct) from a
                # server error (5xx, a bug); 0 = transport-level error
                status = getattr(e, "code", 0) or 0
                with self._lock:
                    if len(self.errors) < 50:
                        self.errors.append(repr(e))
            lat = time.monotonic() - t_req
            with self._lock:
                self.samples.append((t_req - self.t0, lat, ok))
                self.status_counts[status] = \
                    self.status_counts.get(status, 0) + 1
                if preds is not None:
                    self.responses.append((t_req - self.t0, preds))
            i += self.workers

    def run(self, background=False):
        """Fire the schedule. `background=True` returns immediately
        with the worker threads running (the caller swaps mid-run and
        then `join()`s)."""
        self.t0 = time.monotonic()
        self._threads = [threading.Thread(target=self._worker, args=(w,),
                                          daemon=True)
                         for w in range(self.workers)]
        for t in self._threads:
            t.start()
        if not background:
            self.join()
        return self

    def join(self, timeout=None):
        deadline = None if timeout is None else time.monotonic() + timeout
        for t in self._threads:
            t.join(None if deadline is None
                   else max(0.0, deadline - time.monotonic()))

    # ------------------------------------------------------------ report
    @staticmethod
    def _pct(lats, p):
        """Nearest-rank percentile in ms (telemetry/registry.py
        nearest_rank — the same convention as the /metricz ring, so
        the gated p99-during-swap and serving p99 stay comparable)."""
        if not lats:
            return 0.0
        from ..telemetry.registry import nearest_rank
        return round(nearest_rank(sorted(lats), p) * 1e3, 3)

    def report(self, swap_mark="swap"):
        """Aggregate: steady p50/p99 (samples OUTSIDE the swap mark),
        p99 during the mark, offered/achieved rate, error count."""
        with self._lock:
            samples = list(self.samples)
            mark = self._marks.get(swap_mark)
            status_counts = dict(self.status_counts)
        lat_all = [lt for _, lt, ok in samples if ok]
        out = {"requests": len(samples),
               "errors": sum(1 for _, _, ok in samples if not ok),
               "status_counts": status_counts,
               "server_errors_5xx": sum(
                   n for s, n in status_counts.items()
                   if 500 <= s < 600),
               "offered_qps": round(self.qps, 1)}
        if samples:
            span = max(t for t, _, _ in samples) - min(
                t for t, _, _ in samples)
            out["achieved_qps"] = round(
                len(samples) / max(span, 1e-9), 1)
        if mark and mark[1] is not None:
            t0, t1 = mark
            # a sample belongs to the swap window if its LIFETIME
            # overlaps it — a request in flight when the window opens
            # absorbs the stall and must not inflate the steady bucket
            # (which would let the gate pass trivially)
            during = [lt for t, lt, ok in samples
                      if ok and t <= t1 and t + lt >= t0]
            steady = [lt for t, lt, ok in samples
                      if ok and (t > t1 or t + lt < t0)]
            out.update({
                "steady_p50_ms": self._pct(steady, 50),
                "steady_p99_ms": self._pct(steady, 99),
                "p99_during_swap_ms": self._pct(during, 99),
                "swap_window_s": round(t1 - t0, 3),
                "swap_window_requests": len(during),
            })
        else:
            out.update({"steady_p50_ms": self._pct(lat_all, 50),
                        "steady_p99_ms": self._pct(lat_all, 99)})
        return out
