"""Fleet subsystem: model registry, hot-swap serving, and the
drift-triggered train -> validate -> promote loop (docs/Fleet.md).

PR 9 built the sensors (serving/drift.py PSI excursions, skew
monitoring, dataset profiles) and PRs 2/3/7 built the training
machinery (checkpoints, supervisor, block stores); this package is the
actuator that closes the loop:

- `registry.ModelRegistry` — versioned on-disk store of model +
  profile sidecar + metadata with atomic publish (tmp+fsync+rename,
  CRC manifest like the block store) and promote/rollback pointers;
- `hotswap` — load + AOT-warm a challenger CompiledPredictor behind
  the incumbent, flip atomically under the micro-batcher, and a
  registry follower so a running server picks up promotions without
  restart (`python -m lightgbm_tpu.serve model --registry DIR
  --follow`);
- `pipeline.FleetPipeline` — consumes psi_warn excursions from
  /driftz, retrains on fresh data (riding PR-2 checkpoints and PR-7
  block stores), validates the challenger against the incumbent on a
  holdout, and promotes or quarantines via the registry, journaling
  every transition (promote/reject/rollback) through the PR-5 journal;
- `loadgen.LoadGenerator` — sustained-QPS /predict driver that records
  p50/p99 under concurrency, including p99 *during* a hot-swap (the
  bench's fleet_probe and `make verify-fleet` ride it).

Import cost note: this package pulls in the serving stack (and so
jax) only through `hotswap`; `registry`, `pipeline` policy logic and
`loadgen` are importable jax-free.
"""

from .registry import ModelRegistry, RegistryError

__all__ = ["ModelRegistry", "RegistryError"]
