"""Front-door router: least-in-flight dispatch, circuit breakers,
health ejection, budgeted retries and hedging over serving replicas.

No reference equivalent — the reference's resilience story ends at the
socket linker's connect-retry loop (network/linkers_socket.cpp); a
serving FLEET needs its failures contained at the front door. This is
a stdlib-only reverse proxy (ThreadingHTTPServer + http.client, the
same no-new-deps rule as the rest of the serving stack) that makes a
set of `python -m lightgbm_tpu.serve` replicas look like one endpoint:

    python -m lightgbm_tpu.fleet route \
        --targets 127.0.0.1:8099,127.0.0.1:8100 --port 8800

Per predict POST (docs/Resilience.md):

- selection: the healthy replica with the fewest router-side in-flight
  requests (least-in-flight beats round-robin under heterogeneous
  replica speed — a slowed replica naturally accumulates in-flight and
  stops being picked).
- circuit breaker, per replica: `breaker_failures` CONSECUTIVE
  transport errors / 5xx open the breaker; an open breaker sits out
  `breaker_reset_s`, then admits exactly ONE half-open probe — success
  closes it, failure re-opens. 4xx, 429 and 504 are the replica
  WORKING (refusing correctly), so they never trip it.
- health ejection: a background thread polls `GET /healthz?strict=1`
  under a hard timeout; non-200 (including a DRAINING replica — the
  strict probe exists for exactly that) ejects the replica from
  selection until it recovers.
- retries: a transport error or retryable 5xx is retried against a
  DIFFERENT replica, with seeded jitter, while the retry token bucket
  (refilled `retry_budget` per client request) has a token — the
  budget caps error amplification at 1 + retry_budget no matter how
  hard the fleet is failing. 429/504 propagate to the client
  unretried: shedding and deadline semantics are end-to-end.
- hedging (off by default): when `hedge_quantile` > 0 and the latency
  ring has enough samples, a request still unanswered after that
  latency quantile fires one duplicate at a second replica; first
  answer wins and the loser's connection is torn down
  (`hedge_cancelled_count`). Hedges draw from the same retry budget.
- deadlines: the client's `X-Deadline-Ms` is re-derived per attempt
  (remaining = deadline - elapsed) so a retry never inherits a stale
  budget; every upstream call runs under
  min(remaining, `upstream_timeout_s`) — no outbound socket is ever
  unbounded (enforced repo-wide by the `unbounded-io` lint rule).

`/metricz` serves the router's own counters (shed/retry/hedge/eject/
breaker transitions, per-replica gauges) as JSON (with a
``"router": true`` marker the fleet aggregator keys on) and canonical
Prometheus text via `?format=prometheus`; `/healthz` reports the
replica table. Both are answered locally — admin traffic never
consumes replica capacity.
"""

import argparse
import http.client
import json
import queue
import random
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlsplit

from ..telemetry import disttrace
from ..telemetry import prometheus
from ..telemetry.registry import MetricsRegistry
from ..utils.log import Log

# breaker states (ints on the metrics page: closed=0 open=1 half=2)
CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"
_BREAKER_CODE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}

# upstream statuses worth a retry elsewhere: the replica (or its box)
# is broken. 429/504 are the protocol WORKING — never retried.
RETRYABLE_STATUSES = (500, 502, 503)

# hedging needs a latency distribution to aim at; below this many
# samples the quantile is noise and hedging stays off
MIN_HEDGE_SAMPLES = 20

# token-bucket cap: bursts of retries allowed around a failure spike
RETRY_BURST_CAP = 10.0


class Replica:
    """Router-side state for one upstream target. All mutable fields
    are guarded by the owning Router's lock."""

    def __init__(self, target):
        base = target.split("//")[-1].rstrip("/")
        host, _, port = base.partition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port or 80)
        self.target = f"{self.host}:{self.port}"
        self.in_flight = 0
        self.breaker = CLOSED
        self.consecutive_failures = 0
        self.opened_at = 0.0
        self.probe_in_flight = False
        self.ejected = False

    def __repr__(self):
        return (f"Replica({self.target} {self.breaker}"
                f"{' ejected' if self.ejected else ''})")


class Router:
    """Replica table + breaker/budget/hedge policy. Pure logic plus
    http.client calls — the HTTP front end (RouterHandler) and the
    chaos tests drive the same object."""

    def __init__(self, targets, breaker_failures=5, breaker_reset_s=1.0,
                 retry_budget=0.1, hedge_quantile=0.0,
                 upstream_timeout_s=10.0, health_poll_s=0.5,
                 retry_jitter_ms=5.0, trace_recorder=None):
        if not targets:
            raise ValueError("router needs at least one target")
        self.replicas = [Replica(t) for t in targets]
        # distributed tracing (telemetry/disttrace.py): the router owns
        # every trace's ROOT span; a NOOP recorder keeps the hot path
        # branch-free when tracing is off
        self.trace = trace_recorder or disttrace.NOOP_RECORDER
        self.breaker_failures = max(1, int(breaker_failures))
        self.breaker_reset_s = float(breaker_reset_s)
        self.retry_budget = float(retry_budget)
        self.hedge_quantile = float(hedge_quantile)
        self.upstream_timeout_s = float(upstream_timeout_s)
        self.health_poll_s = float(health_poll_s)
        self.retry_jitter_ms = float(retry_jitter_ms)
        self._lock = threading.Lock()
        # SEEDED jitter: retry spacing must not depend on process
        # entropy (chaos runs are reproducible; nondeterminism lint)
        self._rng = random.Random(0x5EED)
        self._retry_tokens = 1.0   # one free retry before any refill
        self.registry = MetricsRegistry()
        reg = self.registry
        self._requests = reg.counter("request_count")
        self._attempts = reg.counter("upstream_attempt_count")
        self._retries = reg.counter("retry_count")
        self._hedges = reg.counter("hedge_count")
        self._hedge_cancelled = reg.counter("hedge_cancelled_count")
        self._no_replica = reg.counter("no_replica_count")
        self._breaker_opens = reg.counter("breaker_open_count")
        self._breaker_closes = reg.counter("breaker_close_count")
        self._ejects = reg.counter("eject_count")
        self._errors = reg.counter("error_count")
        self._deadline_expired = reg.counter("deadline_expired_count")
        self._latency = reg.histogram("latency_ms")
        # per-replica upstream latency: what the hedger aims at, now
        # exposed as p50/p99 gauges so hedge-threshold tuning is
        # observable instead of blind
        self._rep_latency = [
            reg.histogram(f"replica_{i}_upstream_latency_ms")
            for i in range(len(self.replicas))]
        self._rep_index = {rep.target: i
                           for i, rep in enumerate(self.replicas)}
        self.started_at = time.time()
        self._stop = threading.Event()
        self._health_thread = None

    # ------------------------------------------------------------ selection
    def _breaker_admits(self, rep, now):
        """Lock held. OPEN->HALF_OPEN transition happens lazily here:
        the first pick after the reset window becomes the probe."""
        if rep.breaker == CLOSED:
            return True
        if rep.breaker == OPEN:
            if now - rep.opened_at >= self.breaker_reset_s:
                rep.breaker = HALF_OPEN
                rep.probe_in_flight = False
                return not rep.probe_in_flight
            return False
        return not rep.probe_in_flight   # HALF_OPEN: one probe at a time

    def pick(self, exclude=()):
        """Least-in-flight healthy replica, or None. A HALF_OPEN pick
        claims the single probe slot."""
        now = time.monotonic()
        with self._lock:
            best = None
            for rep in self.replicas:
                if rep in exclude or rep.ejected:
                    continue
                if not self._breaker_admits(rep, now):
                    continue
                if best is None or rep.in_flight < best.in_flight:
                    best = rep
            if best is not None and best.breaker == HALF_OPEN:
                best.probe_in_flight = True
            return best

    # -------------------------------------------------------------- breaker
    def on_success(self, rep):
        with self._lock:
            rep.consecutive_failures = 0
            rep.probe_in_flight = False
            if rep.breaker != CLOSED:
                rep.breaker = CLOSED
                self._breaker_closes.inc()
                Log.info("router: breaker CLOSED for %s", rep.target)

    def on_failure(self, rep):
        now = time.monotonic()
        with self._lock:
            rep.consecutive_failures += 1
            if rep.breaker == HALF_OPEN:
                # the probe failed: straight back to OPEN
                rep.breaker = OPEN
                rep.opened_at = now
                rep.probe_in_flight = False
                self._breaker_opens.inc()
                Log.info("router: breaker RE-OPENED for %s", rep.target)
            elif (rep.breaker == CLOSED
                  and rep.consecutive_failures >= self.breaker_failures):
                rep.breaker = OPEN
                rep.opened_at = now
                self._breaker_opens.inc()
                Log.warning("router: breaker OPEN for %s (%d consecutive "
                            "failures)", rep.target,
                            rep.consecutive_failures)

    # -------------------------------------------------------------- budget
    def _grant_request_budget(self):
        with self._lock:
            self._retry_tokens = min(RETRY_BURST_CAP,
                                     self._retry_tokens + self.retry_budget)

    def _take_retry_token(self):
        with self._lock:
            if self._retry_tokens >= 1.0:
                self._retry_tokens -= 1.0
                return True
            return False

    # -------------------------------------------------------------- health
    def probe_health(self):
        """One health sweep over every replica (the poll thread's body;
        tests call it directly for a deterministic step)."""
        timeout = max(0.1, min(1.0, self.health_poll_s))
        for rep in self.replicas:
            healthy = False
            conn = http.client.HTTPConnection(rep.host, rep.port,
                                              timeout=timeout)
            try:
                conn.request("GET", "/healthz?strict=1")
                healthy = conn.getresponse().status == 200
            except OSError:
                healthy = False
            finally:
                conn.close()
            with self._lock:
                if rep.ejected != (not healthy):
                    if healthy:
                        Log.info("router: %s back in rotation",
                                 rep.target)
                    else:
                        self._ejects.inc()
                        Log.warning("router: ejected %s (strict health "
                                    "probe failed)", rep.target)
                rep.ejected = not healthy

    def start_health_loop(self):
        def loop():
            while not self._stop.wait(self.health_poll_s):
                self.probe_health()
        self._health_thread = threading.Thread(
            target=loop, name="router-health", daemon=True)
        self._health_thread.start()

    def stop(self):
        self._stop.set()
        if self._health_thread is not None:
            self._health_thread.join(timeout=2.0)

    # ------------------------------------------------------------- proxying
    def _proxy_once(self, rep, path, body, headers, timeout_s,
                    conn_box=None, span=None):
        """One upstream attempt. Returns (status, resp_headers, data);
        raises OSError-family on transport failure. `conn_box` lets a
        hedging race close this connection from outside (cancel);
        `span` is this attempt's trace span — its context is what the
        replica continues (the attempt, not the root, is the upstream
        hop's parent)."""
        self._attempts.inc()
        headers = disttrace.inject_headers(
            headers, ctx=span.context() if span is not None else None)
        conn = http.client.HTTPConnection(rep.host, rep.port,
                                          timeout=timeout_s)
        if conn_box is not None:
            conn_box.append(conn)
        with self._lock:
            rep.in_flight += 1
        t_up = time.monotonic()
        try:
            conn.request("POST", path, body=body, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            idx = self._rep_index.get(rep.target)
            if idx is not None:
                self._rep_latency[idx].observe(
                    (time.monotonic() - t_up) * 1e3)
            # echo the replica's story to the caller: timing + ids
            # survive the proxy hop instead of dying at the router
            keep = {k: v for k, v in resp.getheaders()
                    if k.lower() in ("content-type", "retry-after",
                                     "x-request-id", "x-timing-ms")}
            return resp.status, keep, data
        finally:
            with self._lock:
                rep.in_flight -= 1
            conn.close()

    def _attempt_timeout(self, deadline_abs):
        if deadline_abs is None:
            return self.upstream_timeout_s
        remaining = deadline_abs - time.monotonic()
        return max(0.05, min(self.upstream_timeout_s, remaining))

    def _upstream_headers(self, headers, deadline_abs):
        out = dict(headers)
        if deadline_abs is not None:
            # re-derive the remaining budget per attempt: a retry must
            # not inherit the original (now stale) header value
            remaining_ms = max(0.0,
                               (deadline_abs - time.monotonic()) * 1e3)
            out["X-Deadline-Ms"] = f"{remaining_ms:.1f}"
        return out

    def _hedge_delay_s(self):
        if self.hedge_quantile <= 0.0:
            return None
        if self._latency.window < MIN_HEDGE_SAMPLES:
            return None
        pct = self.hedge_quantile * 100.0
        ms = self._latency.percentiles((pct,)).get(pct)
        return None if ms is None else ms / 1e3

    def _finish_attempt(self, span, status, err, cancelled=False):
        """Close one attempt span with the outcome the trace reader
        needs: ok / error (transport or retryable 5xx) / cancelled
        (hedge loser whose socket the winner tore down)."""
        if span is None:
            return
        if cancelled:
            st = "cancelled"
        elif err is not None or status in RETRYABLE_STATUSES:
            st = "error"
        else:
            st = "ok"
        tags = {}
        if status is not None:
            tags["http.status"] = int(status)
        if err is not None:
            tags["error"] = str(err)[:200]
        self.trace.finish(span, status=st, **tags)

    def _attempt(self, rep, path, body, headers, deadline_abs,
                 root_ctx=None, attempt_no=0):
        """One attempt with optional hedging. Returns
        (status, headers, data, error, rep_that_answered)."""
        timeout_s = self._attempt_timeout(deadline_abs)
        up_headers = self._upstream_headers(headers, deadline_abs)
        hedge_delay = self._hedge_delay_s()
        if hedge_delay is None:
            span = self.trace.start(
                "router.attempt", ctx=root_ctx, kind="client",
                tags={"replica": rep.target, "attempt": attempt_no})
            try:
                status, rh, data = self._proxy_once(
                    rep, path, body, up_headers, timeout_s, span=span)
                self._finish_attempt(span, status, None)
                return status, rh, data, None, rep
            except OSError as e:
                self._finish_attempt(span, None, e)
                return None, {}, b"", e, rep

        results = queue.Queue()
        races = []    # [{rep, conns, span, cancelled}]

        def run(target_rep, hedged):
            entry = {"rep": target_rep, "conns": [], "cancelled": False}
            entry["span"] = self.trace.start(
                "router.attempt", ctx=root_ctx, kind="client",
                tags={"replica": target_rep.target,
                      "attempt": attempt_no, "hedge": hedged})
            races.append(entry)
            try:
                status, rh, data = self._proxy_once(
                    target_rep, path, body,
                    self._upstream_headers(headers, deadline_abs),
                    timeout_s, conn_box=entry["conns"],
                    span=entry["span"])
                self._finish_attempt(entry["span"], status, None,
                                     cancelled=entry["cancelled"])
                results.put((target_rep, status, rh, data, None))
            except OSError as e:
                self._finish_attempt(entry["span"], None, e,
                                     cancelled=entry["cancelled"])
                results.put((target_rep, None, {}, b"", e))

        threading.Thread(target=run, args=(rep, False),
                         daemon=True).start()
        launched = 1
        try:
            # primary answered (or failed fast) inside the hedge delay:
            # no hedge — a fast FAILURE is dispatch()'s budgeted-retry
            # business, hedging only covers slowness
            won, status, rh, data, err = results.get(timeout=hedge_delay)
            return status, rh, data, err, won
        except queue.Empty:
            pass
        second = self.pick(exclude=(rep,))
        if second is not None and self._take_retry_token():
            self._hedges.inc()
            threading.Thread(target=run, args=(second, True),
                             daemon=True).start()
            launched = 2
        best = None
        for _ in range(launched):
            try:
                out = results.get(timeout=timeout_s + 1.0)
            except queue.Empty:
                break
            won, status, rh, data, err = out
            if err is None and status not in RETRYABLE_STATUSES:
                # first good answer wins: abort the loser's socket so
                # no orphan result is ever written to the client. The
                # cancelled flag flips FIRST so the loser thread's
                # span closes as "cancelled", not "error"
                for entry in races:
                    if entry["rep"] is not won:
                        entry["cancelled"] = True
                        for c in entry["conns"]:
                            try:
                                c.close()
                            except OSError:
                                pass
                        self._hedge_cancelled.inc()
                return status, rh, data, None, won
            best = out
        if best is None:
            return None, {}, b"", OSError("hedge race produced no "
                                          "answer"), rep
        won, status, rh, data, err = best
        return status, rh, data, err, won

    def dispatch(self, path, body, headers):
        """Route one client predict: pick -> attempt -> (budgeted)
        retries, under one trace root span. Returns
        (status, headers, data)."""
        t0 = time.monotonic()
        # continue the client's trace (X-Trace-Ctx) or root a new one;
        # the head sampling decision made here propagates to every hop
        ctx = disttrace.parse_header(
            headers.get(disttrace.TRACE_HEADER) or "")
        root = self.trace.start("router.request", ctx=ctx, kind="server",
                                tags={"component": "router",
                                      "path": path})
        try:
            status, rh, data = self._dispatch(
                path, body, headers, root, t0)
        except BaseException:
            self.trace.finish(root, status="error",
                              elapsed=time.monotonic() - t0)
            raise
        root.set_tag("http.status", int(status))
        self.trace.finish(
            root, status="error" if status >= 500 else "ok",
            elapsed=time.monotonic() - t0)
        return status, rh, data

    def _dispatch(self, path, body, headers, root, t0):
        self._requests.inc()
        self._grant_request_budget()
        root_ctx = root.context() if root is not None else None
        deadline_abs = None
        dl = headers.get("X-Deadline-Ms")
        if dl is not None:
            try:
                deadline_abs = t0 + float(dl) / 1e3
            except ValueError:
                deadline_abs = None
        tried = set()
        attempt_no = 0
        last = (502, {}, json.dumps(
            {"error": "no upstream attempt"}).encode())
        while True:
            if deadline_abs is not None \
                    and deadline_abs <= time.monotonic():
                self._deadline_expired.inc()
                root.set_tag("decision", "deadline_expired")
                return 504, {}, json.dumps(
                    {"error": "deadline expired at router"}).encode()
            rep = self.pick(exclude=tried)
            if rep is None:
                if not tried:
                    self._no_replica.inc()
                    self._errors.inc()
                    root.set_tag("decision", "no_healthy_replica")
                    return 503, {"Retry-After": "1"}, json.dumps(
                        {"error": "no healthy replica"}).encode()
                self._errors.inc()
                root.set_tag("decision", "replicas_exhausted")
                return last
            attempt_no += 1
            status, rh, data, err, won = self._attempt(
                rep, path, body, headers, deadline_abs,
                root_ctx=root_ctx, attempt_no=attempt_no)
            # the answering replica's breaker gets the credit/blame —
            # when a hedge won, the slow primary is not a "failure"
            failed = err is not None or status in RETRYABLE_STATUSES
            (self.on_failure if failed else self.on_success)(won)
            if not failed:
                self._latency.observe((time.monotonic() - t0) * 1e3)
                if attempt_no > 1:
                    root.set_tag("retries", attempt_no - 1)
                return status, rh, data
            last = (status if status is not None else 502,
                    rh, data or json.dumps(
                        {"error": f"upstream failed: {err}"}).encode())
            tried.add(rep)
            if not self._take_retry_token():
                self._errors.inc()
                root.set_tag("decision", "retry_budget_exhausted")
                root.set_tag("retries", attempt_no - 1)
                return last
            self._retries.inc()
            # seeded jitter de-synchronizes retry stampedes
            time.sleep(self._rng.uniform(0.0, self.retry_jitter_ms) / 1e3)

    # -------------------------------------------------------------- metrics
    def snapshot(self):
        """JSON /metricz payload. The ``"router": true`` marker is what
        the fleet aggregator keys the router role on."""
        with self.registry.lock:
            pct = self._latency.percentiles((50, 95, 99))
            snap = {
                "router": True,
                "uptime_s": round(time.time() - self.started_at, 3),
                "request_count": self._requests.value,
                "upstream_attempt_count": self._attempts.value,
                "retry_count": self._retries.value,
                "hedge_count": self._hedges.value,
                "hedge_cancelled_count": self._hedge_cancelled.value,
                "no_replica_count": self._no_replica.value,
                "breaker_open_count": self._breaker_opens.value,
                "breaker_close_count": self._breaker_closes.value,
                "eject_count": self._ejects.value,
                "error_count": self._errors.value,
                "deadline_expired_count": self._deadline_expired.value,
                "latency_p50_ms": round(pct.get(50, 0.0), 4),
                "latency_p95_ms": round(pct.get(95, 0.0), 4),
                "latency_p99_ms": round(pct.get(99, 0.0), 4),
                "latency_window": self._latency.window,
            }
        # per-replica upstream quantiles (the hedger's own aim data)
        with self.registry.lock:
            rep_pct = [h.percentiles((50, 99)) for h in self._rep_latency]
        with self._lock:
            snap["replica_count"] = len(self.replicas)
            snap["healthy_replica_count"] = sum(
                1 for r in self.replicas
                if not r.ejected and r.breaker == CLOSED)
            snap["replicas"] = [
                {"target": r.target, "in_flight": r.in_flight,
                 "breaker": r.breaker, "ejected": r.ejected,
                 "consecutive_failures": r.consecutive_failures,
                 "upstream_latency_p50_ms": round(
                     rep_pct[i].get(50, 0.0), 4),
                 "upstream_latency_p99_ms": round(
                     rep_pct[i].get(99, 0.0), 4)}
                for i, r in enumerate(self.replicas)]
        return snap

    def prometheus(self):
        snap = self.snapshot()
        extra = {k: v for k, v in snap.items()
                 if isinstance(v, (int, float))
                 and not isinstance(v, bool)}
        with self._lock:
            for i, rep in enumerate(self.replicas):
                extra[f"replica_{i}_in_flight"] = rep.in_flight
                extra[f"replica_{i}_breaker_state"] = \
                    _BREAKER_CODE[rep.breaker]
                extra[f"replica_{i}_ejected"] = int(rep.ejected)
        for i, entry in enumerate(snap.get("replicas", ())):
            extra[f"replica_{i}_upstream_latency_p50_ms"] = \
                entry["upstream_latency_p50_ms"]
            extra[f"replica_{i}_upstream_latency_p99_ms"] = \
                entry["upstream_latency_p99_ms"]
        return prometheus.render(self.registry.snapshot(),
                                 extra_gauges=extra)


class RouterHandler(BaseHTTPRequestHandler):
    """Thin HTTP front end over the shared Router object."""

    protocol_version = "HTTP/1.1"
    router = None    # bound by make_router_server

    def log_message(self, fmt, *args):
        Log.debug("router http: " + fmt, *args)

    def _reply(self, code, data, headers=None):
        if isinstance(data, (dict, list)):
            data = json.dumps(data).encode("utf-8")
        self.send_response(code)
        hdrs = dict(headers or {})
        hdrs.setdefault("Content-Type", "application/json")
        hdrs["Content-Length"] = str(len(data))
        for name, value in hdrs.items():
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        parts = urlsplit(self.path)
        fmt = (parse_qs(parts.query).get("format") or [""])[0]
        if parts.path.startswith("/healthz"):
            snap = self.router.snapshot()
            healthy = snap["healthy_replica_count"] > 0
            self._reply(200 if healthy else 503,
                        {"status": "ok" if healthy else "no_replicas",
                         "router": True,
                         "replicas": snap["replicas"]})
        elif parts.path.startswith("/metricz"):
            if fmt == "prometheus":
                data = self.router.prometheus().encode("utf-8")
                self._reply(200, data,
                            {"Content-Type": prometheus.CONTENT_TYPE})
            else:
                self._reply(200, self.router.snapshot())
        else:
            self._reply(404, {"error": f"unknown path {self.path}"})

    def do_POST(self):
        path = self.path.split("?")[0]
        if path not in ("/predict", "/predict_raw", "/predict_leaf"):
            self._reply(404, {"error": f"unknown path {self.path}"})
            return
        if "chunked" in (self.headers.get("Transfer-Encoding")
                         or "").lower():
            self.close_connection = True
            self._reply(411, {"error": "chunked bodies not supported"})
            return
        try:
            length = int(self.headers.get("Content-Length", 0) or 0)
        except ValueError:
            self.close_connection = True
            self._reply(400, {"error": "malformed Content-Length"})
            return
        body = self.rfile.read(length) if length > 0 else b""
        fwd = {k: v for k, v in self.headers.items()
               if k.lower() in ("content-type", "x-request-id",
                                "x-deadline-ms", "x-trace-ctx")}
        # the front door MINTS the request id when the client didn't:
        # every upstream hop and every reply — including router-local
        # 503/504s — carries one id the whole story keys on
        rid = next((v for k, v in fwd.items()
                    if k.lower() == "x-request-id"), None)
        if rid is None:
            rid = uuid.uuid4().hex[:16]
            fwd["X-Request-Id"] = rid
        fwd["Content-Length"] = str(len(body))
        status, rh, data = self.router.dispatch(path, body, fwd)
        rh = dict(rh)
        rh.setdefault("X-Request-Id", rid)
        self._reply(status, data, rh)


class RouterHTTPServer(ThreadingHTTPServer):
    daemon_threads = True


def make_router_server(targets, host="127.0.0.1", port=8800,
                       trace_dir=None, trace_rank=0,
                       trace_sample_rate=disttrace.DEFAULT_SAMPLE_RATE,
                       trace_slow_only=False, trace_slow_ms=1000.0,
                       **knobs):
    """Router + bound handler + ThreadingHTTPServer (not yet serving).
    `knobs` are Router() kwargs. Starts the health loop; the caller
    owns serve_forever and shutdown (srv.router.stop() on teardown).
    `trace_dir` arms distributed tracing: completed spans journal
    there (tail-sampled) for the aggregator's collector to stitch."""
    recorder = None
    if trace_dir:
        recorder = disttrace.TraceRecorder(
            directory=trace_dir, rank=trace_rank, service="router",
            sample_rate=trace_sample_rate, slow_ms=trace_slow_ms,
            slow_only=trace_slow_only)
    router = Router(targets, trace_recorder=recorder, **knobs)
    handler = type("BoundRouterHandler", (RouterHandler,),
                   {"router": router})
    srv = RouterHTTPServer((host, port), handler)
    srv.router = router
    router.probe_health()      # populate ejection state before traffic
    router.start_health_loop()
    return srv


def main(args):
    """`python -m lightgbm_tpu.fleet route` entry (fleet/__main__.py
    parses the arguments and calls this)."""
    targets = [t for t in (args.targets or "").split(",") if t.strip()]
    srv = make_router_server(
        targets, host=args.host, port=args.port,
        breaker_failures=args.breaker_failures,
        breaker_reset_s=args.breaker_reset_s,
        retry_budget=args.retry_budget,
        hedge_quantile=args.hedge_quantile,
        upstream_timeout_s=args.upstream_timeout_s,
        health_poll_s=args.health_poll_s,
        trace_dir=getattr(args, "trace_dir", None),
        trace_rank=getattr(args, "trace_rank", 0),
        trace_sample_rate=getattr(args, "trace_sample_rate",
                                  disttrace.DEFAULT_SAMPLE_RATE),
        trace_slow_only=getattr(args, "trace_slow_only", False),
        trace_slow_ms=getattr(args, "trace_slow_ms", 1000.0))
    Log.info("router fronting %d replica(s): %s", len(targets),
             ", ".join(targets))
    # the driver-facing readiness line (same contract as SERVING)
    print(f"ROUTER http://{args.host}:{srv.server_address[1]}",
          flush=True)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        srv.router.stop()
        if srv.router.trace is not disttrace.NOOP_RECORDER:
            srv.router.trace.close()
        srv.server_close()
    return 0
