"""Fleet pipeline: psi_warn excursion -> retrain -> validate -> promote.

The policy loop that turns the PR-9 sensors into actions
(docs/Fleet.md):

1. **Sense** — `drift_excursion` reads a `/driftz` document (the
   serving drift monitor's snapshot, fetched over HTTP by the CLI or
   passed in-process by tests) and decides whether the fleet is
   drifting: any active psi_warn warning, or psi_max over the
   threshold with enough sampled rows to mean it.
2. **Retrain** — `retrain` trains a challenger on fresh data with the
   SAME params as the incumbent (lineage is recorded, not implied).
   Rides the existing machinery: `snapshot_dir` arms the PR-2
   checkpoint callback (an interrupted retrain resumes instead of
   restarting), and `out_of_core=true` in the params streams the fresh
   data through a PR-7 block store. The model + profile sidecar land
   in a work directory, not the registry — publishing is a separate,
   deliberate step.
3. **Validate** — `validate` scores challenger and incumbent on the
   SAME holdout through the host f64 reference path (the serving skew
   monitor's ground truth) and compares the objective's natural metric
   (AUC for binary — higher is better; L2 otherwise — lower is
   better).
4. **Act** — `run_once` publishes the challenger and either promotes
   it (better by at least `min_improvement`) or quarantines it, via
   the registry — which journals the `promote`/`reject` record. A
   serving fleet following the registry picks the promotion up on its
   next poll; `rollback` is one registry call away.

jax only loads inside `retrain`/`validate` — registry admin flows
(`python -m lightgbm_tpu.fleet list/promote/rollback`) stay light.
"""

import json
import os
import time
import urllib.request

import numpy as np

from ..utils.log import Log
from .registry import ModelRegistry

# mirrors serving/drift.py DEFAULT_PSI_WARN (importing the serving
# package here would pull jax into the registry-admin CLI paths;
# tests/test_fleet.py pins the two constants equal)
DEFAULT_PSI_WARN = 0.2
DEFAULT_MIN_IMPROVEMENT = 0.0


def auc_score(labels, scores):
    """Binary AUC via the rank-sum (Mann-Whitney) identity with
    average ranks on ties — matches the reference AUC metric's
    semantics without needing a constructed dataset."""
    y = np.asarray(labels, np.float64).reshape(-1)
    s = np.asarray(scores, np.float64).reshape(-1)
    pos = y > 0
    n_pos = int(pos.sum())
    n_neg = len(y) - n_pos
    if n_pos == 0 or n_neg == 0:
        return 0.5
    order = np.argsort(s, kind="mergesort")
    ranks = np.empty(len(s), np.float64)
    sorted_s = s[order]
    i = 0
    while i < len(s):
        j = i
        while j + 1 < len(s) and sorted_s[j + 1] == sorted_s[i]:
            j += 1
        ranks[order[i:j + 1]] = 0.5 * (i + j) + 1.0   # average 1-based
        i = j + 1
    return float((ranks[pos].sum() - n_pos * (n_pos + 1) / 2.0)
                 / (n_pos * n_neg))


def fetch_driftz(url, timeout=30):
    """GET `<serving url>/driftz` -> the drift snapshot dict."""
    with urllib.request.urlopen(url.rstrip("/") + "/driftz",
                                timeout=timeout) as r:
        return json.loads(r.read())


def _host_scores(model_path, x):
    """Holdout raw scores through the host f64 reference path (device
    predict forced off — validation must not inherit serving-precision
    error). Rides the serving skew monitor's reference scorer: same
    forced-host routing AND the same input-width canonicalization, so
    a holdout narrower/wider than the model's feature count validates
    instead of crashing the supervisor."""
    from ..serving.drift import host_reference_scorer
    return np.asarray(host_reference_scorer(model_path)("raw", x))


class FleetPipeline:
    """One drift-triggered train->validate->promote policy instance
    (module docstring). `registry` may be a path or a ModelRegistry;
    an attached journal receives every transition record."""

    def __init__(self, registry, train_params, workdir=None,
                 psi_warn=DEFAULT_PSI_WARN,
                 min_improvement=DEFAULT_MIN_IMPROVEMENT,
                 snapshot_dir=None, snapshot_period=5, journal=None):
        self.registry = (registry if isinstance(registry, ModelRegistry)
                         else ModelRegistry(registry, journal=journal))
        if journal is not None:
            self.registry.journal = journal
        self.journal = journal
        self.train_params = dict(train_params)
        self.workdir = os.fspath(workdir) if workdir \
            else os.path.join(self.registry.directory, "work")
        os.makedirs(self.workdir, exist_ok=True)
        self.psi_warn = float(psi_warn)
        self.min_improvement = float(min_improvement)
        self.snapshot_dir = snapshot_dir
        self.snapshot_period = int(snapshot_period)
        objective = str(self.train_params.get("objective", "regression"))
        if objective in ("binary", "lambdarank", "rank_xendcg"):
            self.metric_name, self.higher_better = "auc", True
        elif objective in ("multiclass", "multiclassova", "softmax"):
            # softmax logloss over the raw class scores — a real
            # multiclass comparison, not class-0 L2
            self.metric_name, self.higher_better = "multi_logloss", False
        else:
            self.metric_name, self.higher_better = "l2", False

    # -------------------------------------------------------------- sense
    def drift_excursion(self, driftz):
        """Decide whether a /driftz document is an actionable
        excursion. Returns {feature, psi, rows_sampled} (worst
        offender) or None. Requires the monitor's own min_psi_rows
        bar — acting on a cold window would retrain on noise."""
        if not driftz or not driftz.get("enabled", True):
            return None
        rows = int(driftz.get("rows_sampled", 0))
        if rows < int(driftz.get("min_psi_rows", 0)):
            return None
        warnings = driftz.get("warnings") or []
        psi_max = float(driftz.get("psi_max", 0.0))
        if not warnings and psi_max < self.psi_warn:
            return None
        worst, worst_psi = "", psi_max
        for name, rec in (driftz.get("features") or {}).items():
            if float(rec.get("psi", 0.0)) >= worst_psi:
                worst, worst_psi = name, float(rec["psi"])
        if not worst and warnings:
            worst = str(warnings[-1].get("feature", ""))
            worst_psi = float(warnings[-1].get("psi", psi_max))
        return {"feature": worst, "psi": round(worst_psi, 4),
                "rows_sampled": rows}

    # ------------------------------------------------------------ retrain
    def retrain(self, x, y, num_boost_round=None, tag=None):
        """Train a challenger on fresh data and save model + profile
        sidecar into the work directory. Returns the model path.
        `snapshot_dir` arms checkpointing AND resume: a pipeline
        process killed mid-retrain continues from the newest snapshot
        on the next call. A COMPLETED retrain leaves a RETRAIN_DONE
        marker next to its snapshots; the next retrain sees it and
        starts fresh (clearing the stale snapshots) instead of
        resuming a finished run — resuming one would train zero new
        rounds and ignore the new fresh data."""
        import lightgbm_tpu as lgb
        from lightgbm_tpu import callback
        params = dict(self.train_params)
        rounds = params.pop("num_iterations", None)
        if num_boost_round is not None:
            rounds = num_boost_round
        rounds = int(rounds or 100)
        callbacks, resume_from, done_marker = [], None, None
        if self.snapshot_dir:
            os.makedirs(self.snapshot_dir, exist_ok=True)
            done_marker = os.path.join(self.snapshot_dir, "RETRAIN_DONE")
            if os.path.exists(done_marker):
                for name in os.listdir(self.snapshot_dir):
                    if name.endswith(".ckpt"):
                        os.unlink(os.path.join(self.snapshot_dir, name))
                os.unlink(done_marker)
            callbacks.append(callback.checkpoint(
                self.snapshot_dir, period=max(1, self.snapshot_period)))
            resume_from = self.snapshot_dir
        t0 = time.monotonic()
        booster = lgb.train(params,
                            lgb.Dataset(np.asarray(x), np.asarray(y),
                                        params=params),
                            num_boost_round=rounds,
                            callbacks=callbacks or None,
                            resume_from=resume_from,
                            verbose_eval=False)
        tag = tag or time.strftime("%Y%m%d_%H%M%S")
        model_path = os.path.join(self.workdir, f"challenger_{tag}.txt")
        booster.save_model(model_path)
        if done_marker is not None:
            from ..utils.checkpoint import atomic_write_text
            atomic_write_text(done_marker, json.dumps(
                {"ts": time.time(), "model": model_path,
                 "rounds": rounds}) + "\n")
        Log.info("fleet: retrained challenger %s (%d rows, %d rounds, "
                 "%.2fs)", model_path, len(np.asarray(y)), rounds,
                 time.monotonic() - t0)
        return model_path

    # ----------------------------------------------------------- validate
    def metric(self, labels, raw_scores):
        raw = np.asarray(raw_scores, np.float64)
        if self.metric_name == "auc":
            return auc_score(labels, raw[:, 0])
        if self.metric_name == "multi_logloss":
            y = np.asarray(labels, np.int64).reshape(-1)
            z = raw - raw.max(axis=1, keepdims=True)
            logp = z - np.log(np.exp(z).sum(axis=1, keepdims=True))
            return float(-logp[np.arange(len(y)),
                               np.clip(y, 0, raw.shape[1] - 1)].mean())
        err = np.asarray(labels, np.float64).reshape(-1) - raw[:, 0]
        return float(np.mean(err * err))

    def validate(self, challenger_path, holdout_x, holdout_y,
                 incumbent_path=None):
        """Score challenger (and the incumbent, when one is live) on
        the holdout. Returns {metric_name, challenger, incumbent,
        better} — `better` is True when there is no incumbent (first
        model wins by default)."""
        chall = self.metric(holdout_y,
                            _host_scores(challenger_path, holdout_x))
        if incumbent_path is None:
            cur = self.registry.current_version()
            incumbent_path = (self.registry.model_path(cur)
                              if cur is not None else None)
        out = {"metric_name": self.metric_name,
               "challenger": round(chall, 6), "incumbent": None,
               "better": True}
        if incumbent_path and os.path.exists(incumbent_path):
            inc = self.metric(holdout_y,
                              _host_scores(incumbent_path, holdout_x))
            out["incumbent"] = round(inc, 6)
            delta = (chall - inc) if self.higher_better else (inc - chall)
            out["better"] = delta >= self.min_improvement
        return out

    # ---------------------------------------------------------------- act
    def run_once(self, driftz, fresh_x, fresh_y, holdout_x, holdout_y,
                 num_boost_round=None, force=False):
        """One full policy pass. Returns an action dict:
        {action: noop|promote|reject, ...}. `force=True` skips the
        drift gate (operator-initiated retrain)."""
        excursion = None
        if not force:
            excursion = self.drift_excursion(driftz)
            if excursion is None:
                return {"action": "noop", "reason": "no drift excursion"}
        if self.journal is not None:
            self.journal.event(
                "note", msg="fleet retrain trigger: "
                + json.dumps(excursion or {"forced": True}))
        parent = self.registry.current_version()
        challenger_path = self.retrain(fresh_x, fresh_y,
                                       num_boost_round=num_boost_round)
        verdict = self.validate(challenger_path, holdout_x, holdout_y)
        metadata = {
            "metric_name": verdict["metric_name"],
            "metric": verdict["challenger"],
            "incumbent_metric": verdict["incumbent"],
            "parent_version": parent,
            "train_rows": int(len(np.asarray(fresh_y))),
            "trigger": excursion or {"forced": True},
            "params": {k: v for k, v in self.train_params.items()
                       if isinstance(v, (str, int, float, bool))},
        }
        version = self.registry.publish(challenger_path,
                                        metadata=metadata)
        fields = dict(metric=float(verdict["challenger"]),
                      metric_name=str(verdict["metric_name"]))
        if verdict["incumbent"] is not None:
            fields["incumbent_metric"] = float(verdict["incumbent"])
        if verdict["better"]:
            self.registry.promote(
                version, reason=f"{verdict['metric_name']} "
                f"{verdict['challenger']} vs {verdict['incumbent']}",
                **fields)
            return {"action": "promote", "version": version,
                    "excursion": excursion, **verdict}
        self.registry.quarantine(
            version, reason=f"{verdict['metric_name']} "
            f"{verdict['challenger']} not better than "
            f"{verdict['incumbent']} (+{self.min_improvement})",
            **fields)
        return {"action": "reject", "version": version,
                "excursion": excursion, **verdict}
