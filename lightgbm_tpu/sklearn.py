"""Scikit-learn wrapper interface.

Reference: python-package/lightgbm/sklearn.py:27-622. Same estimator
surface (LGBMModel / LGBMRegressor / LGBMClassifier / LGBMRanker), same
parameter name mapping (sklearn names -> native names via the alias
table), same custom-objective wrapper translating
``(y_true, y_pred[, group]) -> (grad, hess)`` into the engine's
``fobj(preds, dataset)`` contract, and label encoding for classifiers.
"""

import inspect

import numpy as np

from .basic import Dataset, LightGBMError, is_str
from .engine import train

try:
    from sklearn.base import BaseEstimator, ClassifierMixin, RegressorMixin
    from sklearn.preprocessing import LabelEncoder
    SKLEARN_INSTALLED = True
    LGBMModelBase = BaseEstimator
    LGBMRegressorBase = RegressorMixin
    LGBMClassifierBase = ClassifierMixin
    LGBMLabelEncoder = LabelEncoder
except ImportError:  # pragma: no cover
    SKLEARN_INSTALLED = False
    LGBMModelBase = object
    LGBMRegressorBase = object
    LGBMClassifierBase = object
    LGBMLabelEncoder = None


def _objective_function_wrapper(func):
    """sklearn.py:27-84: wrap (y_true, y_pred[, group]) -> grad, hess into
    fobj(preds, dataset); weights multiply grad/hess."""

    argc = len(inspect.getfullargspec(func).args)

    def inner(preds, dataset):
        labels = dataset.get_label()
        if argc == 2:
            grad, hess = func(labels, preds)
        elif argc == 3:
            grad, hess = func(labels, preds, dataset.get_group())
        else:
            raise TypeError("Self-defined objective function should have 2 or "
                            "3 arguments, got %d" % argc)
        weight = dataset.get_weight()
        if weight is not None:
            grad = np.asarray(grad, dtype=np.float64)
            hess = np.asarray(hess, dtype=np.float64)
            if len(weight) == len(grad):
                grad = grad * weight
                hess = hess * weight
            else:
                num_data = len(weight)
                num_class = len(grad) // num_data
                if num_class * num_data != len(grad):
                    raise ValueError("Length of grad and hess should equal to "
                                     "num_class * num_data")
                w = np.tile(np.asarray(weight), num_class)
                grad = grad * w
                hess = hess * w
        return grad, hess
    return inner


def _eval_function_wrapper(func):
    """sklearn.py:86-131: wrap (y_true, y_pred[, weight[, group]]) ->
    (name, value, bigger_better) into feval(preds, dataset)."""

    argc = len(inspect.getfullargspec(func).args)

    def inner(preds, dataset):
        labels = dataset.get_label()
        if argc == 2:
            return func(labels, preds)
        if argc == 3:
            return func(labels, preds, dataset.get_weight())
        if argc == 4:
            return func(labels, preds, dataset.get_weight(), dataset.get_group())
        raise TypeError("Self-defined eval function should have 2, 3 or 4 "
                        "arguments, got %d" % argc)
    return inner


class LGBMModel(LGBMModelBase):
    """Base estimator (sklearn.py:133-455)."""

    def __init__(self, boosting_type="gbdt", num_leaves=31, max_depth=-1,
                 learning_rate=0.1, n_estimators=10, max_bin=255,
                 silent=True, objective="regression",
                 nthread=-1, min_split_gain=0, min_child_weight=5,
                 min_child_samples=10, subsample=1, subsample_freq=1,
                 colsample_bytree=1, reg_alpha=0, reg_lambda=0,
                 scale_pos_weight=1, is_unbalance=False, seed=0):
        if not SKLEARN_INSTALLED:
            raise LightGBMError("Scikit-learn is required for this module")
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.max_bin = max_bin
        self.silent = silent
        self.objective = objective
        self.nthread = nthread
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.scale_pos_weight = scale_pos_weight
        self.is_unbalance = is_unbalance
        self.seed = seed
        self._Booster = None
        self.best_iteration = -1
        self.evals_result_ = None
        if callable(self.objective):
            self.fobj = _objective_function_wrapper(self.objective)
        else:
            self.fobj = None

    def booster(self):
        if self._Booster is None:
            raise LightGBMError("Need to call fit beforehand")
        return self._Booster

    def get_params(self, deep=False):
        params = super().get_params(deep=deep)
        params.pop("silent", None)
        if params.get("nthread", 1) <= 0:
            params.pop("nthread", None)
        return params

    def fit(self, X, y,
            sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_sample_weight=None,
            eval_init_score=None, eval_group=None,
            eval_metric=None,
            early_stopping_rounds=None, verbose=True,
            feature_name=None, categorical_feature=None,
            other_params=None):
        """sklearn.py:265-395."""
        evals_result = {}
        params = self.get_params()
        params["verbose"] = 0 if self.silent else 1

        if self.fobj:
            params["objective"] = "none"
        else:
            params["objective"] = self.objective
        if other_params is not None:
            params.update(other_params)
        # sklearn's get_params returns the estimator's constructor kwargs;
        # drop the ones that are not native training parameters
        params.pop("n_estimators", None)

        if callable(eval_metric):
            feval = _eval_function_wrapper(eval_metric)
        elif is_str(eval_metric) or isinstance(eval_metric, list):
            feval = None
            params.update({"metric": eval_metric})
        else:
            feval = None

        def _construct_dataset(X, y, sample_weight, init_score, group, params):
            ret = Dataset(X, label=y, max_bin=self.max_bin,
                          weight=sample_weight, group=group, params=params)
            ret.set_init_score(init_score)
            return ret

        train_set = _construct_dataset(X, y, sample_weight, init_score,
                                       group, params)

        valid_sets = []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, valid_data in enumerate(eval_set):
                if valid_data[0] is X and valid_data[1] is y:
                    valid_set = train_set
                else:
                    def get_meta(collection, i):
                        if collection is None:
                            return None
                        if isinstance(collection, dict):
                            return collection.get(i, None)
                        return collection[i]
                    valid_set = _construct_dataset(
                        valid_data[0], valid_data[1],
                        get_meta(eval_sample_weight, i),
                        get_meta(eval_init_score, i),
                        get_meta(eval_group, i), params)
                valid_sets.append(valid_set)

        self._Booster = train(params, train_set, self.n_estimators,
                              valid_sets=valid_sets,
                              early_stopping_rounds=early_stopping_rounds,
                              evals_result=evals_result, fobj=self.fobj,
                              feval=feval, verbose_eval=verbose,
                              feature_name=feature_name,
                              categorical_feature=categorical_feature)

        if evals_result:
            self.evals_result_ = evals_result
        if early_stopping_rounds is not None:
            self.best_iteration = self._Booster.best_iteration
        return self

    def predict(self, data, raw_score=False, num_iteration=0):
        return self.booster().predict(data, raw_score=raw_score,
                                      num_iteration=num_iteration)

    def apply(self, X, num_iteration=0):
        """Predicted leaf index of every tree for each sample."""
        return self.booster().predict(X, pred_leaf=True,
                                      num_iteration=num_iteration)

    def evals_result(self):
        if self.evals_result_:
            return self.evals_result_
        raise LightGBMError("No results found.")

    def feature_importance(self):
        """Normalized split-count importances (sklearn.py:448-455)."""
        importance = self._Booster.feature_importance().astype(np.float32)
        return importance / importance.sum()

    @property
    def feature_importances_(self):
        """Raw split-count importances from the split ledger
        (reference sklearn surface; `booster().feature_importance(
        importance_type='gain')` for the gain variant)."""
        return self.booster().feature_importance(importance_type="split")


class LGBMRegressor(LGBMModel, LGBMRegressorBase):

    def fit(self, X, y,
            sample_weight=None, init_score=None,
            eval_set=None, eval_sample_weight=None,
            eval_init_score=None,
            eval_metric="l2",
            early_stopping_rounds=None, verbose=True,
            feature_name=None, categorical_feature=None,
            other_params=None):
        super().fit(X, y, sample_weight, init_score, None,
                    eval_set, eval_sample_weight, eval_init_score, None,
                    eval_metric, early_stopping_rounds, verbose,
                    feature_name, categorical_feature, other_params)
        return self


class LGBMClassifier(LGBMModel, LGBMClassifierBase):

    def __init__(self, boosting_type="gbdt", num_leaves=31, max_depth=-1,
                 learning_rate=0.1, n_estimators=10, max_bin=255,
                 silent=True, objective="binary",
                 nthread=-1, min_split_gain=0, min_child_weight=5,
                 min_child_samples=10, subsample=1, subsample_freq=1,
                 colsample_bytree=1, reg_alpha=0, reg_lambda=0,
                 scale_pos_weight=1, is_unbalance=False, seed=0):
        super().__init__(boosting_type, num_leaves, max_depth, learning_rate,
                         n_estimators, max_bin, silent, objective, nthread,
                         min_split_gain, min_child_weight, min_child_samples,
                         subsample, subsample_freq, colsample_bytree,
                         reg_alpha, reg_lambda, scale_pos_weight,
                         is_unbalance, seed)

    def fit(self, X, y,
            sample_weight=None, init_score=None,
            eval_set=None, eval_sample_weight=None,
            eval_init_score=None,
            eval_metric="binary_logloss",
            early_stopping_rounds=None, verbose=True,
            feature_name=None, categorical_feature=None,
            other_params=None):
        self.classes_ = np.unique(y)
        self.n_classes_ = len(self.classes_)
        other_params = {} if other_params is None else dict(other_params)
        if self.n_classes_ > 2:
            # the reference mutates self.objective here (sklearn.py:512),
            # which breaks refitting the same estimator on binary data;
            # pass the override through params instead
            if self.fobj is None:
                other_params["objective"] = "multiclass"
            other_params["num_class"] = self.n_classes_
            if eval_set is not None and eval_metric == "binary_logloss":
                eval_metric = "multi_logloss"

        self._le = LGBMLabelEncoder().fit(y)
        training_labels = self._le.transform(y)
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            eval_set = [(x[0], self._le.transform(x[1])) for x in eval_set]

        super().fit(X, training_labels, sample_weight, init_score, None,
                    eval_set, eval_sample_weight, eval_init_score, None,
                    eval_metric, early_stopping_rounds, verbose,
                    feature_name, categorical_feature, other_params)
        return self

    def predict(self, data, raw_score=False, num_iteration=0):
        class_probs = self.booster().predict(data, raw_score=raw_score,
                                             num_iteration=num_iteration)
        if len(class_probs.shape) > 1:
            column_indexes = np.argmax(class_probs, axis=1)
        else:
            column_indexes = np.repeat(0, class_probs.shape[0])
            column_indexes[class_probs > 0.5] = 1
        return self._le.inverse_transform(column_indexes)

    def predict_proba(self, data, raw_score=False, num_iteration=0):
        class_probs = self.booster().predict(data, raw_score=raw_score,
                                             num_iteration=num_iteration)
        if self.n_classes_ > 2:
            return class_probs
        classone_probs = class_probs
        classzero_probs = 1.0 - classone_probs
        return np.vstack((classzero_probs, classone_probs)).transpose()


class LGBMRanker(LGBMModel):

    def __init__(self, boosting_type="gbdt", num_leaves=31, max_depth=-1,
                 learning_rate=0.1, n_estimators=10, max_bin=255,
                 silent=True, objective="lambdarank",
                 nthread=-1, min_split_gain=0, min_child_weight=5,
                 min_child_samples=10, subsample=1, subsample_freq=1,
                 colsample_bytree=1, reg_alpha=0, reg_lambda=0,
                 scale_pos_weight=1, is_unbalance=False, seed=0):
        super().__init__(boosting_type, num_leaves, max_depth, learning_rate,
                         n_estimators, max_bin, silent, objective, nthread,
                         min_split_gain, min_child_weight, min_child_samples,
                         subsample, subsample_freq, colsample_bytree,
                         reg_alpha, reg_lambda, scale_pos_weight,
                         is_unbalance, seed)

    def fit(self, X, y,
            sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_sample_weight=None,
            eval_init_score=None, eval_group=None,
            eval_metric="ndcg", eval_at=1,
            early_stopping_rounds=None, verbose=True,
            feature_name=None, categorical_feature=None,
            other_params=None):
        """sklearn.py:570-622. `eval_at`: NDCG evaluation positions."""
        if group is None:
            raise ValueError("Should set group for ranking task")
        if eval_set is not None:
            if eval_group is None:
                raise ValueError("Eval_group cannot be None when eval_set "
                                 "is not None")
            if len(eval_group) != len(eval_set):
                raise ValueError("Length of eval_group should equal to "
                                 "eval_set")
            for inner_group in (eval_group.values()
                                if isinstance(eval_group, dict) else eval_group):
                if inner_group is None:
                    raise ValueError("Should set group for all eval dataset "
                                     "for ranking task")
        if eval_at is not None:
            other_params = {} if other_params is None else other_params
            if isinstance(eval_at, int):
                eval_at = [eval_at]
            other_params["ndcg_eval_at"] = list(eval_at)
        super().fit(X, y, sample_weight, init_score, group,
                    eval_set, eval_sample_weight, eval_init_score, eval_group,
                    eval_metric, early_stopping_rounds, verbose,
                    feature_name, categorical_feature, other_params)
        return self
