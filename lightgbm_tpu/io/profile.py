"""Dataset profile: the training-time baseline feature distribution.

Captured once at binning (the only moment the full dataset streams past
the bin mappers anyway) and persisted with every durable form of the
dataset and model:

- attached to the `CoreDataset` as ``ds.profile``;
- ridden through the binary dataset cache and the block-store sidecar
  (io/dataset.py encode/decode_dataset_sidecar — ONE encoder for both
  binary forms, so the profile cannot drift between them);
- written as ``<model>.profile.json`` next to every saved model file
  (models/gbdt.py save_model_to_file), which is the artifact the
  serving-side drift monitor loads (serving/drift.py): it carries the
  bin BOUNDS as well as the occupancy counts, so a serving process can
  bin incoming rows identically to training without the dataset.

Per used feature the profile records: name, real column index, bin
type, the mapper's bin bounds (numeric upper bounds / categorical ids),
the full-dataset bin-occupancy histogram, and a missing (NaN) count.
Training ingestion collapses NaN to 0.0 BEFORE binning (io/parser.py;
bin.h NaN->zero-bin), so on the standard load paths missing mass lands
in the zero bin and the `missing` field stays 0 — the serving-side
monitor bins NaN through the same rule, which is what keeps the
training/serving occupancy histograms comparable regardless of how
missing values arrive. The zero-bin occupancy (`zero_rate`) is
therefore the zero-OR-missing rate on both sides.

`profile_bins` (docs/Parameters.md) caps the RESOLUTION drift
comparisons run at: `group_counts` folds a mapper's bins into at most
that many groups (contiguous, even in bin space) before PSI — both the
baseline and the serving-side rolling histogram fold the same way, so
the comparison stays aligned while small samples stop being noisy at
255-bin granularity.

jax-free; numpy + stdlib json only (the serving image's floor).
"""

import json
import os

import numpy as np

from ..utils.log import Log
from .bin_mapper import NUMERICAL, BinMapper

PROFILE_VERSION = 1
PROFILE_SUFFIX = ".profile.json"
# PSI's classic formulation uses ~10 quantile buckets; finer groups
# make small serving samples spuriously noisy (an empty group reads as
# drift), coarser ones hide real shifts
DEFAULT_PROFILE_BINS = 10


def profiling_enabled():
    """Capture kill-switch: LIGHTGBM_TPU_DATASET_PROFILE=0 skips the
    occupancy pass (the profile then simply does not exist; every
    consumer treats that as 'no baseline')."""
    return os.environ.get("LIGHTGBM_TPU_DATASET_PROFILE", "") != "0"


def group_counts(counts, profile_bins):
    """Fold a per-bin count vector into at most `profile_bins`
    contiguous groups (group of bin i = i * G // B — even in bin
    space). <=0 or enough room returns the counts unchanged."""
    counts = np.asarray(counts, np.int64)
    g = int(profile_bins)
    if g <= 0 or len(counts) <= g:
        return counts
    idx = (np.arange(len(counts), dtype=np.int64) * g) // len(counts)
    out = np.zeros(g, np.int64)
    np.add.at(out, idx, counts)
    return out


class DatasetProfile:
    """One dataset's per-feature baseline distribution (module
    docstring). `features` is a list of dicts with keys: name, column,
    bin_type, num_bin, upper_bounds (numeric) / categories
    (categorical), counts, missing."""

    def __init__(self, num_rows, features):
        self.num_rows = int(num_rows)
        self.features = list(features)

    # ------------------------------------------------------------ build
    @classmethod
    def from_parts(cls, mappers, real_idx, feature_names, counts_list,
                   num_rows, missing=None):
        """Assemble from a loader's pieces: the bin mappers, the
        used->total map, per-used-feature occupancy counts, and the
        optional per-used-feature NaN counts."""
        features = []
        for u, m in enumerate(mappers):
            col = int(real_idx[u])
            name = (str(feature_names[col])
                    if col < len(feature_names) and feature_names[col]
                    else f"Column_{col}")
            rec = {
                "name": name,
                "column": col,
                "bin_type": int(m.bin_type),
                "num_bin": int(m.num_bin),
                "counts": np.asarray(counts_list[u], np.int64),
                "missing": int(missing[u]) if missing is not None else 0,
            }
            if m.bin_type == NUMERICAL:
                rec["upper_bounds"] = np.asarray(m.bin_upper_bound,
                                                 np.float64)
            else:
                rec["categories"] = np.asarray(m.bin_2_categorical,
                                               np.int64)
            features.append(rec)
        return cls(num_rows, features)

    @classmethod
    def from_dataset(cls, ds, missing=None):
        """Occupancy pass over a constructed dataset: one bincount per
        used feature. Handles the three storage layouts: a plain
        (F, N) matrix, a bundled stored matrix (slots decode through
        the bundle plan), and an out-of-core block store (streamed
        block by block — never materializes the matrix)."""
        counts = [np.zeros(m.num_bin, np.int64) for m in ds.bin_mappers]
        plan = ds.bundle_plan

        def accumulate(stored):
            slot_cache = {}     # bundled slots decode ONCE per slot,
            for u in range(len(ds.bin_mappers)):   # not per member
                nb = len(counts[u])
                if plan is None:
                    col = stored[u].astype(np.int64, copy=False)
                else:
                    slot = int(plan.feat_slot[u])
                    sc = slot_cache.get(slot)
                    if sc is None:
                        sc = stored[slot].astype(np.int64, copy=False)
                        slot_cache[slot] = sc
                    off = int(plan.feat_offset[u])
                    col = np.where((sc > off) & (sc <= off + nb - 1),
                                   sc - off, 0)
                counts[u] += np.bincount(np.minimum(col, nb - 1),
                                         minlength=nb)[:nb]

        if ds.bins is not None:
            accumulate(ds.bins)
        else:
            store = getattr(ds, "block_store", None)
            if store is None:
                return None
            for i in range(store.num_blocks):
                accumulate(np.asarray(store.read_block(i)))
        return cls.from_parts(ds.bin_mappers, ds.real_feature_idx,
                              ds.feature_names, counts, ds.num_data,
                              missing=missing)

    # --------------------------------------------------------- accessors
    @property
    def num_features(self):
        return len(self.features)

    def zero_bin(self, u):
        """The bin the value 0.0 (and therefore NaN) lands in."""
        rec = self.features[u]
        if rec["bin_type"] == NUMERICAL:
            return int(np.searchsorted(rec["upper_bounds"], 0.0,
                                       side="left"))
        cats = rec["categories"]
        hit = np.nonzero(cats == 0)[0]
        return int(hit[0]) if len(hit) else 0

    def zero_rate(self, u):
        rec = self.features[u]
        total = int(rec["counts"].sum())
        if total <= 0:
            return 0.0
        return float(rec["counts"][self.zero_bin(u)]) / total

    def missing_rate(self, u):
        if self.num_rows <= 0:
            return 0.0
        return float(self.features[u]["missing"]) / self.num_rows

    def mapper(self, u):
        """Rebuild the feature's BinMapper (value->bin for the serving
        drift monitor; identical boundaries by construction)."""
        rec = self.features[u]
        m = BinMapper()
        m.num_bin = int(rec["num_bin"])
        m.is_trivial = m.num_bin <= 1
        m.bin_type = int(rec["bin_type"])
        if m.bin_type == NUMERICAL:
            m.bin_upper_bound = np.asarray(rec["upper_bounds"],
                                           np.float64)
        else:
            m.bin_2_categorical = np.asarray(rec["categories"], np.int64)
        return m

    # ----------------------------------------------------- serialization
    def to_json_dict(self):
        features = []
        for rec in self.features:
            out = {"name": rec["name"], "column": int(rec["column"]),
                   "bin_type": int(rec["bin_type"]),
                   "num_bin": int(rec["num_bin"]),
                   "counts": [int(c) for c in rec["counts"]],
                   "missing": int(rec["missing"])}
            if rec["bin_type"] == NUMERICAL:
                # inf is not JSON: the last upper bound is always +inf
                # (bin_mapper.find_bin), encode it as null
                out["upper_bounds"] = [
                    None if not np.isfinite(b) else float(b)
                    for b in rec["upper_bounds"]]
            else:
                out["categories"] = [int(c) for c in rec["categories"]]
            features.append(out)
        return {"version": PROFILE_VERSION, "num_rows": self.num_rows,
                "features": features}

    @classmethod
    def from_json_dict(cls, d):
        if int(d.get("version", 0)) > PROFILE_VERSION:
            raise ValueError(
                f"profile version {d.get('version')} is newer than this "
                f"build reads ({PROFILE_VERSION})")
        features = []
        for rec in d.get("features", []):
            out = {"name": str(rec["name"]), "column": int(rec["column"]),
                   "bin_type": int(rec["bin_type"]),
                   "num_bin": int(rec["num_bin"]),
                   "counts": np.asarray(rec["counts"], np.int64),
                   "missing": int(rec.get("missing", 0))}
            if out["bin_type"] == NUMERICAL:
                out["upper_bounds"] = np.asarray(
                    [np.inf if b is None else float(b)
                     for b in rec["upper_bounds"]], np.float64)
            else:
                out["categories"] = np.asarray(rec["categories"],
                                               np.int64)
            features.append(out)
        return cls(int(d.get("num_rows", 0)), features)

    def save(self, path):
        """Atomic JSON write (a kill mid-save must never leave a
        truncated profile where a valid one stood)."""
        from ..utils.checkpoint import atomic_write_text
        atomic_write_text(os.fspath(path),
                          json.dumps(self.to_json_dict(),
                                     separators=(",", ":")) + "\n")

    @classmethod
    def load(cls, path):
        with open(os.fspath(path), "r", encoding="utf-8") as f:
            return cls.from_json_dict(json.load(f))

    # ------------------------------------------------- sidecar npz form
    # The binary cache and block-store sidecar persist the profile as a
    # few flat arrays next to the mappers (which already carry the
    # bounds); decode rebuilds the full profile from both.

    def encode_sidecar(self, arrays):
        b_max = max((len(r["counts"]) for r in self.features), default=1)
        counts = np.zeros((len(self.features), b_max), np.int64)
        for u, rec in enumerate(self.features):
            counts[u, :len(rec["counts"])] = rec["counts"]
        arrays["profile_counts"] = counts
        arrays["profile_missing"] = np.asarray(
            [rec["missing"] for rec in self.features], np.int64)
        arrays["profile_num_rows"] = np.asarray(self.num_rows)
        return arrays

    @classmethod
    def decode_sidecar(cls, z, ds):
        """Rebuild from a decoded dataset sidecar (mappers/maps/names
        already populated on `ds`). Returns None when the archive
        predates profiles — older caches stay loadable."""
        if "profile_num_rows" not in getattr(z, "files", ()):
            return None
        try:
            counts = np.asarray(z["profile_counts"], np.int64)
            missing = np.asarray(z["profile_missing"], np.int64)
            num_rows = int(z["profile_num_rows"])
            if counts.shape[0] != len(ds.bin_mappers):
                raise ValueError(
                    f"profile covers {counts.shape[0]} features, dataset "
                    f"has {len(ds.bin_mappers)}")
            counts_list = [counts[u, :m.num_bin]
                           for u, m in enumerate(ds.bin_mappers)]
            return cls.from_parts(ds.bin_mappers, ds.real_feature_idx,
                                  ds.feature_names, counts_list, num_rows,
                                  missing=missing)
        except (KeyError, ValueError, IndexError) as e:
            Log.warning("ignoring unusable dataset profile in cache: %s",
                        e)
            return None


def count_missing(feats, real_idx):
    """Per-used-feature NaN counts of a raw (N, F) feature matrix.
    Standard ingestion collapses NaN to 0.0 before this point
    (io/parser.py), so the counts are 0 there; paths that preserve raw
    NaN (future keep-NaN ingestion) report real counts through the
    same plumbing."""
    real_idx = np.asarray(real_idx, np.int64)
    return np.asarray([int(np.isnan(feats[:, j]).sum()) for j in real_idx],
                      np.int64)


def model_profile_path(model_path):
    return os.fspath(model_path) + PROFILE_SUFFIX
