"""Streaming (two-round) text loading: O(block) host memory.

Reference: include/LightGBM/utils/pipeline_reader.h:18-70 (block reads),
include/LightGBM/utils/text_reader.h:21-311 (count / sample / filtered
reads), and the two-round path of src/io/dataset_loader.cpp:505-610:
round one samples rows to find bin boundaries, round two re-reads the
file pushing binned values directly into feature storage, so the full
float matrix never exists in memory.

Host-side design: pandas' C tokenizer already does double-buffered block
reads internally (`chunksize=`), so the pipeline reader collapses to a
block iterator; the value-add here is the two-round protocol itself
(sample pass -> mapper construction -> binning pass) with peak memory
O(block_rows x cols) + the uint8 bin matrix, instead of the O(N x cols)
float64 matrix of the in-memory path.
"""

import numpy as np

from .parser import libsvm_pairs, NA_VALUES

DEFAULT_BLOCK_ROWS = 1 << 16


def count_rows(path, has_header):
    """Non-empty line count only — no tokenization (text_reader.h
    CountLine). For callers that don't need scan_file's LibSVM
    max-feature-id discovery pass."""
    n = 0
    with open(path, "r") as f:
        if has_header:
            next(f, None)
        for line in f:
            if line.strip():
                n += 1
    return n


def scan_file(path, fmt, has_header):
    """First pass: row count + (names, num_cols). For LibSVM also
    discovers the column count (max index + 1) — text_reader.h CountLine
    plus the reference's max-idx scan."""
    if fmt == "libsvm":
        n = 0
        max_idx = -1
        with open(path, "r") as f:
            if has_header:
                next(f, None)
            for line in f:
                line = line.strip()
                if not line:
                    continue
                n += 1
                for idx, _ in libsvm_pairs(line.split()[1:]):
                    if idx > max_idx:
                        max_idx = idx
        # +1 for the label column so num_cols matches the dense formats
        return n, None, max_idx + 2
    names = None
    with open(path, "r") as f:
        first = f.readline().rstrip("\r\n")
        sep = "," if fmt == "csv" else "\t"
        cols = first.split(sep)
        num_cols = len(cols)
        if has_header:
            names = [str(c) for c in cols]
            n = 0
        else:
            n = 1 if first.strip() else 0
        for line in f:
            if line.strip():
                n += 1
    return n, names, num_cols


def iter_blocks(path, fmt, has_header, num_cols, block_rows=DEFAULT_BLOCK_ROWS):
    """Second/third pass: yield (row_start, float64 (b, num_cols) block)
    with NaNs zeroed, matching parse_text_file's dense semantics."""
    if fmt == "libsvm":
        buf = np.zeros((block_rows, num_cols), dtype=np.float64)
        fill = 0
        start = 0
        with open(path, "r") as f:
            if has_header:
                next(f, None)
            for line in f:
                line = line.strip()
                if not line:
                    continue
                parts = line.split()
                buf[fill, 0] = float(parts[0])
                for idx, val in libsvm_pairs(parts[1:]):
                    buf[fill, idx + 1] = val
                fill += 1
                if fill == block_rows:
                    yield start, buf[:fill]
                    start += fill
                    fill = 0
                    buf = np.zeros((block_rows, num_cols), dtype=np.float64)
        if fill:
            yield start, buf[:fill]
        return

    import pandas as pd
    sep = "," if fmt == "csv" else "\t"
    start = 0
    for chunk in pd.read_csv(path, sep=sep, header=0 if has_header else None,
                             dtype=np.float64, na_values=NA_VALUES,
                             chunksize=block_rows):
        block = np.nan_to_num(chunk.to_numpy(dtype=np.float64), nan=0.0)
        yield start, block
        start += len(block)


def prefetch_blocks(block_iter, depth=2):
    """Double-buffered block pipeline (pipeline_reader.h:18-70): a
    producer thread runs the parse iterator (pandas' C tokenizer and
    the numpy conversions release the GIL) while the consumer bins the
    previous block; the bounded queue caps peak memory at `depth`
    blocks and provides the backpressure the reference gets from its
    two-buffer swap."""
    import queue
    import threading

    q = queue.Queue(maxsize=depth)
    end = object()
    stop = threading.Event()
    err = []

    def produce():
        try:
            for item in block_iter:
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return
        except BaseException as e:  # surface parse errors in the consumer
            err.append(e)
        finally:
            while not stop.is_set():
                try:
                    q.put(end, timeout=0.1)
                    break
                except queue.Full:
                    continue

    t = threading.Thread(target=produce, daemon=True, name="block-prefetch")
    t.start()
    try:
        while True:
            item = q.get()
            if item is end:
                break
            yield item
    finally:
        # early consumer exit (rank filtering breaks mid-file): release
        # the producer so the file handle closes promptly
        stop.set()
        while True:
            try:
                q.get_nowait()
            except queue.Empty:
                break
        t.join(timeout=10)
    if err:
        raise err[0]


def iter_sparse_blocks(path, has_header, block_rows=DEFAULT_BLOCK_ROWS):
    """LibSVM second-pass iterator in O(block nnz) memory: yields
    (row_start, labels (b,) f64, rows (nnz,) i64 block-local,
    cols (nnz,) i64 feature ids, vals (nnz,) f64). The dense-block
    iterator materializes (b, num_cols) floats — at news20-like widths
    that is GBs per block; this is the O(nnz) route the reference's
    sparse row parser feeds (src/io/parser.hpp LibSVM + sparse_bin.hpp
    push path)."""
    labels = []
    rows, cols, vals = [], [], []
    start = 0
    fill = 0
    with open(path, "r") as f:
        if has_header:
            next(f, None)
        for line in f:
            line = line.strip()
            if not line:
                continue
            parts = line.split()
            labels.append(float(parts[0]))
            for idx, val in libsvm_pairs(parts[1:]):
                rows.append(fill)
                cols.append(idx)
                vals.append(val)
            fill += 1
            if fill == block_rows:
                yield (start, np.asarray(labels, dtype=np.float64),
                       np.asarray(rows, dtype=np.int64),
                       np.asarray(cols, dtype=np.int64),
                       np.asarray(vals, dtype=np.float64))
                start += fill
                fill = 0
                labels, rows, cols, vals = [], [], [], []
    if fill:
        yield (start, np.asarray(labels, dtype=np.float64),
               np.asarray(rows, dtype=np.int64),
               np.asarray(cols, dtype=np.int64),
               np.asarray(vals, dtype=np.float64))


def collect_sample_csc(path, has_header, num_feats, sample_idx,
                       block_rows=DEFAULT_BLOCK_ROWS):
    """Round one for wide LibSVM: gather the sampled rows as CSC
    (colptr, indices-into-sample, vals) + labels, in O(sample nnz)
    memory — the dense collect_sample_rows would need
    (sample, num_cols) floats."""
    sample_idx = np.asarray(sample_idx, dtype=np.int64)
    labels = np.zeros(len(sample_idx), dtype=np.float64)
    parts_c, parts_r, parts_v = [], [], []
    for start, lab, rows, cols, vals in iter_sparse_blocks(
            path, has_header, block_rows):
        lo = np.searchsorted(sample_idx, start)
        hi = np.searchsorted(sample_idx, start + len(lab))
        if hi <= lo:
            continue
        want = sample_idx[lo:hi] - start          # block-local row ids
        labels[lo:hi] = lab[want]
        # map block rows -> sample positions; -1 = not sampled
        pos = np.full(len(lab), -1, dtype=np.int64)
        pos[want] = np.arange(lo, hi)
        keep = pos[rows] >= 0
        parts_r.append(pos[rows[keep]])
        parts_c.append(cols[keep])
        parts_v.append(vals[keep])
    rows = (np.concatenate(parts_r) if parts_r
            else np.zeros(0, dtype=np.int64))
    cols = (np.concatenate(parts_c) if parts_c
            else np.zeros(0, dtype=np.int64))
    vals = (np.concatenate(parts_v) if parts_v
            else np.zeros(0, dtype=np.float64))
    order = np.argsort(cols, kind="stable")
    counts = np.bincount(cols, minlength=num_feats)
    colptr = np.concatenate([[0], np.cumsum(counts)])
    return labels, colptr, rows[order], vals[order]


def collect_sample_rows(path, fmt, has_header, num_cols, sample_idx,
                        block_rows=DEFAULT_BLOCK_ROWS):
    """Round one: gather the (ascending) sampled row indices in one
    streaming pass (text_reader.h SampleFromFile)."""
    sample_idx = np.asarray(sample_idx, dtype=np.int64)
    out = np.empty((len(sample_idx), num_cols), dtype=np.float64)
    for start, block in prefetch_blocks(
            iter_blocks(path, fmt, has_header, num_cols, block_rows)):
        lo = np.searchsorted(sample_idx, start)
        hi = np.searchsorted(sample_idx, start + len(block))
        if hi > lo:
            out[lo:hi] = block[sample_idx[lo:hi] - start]
    return out
