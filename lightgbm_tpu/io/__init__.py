from .bin_mapper import BinMapper
from .metadata import Metadata
from .dataset import CoreDataset, DatasetLoader
from .parser import detect_format, parse_text_file

__all__ = ["BinMapper", "Metadata", "CoreDataset", "DatasetLoader",
           "detect_format", "parse_text_file"]
