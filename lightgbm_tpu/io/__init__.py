from .bin_mapper import BinMapper
from .metadata import Metadata
from .dataset import CoreDataset, DatasetLoader
from .parser import detect_format, iter_text_file_chunks, parse_text_file

__all__ = ["BinMapper", "Metadata", "CoreDataset", "DatasetLoader",
           "detect_format", "iter_text_file_chunks", "parse_text_file"]
