"""Text parsers: CSV / TSV / LibSVM with format auto-detection.

Reference: src/io/parser.cpp:72-144 (format sniffing from the first two
lines), src/io/parser.hpp:15-112 (per-line parsing; values with
|v| <= 1e-10 are treated as zero / not emitted).

The TPU build parses on the host into dense float32 column blocks
(pandas' C tokenizer for CSV/TSV, a numpy pass for LibSVM) — the
reference's per-thread (col,value) pair pipeline is a CPU-cache design
that has no advantage here because the very next step is vectorized
binning over whole columns.
"""

import numpy as np

from ..utils.log import Log

ZERO_THRESHOLD = 1e-10
NA_VALUES = ["na", "NA", "nan", "NaN", "null"]


def libsvm_pairs(tokens):
    """Parse `idx:val` tokens, skipping malformed ones (empty or
    non-numeric index — e.g. ranking-style `qid:3` — or an unparsable
    value) — shared by the in-memory and streaming loaders so both
    paths treat the same line identically."""
    out = []
    for tok in tokens:
        c = tok.find(":")
        if c <= 0:
            continue
        try:
            idx, val = int(tok[:c]), float(tok[c + 1:])
        except ValueError:
            continue  # skip, matching the documented rule
        if idx < 0:
            continue  # a negative index would write the label column
        out.append((idx, val))
    return out


def _first_lines(path, n=2):
    lines = []
    with open(path, "r") as f:
        for line in f:
            line = line.rstrip("\r\n")
            if line:
                lines.append(line)
            if len(lines) >= n:
                break
    return lines


def detect_format(path) -> str:
    """Sniff CSV / TSV / LibSVM from the first two lines (parser.cpp:72-144)."""
    lines = _first_lines(path, 2)
    if not lines:
        Log.fatal("Data file %s is empty", str(path))
    probe = lines[-1]  # prefer the second line (first may be a header)
    num_colon = probe.count(":")
    num_tab = probe.count("\t")
    num_comma = probe.count(",")
    if num_colon > 0 and num_tab == 0 and num_comma == 0:
        return "libsvm"
    if num_tab > 0:
        return "tsv"
    if num_comma > 0:
        return "csv"
    if num_colon > 0:
        return "libsvm"
    # single column fallback
    return "tsv"


def _parse_libsvm(path, has_header):
    """LibSVM: `label idx:val idx:val ...`; indices are used as-is
    (the reference's LibSVMParser does not shift them, parser.hpp:77-112)."""
    labels = []
    rows = []
    max_idx = -1
    with open(path, "r") as f:
        if has_header:
            next(f, None)
        for line in f:
            line = line.strip()
            if not line:
                continue
            parts = line.split()
            labels.append(float(parts[0]))
            pairs = libsvm_pairs(parts[1:])
            for i, _ in pairs:
                if i > max_idx:
                    max_idx = i
            rows.append(pairs)
    n = len(rows)
    mat = np.zeros((n, max_idx + 1), dtype=np.float64)
    for r, pairs in enumerate(rows):
        for i, v in pairs:
            mat[r, i] = v
    return np.asarray(labels, dtype=np.float32), mat, None


def parse_text_file(path, has_header=False, label_column=""):
    """Parse a data file into
    (label, features (N, C-1) float32, header names, format, label_idx).

    label/weight/group column resolution follows the reference
    (`DatasetLoader::SetHeader`, dataset_loader.cpp:57-160): label defaults
    to column 0; `name:xxx` selects by header name; plain integers are
    file-column indices. Feature indices do NOT count the label column.
    """
    import pandas as pd

    fmt = detect_format(path)
    if fmt == "libsvm":
        label, mat, names = _parse_libsvm(path, has_header)
        return label, mat, names, fmt, 0

    sep = "," if fmt == "csv" else "\t"
    df = pd.read_csv(path, sep=sep, header=0 if has_header else None,
                     dtype=np.float64, na_values=NA_VALUES)
    names = [str(c) for c in df.columns] if has_header else None
    data = df.to_numpy(dtype=np.float64)
    data = np.nan_to_num(data, nan=0.0)

    label_idx = 0
    if label_column != "":
        if str(label_column).startswith("name:"):
            want = str(label_column)[5:]
            if names is None or want not in names:
                Log.fatal("Could not find label column %s in data file", want)
            label_idx = names.index(want)
        else:
            label_idx = int(label_column)

    label = data[:, label_idx].astype(np.float32)
    # keep float64: the reference parses and bins in double (parser.hpp),
    # and a float32 round-trip perturbs bin boundaries in the last digit
    feats = np.delete(data, label_idx, axis=1)
    feat_names = None
    if names is not None:
        feat_names = [n for i, n in enumerate(names) if i != label_idx]
    return label, feats, feat_names, fmt, label_idx
