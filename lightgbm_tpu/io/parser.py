"""Text parsers: CSV / TSV / LibSVM with format auto-detection.

Reference: src/io/parser.cpp:72-144 (format sniffing from the first two
lines), src/io/parser.hpp:15-112 (per-line parsing; values with
|v| <= 1e-10 are treated as zero / not emitted).

The TPU build parses on the host into dense float32 column blocks
(pandas' C tokenizer for CSV/TSV, a numpy pass for LibSVM) — the
reference's per-thread (col,value) pair pipeline is a CPU-cache design
that has no advantage here because the very next step is vectorized
binning over whole columns.
"""

import numpy as np

from ..utils.log import Log

ZERO_THRESHOLD = 1e-10
NA_VALUES = ["na", "NA", "nan", "NaN", "null"]


def libsvm_pairs(tokens):
    """Parse `idx:val` tokens, skipping malformed ones (empty or
    non-numeric index — e.g. ranking-style `qid:3` — or an unparsable
    value) — shared by the in-memory and streaming loaders so both
    paths treat the same line identically."""
    out = []
    for tok in tokens:
        c = tok.find(":")
        if c <= 0:
            continue
        try:
            idx, val = int(tok[:c]), float(tok[c + 1:])
        except ValueError:
            continue  # skip, matching the documented rule
        if idx < 0:
            continue  # a negative index would write the label column
        out.append((idx, val))
    return out


def _first_lines(path, n=2):
    lines = []
    with open(path, "r") as f:
        for line in f:
            line = line.rstrip("\r\n")
            if line:
                lines.append(line)
            if len(lines) >= n:
                break
    return lines


def detect_format(path) -> str:
    """Sniff CSV / TSV / LibSVM from the first two lines (parser.cpp:72-144)."""
    lines = _first_lines(path, 2)
    if not lines:
        Log.fatal("Data file %s is empty", str(path))
    probe = lines[-1]  # prefer the second line (first may be a header)
    num_colon = probe.count(":")
    num_tab = probe.count("\t")
    num_comma = probe.count(",")
    if num_colon > 0 and num_tab == 0 and num_comma == 0:
        return "libsvm"
    if num_tab > 0:
        return "tsv"
    if num_comma > 0:
        return "csv"
    if num_colon > 0:
        return "libsvm"
    # single column fallback
    return "tsv"


def _densify_libsvm(labels, rows, max_idx):
    """(label f32, dense (N, max_idx+1) f64) from parsed LibSVM pairs —
    shared by the one-shot and streaming paths so row assembly cannot
    diverge."""
    mat = np.zeros((len(rows), max_idx + 1), dtype=np.float64)
    for r, pairs in enumerate(rows):
        for i, v in pairs:
            mat[r, i] = v
    return np.asarray(labels, dtype=np.float32), mat


def _parse_libsvm(path, has_header):
    """LibSVM: `label idx:val idx:val ...`; indices are used as-is
    (the reference's LibSVMParser does not shift them, parser.hpp:77-112)."""
    labels = []
    rows = []
    max_idx = -1
    with open(path, "r") as f:
        if has_header:
            next(f, None)
        for line in f:
            line = line.strip()
            if not line:
                continue
            parts = line.split()
            labels.append(float(parts[0]))
            pairs = libsvm_pairs(parts[1:])
            for i, _ in pairs:
                if i > max_idx:
                    max_idx = i
            rows.append(pairs)
    label, mat = _densify_libsvm(labels, rows, max_idx)
    return label, mat, None


def _first_offender(path, sep, has_header, ncols):
    """Exact (line number, description) of the first malformed line —
    a raw-text second pass, run only when the tolerant parse already
    found something to diagnose. DataFrame row indices cannot name the
    line (structurally-skipped lines shift them), so re-scan the file
    itself. Quoted fields with embedded separators can mis-split here;
    the pass only serves the diagnostic, never the data."""
    try:
        from pandas._libs.parsers import STR_NA_VALUES
        na = set(STR_NA_VALUES) | set(NA_VALUES)
    except Exception:  # pandas internals drifted: use our own list
        na = set(NA_VALUES) | {"", "N/A", "NULL", "None", "n/a", "<NA>"}
    with open(path, "r") as f:
        if has_header:
            next(f, None)
        for lineno, raw in enumerate(f, 2 if has_header else 1):
            line = raw.rstrip("\r\n")
            if not line:
                continue  # pandas skips blank lines
            fields = line.split(sep)
            if len(fields) != ncols:
                return (f"line {lineno}: wrong field count "
                        f"({len(fields)} != {ncols}): {line!r}")
            for col, token in enumerate(fields):
                token = token.strip()
                if token in na:
                    continue
                try:
                    float(token)
                except ValueError:
                    return (f"line {lineno}: column {col} value "
                            f"{token!r}")
    return "not re-locatable in a raw scan (quoting?)"


def _coerce_quarantine(df):
    """Quarantine rule shared by the one-shot and streaming CSV/TSV
    parsers: a bad CELL is one coerced to NaN where the raw text was
    neither empty nor a recognized NA marker (those legitimately parse
    to NaN and become 0.0 downstream, same as the strict path).
    Returns (numeric DataFrame of the GOOD rows, n bad rows dropped)."""
    import pandas as pd

    numeric = df.apply(pd.to_numeric, errors="coerce")
    bad_cells = numeric.isna().to_numpy() & ~df.isna().to_numpy()
    bad_rows = bad_cells.any(axis=1)
    return numeric[~bad_rows], int(bad_rows.sum())


def _read_csv_quarantine(path, sep, has_header, max_bad_rows):
    """Tolerant CSV/TSV fallback: rows with unparsable cells (and
    structurally bad lines) are QUARANTINED — counted, diagnosed, and
    dropped — instead of aborting the load, as long as at most
    `max_bad_rows` rows are bad. Mirrors the LibSVM path, which already
    skips malformed tokens per its documented rule (libsvm_pairs).

    Returns (DataFrame of good rows as float64, n_quarantined). The
    first offender is reported with its exact line number and content
    so a producer-side bug is diagnosable from the training log alone."""
    import pandas as pd

    bad_lines = []  # structural offenders (wrong field count)

    def on_bad(fields):
        bad_lines.append("\t".join(str(f) for f in fields))
        return None  # skip

    df = pd.read_csv(path, sep=sep, header=0 if has_header else None,
                     dtype=str, na_values=NA_VALUES, engine="python",
                     on_bad_lines=on_bad)
    numeric, n_bad_cells = _coerce_quarantine(df)
    n_bad = n_bad_cells + len(bad_lines)
    if n_bad:
        first = _first_offender(path, sep, has_header, df.shape[1])
        if n_bad > max_bad_rows:
            Log.fatal("%d malformed rows in %s exceed max_bad_rows=%d; "
                      "first offender: %s", n_bad, str(path),
                      max_bad_rows, first)
        Log.warning("quarantined %d malformed row(s) in %s "
                    "(max_bad_rows=%d); first offender: %s",
                    n_bad, str(path), max_bad_rows, first)
    return numeric, n_bad


def _resolve_label_idx(label_column, names, path):
    """Reference label-column resolution (`DatasetLoader::SetHeader`):
    default column 0, `name:xxx` selects by header name, plain integers
    are file-column indices."""
    if label_column == "":
        return 0
    if str(label_column).startswith("name:"):
        want = str(label_column)[5:]
        if names is None or want not in names:
            Log.fatal("Could not find label column %s in data file", want)
        return names.index(want)
    return int(label_column)


def iter_text_file_chunks(path, chunk_rows, has_header=False,
                          label_column="", max_bad_rows=0,
                          keep_nan=False):
    """Stream a data file as (label, features) float chunks of at most
    `chunk_rows` rows — the bounded-memory twin of parse_text_file
    (identical per-row semantics: same format sniffing, NA handling,
    label-column resolution and quarantine rule), used by the predict
    path so serving-scale scoring files never materialize whole
    (application.py Predictor.predict_file).

    `keep_nan=True` preserves NA cells as NaN instead of the training
    ingestion's NaN->0.0 collapse (binning needs finite inputs), so
    file prediction routes missing values exactly like the serving
    endpoint: right child on numeric AND categorical splits (reference
    default-direction semantics). NA labels also stay NaN — the
    predict path never reads them.

    CSV/TSV chunks all share the file's column count; LibSVM chunk
    width is the largest feature index seen IN THAT CHUNK + 1 — callers
    align widths (the predict path pads to the model's feature count).
    The `max_bad_rows` quarantine budget is shared across the whole
    file, matching the one-shot parse."""
    import pandas as pd

    fmt = detect_format(path)
    if fmt == "libsvm":
        labels, rows, max_idx = [], [], -1

        def flush():
            return _densify_libsvm(labels, rows, max_idx)

        with open(path, "r") as f:
            if has_header:
                next(f, None)
            for line in f:
                line = line.strip()
                if not line:
                    continue
                parts = line.split()
                labels.append(float(parts[0]))
                pairs = libsvm_pairs(parts[1:])
                for i, _ in pairs:
                    max_idx = max(max_idx, i)
                rows.append(pairs)
                if len(rows) >= chunk_rows:
                    yield flush()
                    labels, rows, max_idx = [], [], -1
        if rows:
            yield flush()
        return

    sep = "," if fmt == "csv" else "\t"
    n_bad = 0
    bad_lines = []

    def on_bad(fields):
        bad_lines.append(fields)
        return None  # skip

    if max_bad_rows > 0:
        reader = pd.read_csv(path, sep=sep,
                             header=0 if has_header else None,
                             dtype=str, na_values=NA_VALUES,
                             engine="python", on_bad_lines=on_bad,
                             chunksize=chunk_rows)
    else:
        reader = pd.read_csv(path, sep=sep,
                             header=0 if has_header else None,
                             dtype=np.float64, na_values=NA_VALUES,
                             chunksize=chunk_rows)
    label_idx = None
    for df in reader:
        if label_idx is None:
            names = ([str(c) for c in df.columns] if has_header else None)
            label_idx = _resolve_label_idx(label_column, names, path)
        if max_bad_rows > 0:
            good, n_bad_rows = _coerce_quarantine(df)
            n_bad += n_bad_rows + len(bad_lines)
            bad_lines.clear()
            if n_bad > max_bad_rows:
                Log.fatal("%d malformed rows in %s exceed max_bad_rows=%d; "
                          "first offender: %s", n_bad, str(path),
                          max_bad_rows,
                          _first_offender(path, sep, has_header,
                                          df.shape[1]))
            df = good
        data = df.to_numpy(dtype=np.float64)
        if not keep_nan:
            data = np.nan_to_num(data, nan=0.0)
        label = data[:, label_idx].astype(np.float32)
        yield label, np.delete(data, label_idx, axis=1)
    if n_bad:
        Log.warning("quarantined %d malformed row(s) in %s "
                    "(max_bad_rows=%d)", n_bad, str(path), max_bad_rows)


def parse_text_file(path, has_header=False, label_column="",
                    max_bad_rows=0):
    """Parse a data file into
    (label, features (N, C-1) float32, header names, format, label_idx).

    label/weight/group column resolution follows the reference
    (`DatasetLoader::SetHeader`, dataset_loader.cpp:57-160): label defaults
    to column 0; `name:xxx` selects by header name; plain integers are
    file-column indices. Feature indices do NOT count the label column.

    max_bad_rows > 0 tolerates up to that many malformed CSV/TSV rows
    (quarantined with diagnostics, _read_csv_quarantine); the default 0
    keeps strict mode — the first malformed row aborts the load.
    """
    import pandas as pd

    fmt = detect_format(path)
    if fmt == "libsvm":
        label, mat, names = _parse_libsvm(path, has_header)
        return label, mat, names, fmt, 0

    sep = "," if fmt == "csv" else "\t"
    if max_bad_rows > 0:
        df, _ = _read_csv_quarantine(path, sep, has_header, max_bad_rows)
    else:
        df = pd.read_csv(path, sep=sep, header=0 if has_header else None,
                         dtype=np.float64, na_values=NA_VALUES)
    names = [str(c) for c in df.columns] if has_header else None
    data = df.to_numpy(dtype=np.float64)
    data = np.nan_to_num(data, nan=0.0)

    label_idx = _resolve_label_idx(label_column, names, path)

    label = data[:, label_idx].astype(np.float32)
    # keep float64: the reference parses and bins in double (parser.hpp),
    # and a float32 round-trip perturbs bin boundaries in the last digit
    feats = np.delete(data, label_idx, axis=1)
    feat_names = None
    if names is not None:
        feat_names = [n for i, n in enumerate(names) if i != label_idx]
    return label, feats, feat_names, fmt, label_idx
