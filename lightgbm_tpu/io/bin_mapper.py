"""BinMapper: value -> bin discretization.

Reference: include/LightGBM/bin.h:52-170, src/io/bin.cpp:44-268.
Numeric features: greedy equal-frequency bin bounds found on a value
sample; categorical: count-sorted top-`max_bin` categories. The find-bin
algorithm below reproduces the reference's semantics exactly (including
the zero-count insertion and the big-count-value handling) because
train/valid bin compatibility ("CheckAlign") and accuracy parity both
hinge on identical bin boundaries.

value_to_bin is vectorized (np.searchsorted) instead of the reference's
per-value binary search (bin.h:353-375) — same result, one fused pass.
"""

import numpy as np

from ..utils.log import Log

NUMERICAL = 0
CATEGORICAL = 1

_ZERO = 1e-10


class BinMapper:
    def __init__(self):
        self.num_bin = 1
        self.is_trivial = True
        self.sparse_rate = 0.0
        self.bin_type = NUMERICAL
        self.bin_upper_bound = np.asarray([np.inf])
        self.bin_2_categorical = np.zeros(0, dtype=np.int64)
        self._cat_lookup = None

    # ------------------------------------------------------------------ find
    def find_bin(self, sample_values, total_sample_cnt, max_bin, bin_type=NUMERICAL):
        """Find bin bounds from sampled non-zero values (bin.cpp:44-196).

        sample_values: the non-zero sampled values of this feature;
        total_sample_cnt: total rows sampled (zeros implied by the gap).
        """
        self.bin_type = bin_type
        values = np.sort(np.asarray(sample_values, dtype=np.float64))
        zero_cnt = int(total_sample_cnt - len(values))

        # build (distinct_values, counts) with the zero block inserted in order
        distinct_values, counts = [], []
        if len(values) == 0 or (values[0] > 0.0 and zero_cnt > 0):
            distinct_values.append(0.0)
            counts.append(zero_cnt)
        if len(values) > 0:
            uniq, cnt = np.unique(values, return_counts=True)
            for i, (v, c) in enumerate(zip(uniq.tolist(), cnt.tolist())):
                if i > 0 and uniq[i - 1] < 0.0 and v > 0.0:
                    distinct_values.append(0.0)
                    counts.append(zero_cnt)
                distinct_values.append(v)
                counts.append(int(c))
                if v == 0.0:
                    counts[-1] += zero_cnt
            if uniq[-1] < 0.0 and zero_cnt > 0:
                distinct_values.append(0.0)
                counts.append(zero_cnt)

        num_values = len(distinct_values)
        sample_size = float(total_sample_cnt)
        cnt_in_bin0 = 0

        if bin_type == NUMERICAL:
            if num_values <= max_bin:
                self.num_bin = max(num_values, 1)
                if num_values == 0:
                    self.bin_upper_bound = np.asarray([np.inf])
                else:
                    ub = np.empty(num_values)
                    dv = np.asarray(distinct_values)
                    ub[:-1] = (dv[:-1] + dv[1:]) / 2.0
                    ub[-1] = np.inf
                    self.bin_upper_bound = ub
                    cnt_in_bin0 = counts[0]
            else:
                ub, cnt_in_bin0 = _greedy_bounds(
                    np.asarray(distinct_values), np.asarray(counts, dtype=np.int64),
                    sample_size, max_bin)
                self.bin_upper_bound = ub
                self.num_bin = len(ub)
        else:
            dv_int = []
            cnt_int = []
            for v, c in zip(distinct_values, counts):
                iv = int(v)
                if dv_int and iv == dv_int[-1]:
                    cnt_int[-1] += c
                else:
                    dv_int.append(iv)
                    cnt_int.append(c)
            order = np.argsort(-np.asarray(cnt_int), kind="stable")
            self.num_bin = min(max_bin, len(dv_int))
            self.bin_2_categorical = np.asarray(
                [dv_int[i] for i in order[:self.num_bin]], dtype=np.int64)
            self._cat_lookup = None
            used_cnt = int(sum(cnt_int[i] for i in order[:self.num_bin]))
            if sample_size > 0 and used_cnt / sample_size < 0.95:
                Log.warning("Too many categoricals are ignored, please use bigger "
                            "max_bin or partition this column")
            cnt_in_bin0 = int(sample_size) - used_cnt + (cnt_int[order[0]] if dv_int else 0)

        self.is_trivial = self.num_bin <= 1
        self.sparse_rate = (cnt_in_bin0 / sample_size) if sample_size > 0 else 0.0
        return self

    # ------------------------------------------------------------- transform
    def value_to_bin(self, values):
        """Vectorized value->bin (bin.h:353-375). Returns int32 bins."""
        values = np.asarray(values)
        if self.bin_type == NUMERICAL:
            v = np.asarray(values, dtype=np.float64)
            # NaN must bin to 0 (bin.h NaN->zero-bin); ±inf lands in the
            # edge bins with or without cleaning, so the (copying)
            # nan_to_num pass only runs when NaNs actually exist — the
            # 11M HIGGS load calls this 28 times on pre-cleaned columns
            if np.isnan(v).any():
                v = np.nan_to_num(v, nan=0.0)
            return np.searchsorted(self.bin_upper_bound, v, side="left").astype(np.int32)
        if self._cat_lookup is None:
            self._cat_lookup = {int(c): i for i, c in enumerate(self.bin_2_categorical)}
        look = self._cat_lookup
        flat = values.reshape(-1)
        out = np.fromiter((look.get(int(v), 0) for v in flat), dtype=np.int32,
                          count=len(flat))
        return out.reshape(values.shape)

    def bin_to_value(self, bin_idx):
        """Representative real value of a bin, used as the tree's stored
        threshold (Feature::BinToValue)."""
        if self.bin_type == NUMERICAL:
            return float(self.bin_upper_bound[int(bin_idx)])
        return float(self.bin_2_categorical[int(bin_idx)])

    # --------------------------------------------------------- serialization
    def to_dict(self):
        return {
            "num_bin": int(self.num_bin),
            "is_trivial": bool(self.is_trivial),
            "sparse_rate": float(self.sparse_rate),
            "bin_type": int(self.bin_type),
            "bin_upper_bound": np.asarray(self.bin_upper_bound, dtype=np.float64),
            "bin_2_categorical": np.asarray(self.bin_2_categorical, dtype=np.int64),
        }

    @classmethod
    def from_dict(cls, d):
        m = cls()
        m.num_bin = int(d["num_bin"])
        m.is_trivial = bool(d["is_trivial"])
        m.sparse_rate = float(d["sparse_rate"])
        m.bin_type = int(d["bin_type"])
        m.bin_upper_bound = np.asarray(d["bin_upper_bound"], dtype=np.float64)
        m.bin_2_categorical = np.asarray(d["bin_2_categorical"], dtype=np.int64)
        return m

    def __eq__(self, other):
        if self.num_bin != other.num_bin or self.bin_type != other.bin_type:
            return False
        if self.bin_type == NUMERICAL:
            return np.array_equal(self.bin_upper_bound, other.bin_upper_bound)
        return np.array_equal(self.bin_2_categorical, other.bin_2_categorical)


def _greedy_bounds(distinct_values, counts, sample_size, max_bin):
    """Greedy equal-frequency bound finding (bin.cpp:100-153)."""
    num_values = len(distinct_values)
    mean_bin_size = sample_size / max_bin
    rest_bin_cnt = max_bin
    rest_sample_cnt = int(sample_size)
    is_big = counts >= mean_bin_size
    rest_bin_cnt -= int(np.sum(is_big))
    rest_sample_cnt -= int(np.sum(counts[is_big]))
    mean_bin_size = rest_sample_cnt / rest_bin_cnt if rest_bin_cnt > 0 else np.inf

    upper_bounds = np.full(max_bin, np.inf)
    lower_bounds = np.full(max_bin, np.inf)
    bin_cnt = 0
    lower_bounds[0] = distinct_values[0]
    cur_cnt_inbin = 0
    cnt_in_bin0 = 0
    for i in range(num_values - 1):
        if not is_big[i]:
            rest_sample_cnt -= counts[i]
        cur_cnt_inbin += counts[i]
        if (is_big[i] or cur_cnt_inbin >= mean_bin_size or
                (is_big[i + 1] and cur_cnt_inbin >= max(1.0, mean_bin_size * 0.5))):
            upper_bounds[bin_cnt] = distinct_values[i]
            if bin_cnt == 0:
                cnt_in_bin0 = cur_cnt_inbin
            bin_cnt += 1
            lower_bounds[bin_cnt] = distinct_values[i + 1]
            if bin_cnt >= max_bin - 1:
                break
            cur_cnt_inbin = 0
            if not is_big[i]:
                rest_bin_cnt -= 1
                mean_bin_size = rest_sample_cnt / rest_bin_cnt if rest_bin_cnt > 0 else np.inf
    bin_cnt += 1
    ub = np.empty(bin_cnt)
    ub[:-1] = (upper_bounds[:bin_cnt - 1] + lower_bounds[1:bin_cnt]) / 2.0
    ub[-1] = np.inf
    return ub, int(cnt_in_bin0)
