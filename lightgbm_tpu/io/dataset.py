"""Binned dataset container + loader.

Reference: include/LightGBM/dataset.h:278-421, src/io/dataset.cpp,
include/LightGBM/dataset_loader.h, src/io/dataset_loader.cpp:162-941.

TPU-first design: the training data is stored as ONE dense features-major
integer matrix `bins` of shape (num_stored_rows, num_data) at its natural
PACKED width (bins_dtype: uint8 when every stored row has <= 256 bins,
int16 up to 32768, int32 as the escape) — pushed to device once and
streamed at that width by every histogram kernel, so a per-split scan
moves 1-2 bytes per cell instead of a widened int32's 4. The reference's per-feature Bin objects
(dense/sparse/ordered variants, src/io/dense_bin.hpp / sparse_bin.hpp /
ordered_sparse_bin.hpp) are CPU-cache layouts; on TPU one dense matrix
feeds the MXU directly. Sparse data is handled by CAPACITY, not layout:
exclusive feature bundling (io/bundling.py) packs mutually-exclusive
sparse features into shared slots so stored rows ~ slots << features,
and every ingestion path stays O(nnz) on the way there — CSC/CSR column
sources bin one column at a time, LibSVM files stream as triplet blocks
(_stream_sparse_libsvm), and EFB planning reads one sample column at a
time. A wide sparse load that would still materialize a dense F x N
matrix (nothing bundles) hits a loud budget guard (check_bins_budget)
instead of silently OOMing.

The binary dataset cache (reference dataset.cpp:133-212 with a magic
token) is an .npz with the same role: skip text parsing + binning on
reload; auto-detected next to the data file.
"""

import os

import numpy as np

from ..utils.log import Log
from ..utils.random import Random
from .bin_mapper import BinMapper, NUMERICAL, CATEGORICAL
from .metadata import Metadata
from .parser import parse_text_file, ZERO_THRESHOLD

BINARY_MAGIC = "lightgbm_tpu_dataset_v1"
# v2: bins persist at their natural PACKED width (uint8 <= 256 bins,
# int16 above — the histogram engine's streaming contract, see
# bins_dtype). v1 caches (uint8/uint16) still load, with uint16
# narrowed to int16 on the way in; anything wider (a stale f32/int32
# matrix from a foreign or pre-packing build) is rejected cleanly.
BINARY_FORMAT_VERSION = 2
_ZIP_MAGIC = b"PK\x03\x04"  # npz container prefix


def bins_dtype(num_bins):
    """Natural storage width of a bin matrix — the packed-bin contract
    every loader path and the histogram kernels share: uint8 when every
    stored row has <= 256 bins, int16 up to 32768 (TPU-native narrow
    int; bin ids are non-negative so the sign bit is free), int32
    beyond (unreachable under the reference's max_bin ceiling, kept as
    a correctness escape)."""
    if num_bins <= 256:
        return np.uint8
    if num_bins <= 32768:
        return np.int16
    return np.int32


_BINS_CACHE_DTYPES = ("uint8", "uint16", "int16", "int32")


class BinaryDatasetError(Exception):
    """A binary dataset file failed validation. `claimed` is True when
    the file LOOKS like a binary dataset (npz container) but is
    truncated/corrupt/foreign — as opposed to a text file that was
    never binary at all — so callers can fall past a rotten cache with
    a warning (mirroring the checkpoint loader's behavior) while
    staying silent for ordinary text data files."""

    def __init__(self, message, claimed=False):
        super().__init__(message)
        self.claimed = claimed


def _qid_to_counts(qid_col):
    """Row-order run-length encoding of a per-row query-id column into
    per-query counts (Metadata::LoadQueryBoundaries semantics,
    metadata.cpp:358-371)."""
    qid = np.asarray(qid_col).astype(np.int64)
    if len(qid) == 0:
        return np.zeros(0, dtype=np.int64)
    change = np.nonzero(np.diff(qid))[0] + 1
    edges = np.concatenate([[0], change, [len(qid)]])
    return np.diff(edges)


class _VirtualBinsView:
    """Fancy-indexable [feat_arr, row_arr] view over a bundled stored
    matrix (host traversal path; see io/bundling.py for the encoding)."""

    def __init__(self, stored, plan, num_bin_pf):
        self._stored = stored
        self._plan = plan
        self._nb = np.asarray(num_bin_pf)
        self.shape = (len(plan.feat_slot), stored.shape[1])

    def __getitem__(self, key):
        feat, rows = key
        feat = np.asarray(feat)
        sc = self._stored[self._plan.feat_slot[feat], rows].astype(np.int64)
        off = self._plan.feat_offset[feat]
        nb = self._nb[feat]
        return np.where((sc > off) & (sc <= off + nb - 1), sc - off, 0)


AUTO_STREAM_MIN_FEATS = 1024


def _libsvm_looks_wide(filename, has_header):
    """Cheap probe: is this a LibSVM file whose feature ids reach past
    AUTO_STREAM_MIN_FEATS within the first 1000 data lines? Wide sparse
    files auto-route to the O(nnz) streaming loader; narrow ones keep
    the (also-correct) in-memory path."""
    from .parser import detect_format, libsvm_pairs
    try:
        if detect_format(filename) != "libsvm":
            return False
        with open(filename, "r") as f:
            if has_header:
                next(f, None)
            for _, line in zip(range(1000), f):
                parts = line.split()
                if len(parts) < 2:
                    continue
                for idx, _ in libsvm_pairs(parts[1:]):
                    if idx + 1 > AUTO_STREAM_MIN_FEATS:
                        return True
    except Exception:   # unreadable / binary / undecodable: not libsvm
        return False
    return False


def check_bins_budget(rows, cols, itemsize, what):
    """Loud guard before allocating a stored bin matrix: a wide sparse
    dataset that failed to bundle would silently materialize the dense
    F x N block the reference's SparseBin exists to avoid
    (src/io/sparse_bin.hpp:17-331). Budget in GB via
    LIGHTGBM_TPU_MAX_BINS_GB (default 16; <= 0 disables)."""
    budget_gb = float(os.environ.get("LIGHTGBM_TPU_MAX_BINS_GB", "16"))
    if budget_gb <= 0:
        return
    need = rows * cols * itemsize / (1 << 30)
    if need > budget_gb:
        Log.fatal(
            "%s needs a %d x %d bin matrix (%.1f GB > budget %.0f GB). "
            "For wide sparse data enable bundling (is_enable_sparse=true"
            ") so exclusive features share slots; raise/disable the "
            "budget with LIGHTGBM_TPU_MAX_BINS_GB if the dense matrix "
            "is intended.", what, rows, cols, need, budget_gb)


def _bin_dense_on_device(mat, real_idx, mappers, dtype):
    """Full-matrix binning on the accelerator: bin k = #(bounds < v)
    == np.searchsorted(bounds, v, 'left') for every numerical mapper.
    The host pass costs ~82 s at 11M x 28 on this single-core box;
    the device compare-sum is O(N*F*B) VPU compares (~0.1 s) plus the
    raw-matrix transfer — the reference bins on CPU because it IS a
    CPU framework (bin.cpp FindBin/value_to_bin); an accelerator-first
    loader puts the scan where the FLOPs are.

    f32-exactness: bounds are f64 (sample midpoints); the f32 cast is
    rounded toward -inf so `v > bound32` equals the f64 `v > bound`
    for every f32 input v (same boundary rule as the device-predict
    thresholds, models/gbdt.py _device_model).

    Gated by LIGHTGBM_TPU_DEVICE_BIN (default auto = non-CPU backends,
    numerical features only). Returns (F, N) bins or None (caller
    falls back to the threaded host pass)."""
    mode = os.environ.get("LIGHTGBM_TPU_DEVICE_BIN", "auto")
    if mode == "0":
        return None
    try:
        import jax
        import jax.numpy as jnp
        if mode == "auto" and jax.default_backend() == "cpu":
            return None
        if any(m.bin_type != NUMERICAL for m in mappers):
            return None
        if mat.dtype != np.float32:
            # the -inf-rounded f32 bounds make the compare exact for
            # f32 INPUTS only; f64 matrices (text loads keep f64 so
            # boundaries survive the last digit, parser.py) must bin
            # through the host f64 searchsorted
            return None
        n = mat.shape[0]
        f = len(real_idx)
        b_max = max(len(m.bin_upper_bound) for m in mappers)
        bounds = np.full((f, b_max), np.inf)
        for u, m in enumerate(mappers):
            bounds[u, :len(m.bin_upper_bound)] = m.bin_upper_bound
        b32 = bounds.astype(np.float32)
        lifted = b32.astype(np.float64) > bounds
        b32 = np.where(lifted,
                       np.nextafter(b32, np.float32(-np.inf),
                                    dtype=np.float32), b32)
        # (+inf pad bounds contribute 0 to the strict-compare count)
        chunk = 1 << 16
        n_pad = -(-n // chunk) * chunk
        all_cols = (f == mat.shape[1]
                    and np.array_equal(real_idx, np.arange(f)))
        if n_pad == n and all_cols and mat.flags.c_contiguous:
            x_used = mat            # zero-copy fast path
        else:
            # ONE full-size buffer: pad rows + column-select in place
            x_used = np.zeros((n_pad, f), np.float32)
            x_used[:n] = mat if all_cols else mat[:, real_idx]
        # host rule bins NaN like the value 0.0 (bin.h NaN->zero-bin);
        # on device NaN compares false everywhere -> raw bin 0, which
        # differs when a column has negative bounds
        if np.isnan(x_used).any():
            x_used = np.nan_to_num(x_used, nan=0.0)
        xdev = jnp.asarray(x_used).reshape(n_pad // chunk, chunk, f)
        bdev = jnp.asarray(b32)
        out_dt = jnp.dtype(dtype)

        @jax.jit
        def bin_all(xc):
            def one(xb):   # (chunk, F) -> (chunk, F) narrow ints
                return jnp.sum(xb[:, :, None] > bdev[None, :, :],
                               axis=-1, dtype=jnp.int32).astype(out_dt)
            return jax.lax.map(one, xc)

        # narrow on device: the download is N x F bytes, not 4x that
        out = np.asarray(bin_all(xdev)).reshape(n_pad, f)[:n]
        return np.ascontiguousarray(out.T).astype(dtype, copy=False)
    except Exception as e:   # any device hiccup: host pass is the truth
        Log.warning("Device binning unavailable (%s); binning on host",
                    e)
        return None


def _bin_columns_threaded(col_fn, count):
    """Map col_fn over column indices with a thread pool: value_to_bin
    is searchsorted-dominated and releases the GIL, so the reference's
    OpenMP-parallel ExtractFeatures (dataset_loader.cpp:762-841) maps to
    plain threads here (~6x on the 11M x 28 HIGGS load)."""
    from concurrent.futures import ThreadPoolExecutor
    workers = min(8, os.cpu_count() or 1, max(count, 1))
    if workers <= 1 or count <= 1:
        return [col_fn(j) for j in range(count)]
    with ThreadPoolExecutor(max_workers=workers) as ex:
        return list(ex.map(col_fn, range(count)))


def is_column_source(obj):
    """True for objects implementing the column-source protocol
    (DenseColumns / CscColumns). A bare hasattr(obj, "col") is NOT
    enough: scipy.sparse COO matrices carry a `.col` ndarray."""
    return callable(getattr(obj, "col", None)) and hasattr(obj, "num_total")


class DenseColumns:
    """Column source over a dense (N, F) matrix (see _construct)."""

    def __init__(self, mat):
        self._m = mat
        self.n, self.num_total = mat.shape

    def col(self, j):
        return self._m[:, j]


class CscColumns:
    """Column source over CSC triplets: each column materializes as ONE
    dense (N,) f32 vector at a time, so a sparse FFI input is binned in
    O(nnz + N) peak memory instead of the O(N * F) dense raw matrix —
    the TPU-side analog of the reference's row-iterator dataset
    construction (c_api.cpp:317-427)."""

    def __init__(self, colptr, indices, vals, num_row, num_col):
        self._p = np.asarray(colptr, dtype=np.int64)
        self._i = np.asarray(indices, dtype=np.int64)
        self._v = np.nan_to_num(np.asarray(vals, dtype=np.float32), nan=0.0)
        self.n = int(num_row)
        self.num_total = int(num_col)

    def col(self, j):
        out = np.zeros(self.n, dtype=np.float32)
        sl = slice(self._p[j], self._p[j + 1])
        out[self._i[sl]] = self._v[sl]
        return out

    @classmethod
    def from_csr(cls, indptr, indices, vals, num_col):
        """O(nnz log nnz) CSR -> CSC transpose (stable by row within a
        column); never builds the dense matrix."""
        indptr = np.asarray(indptr, dtype=np.int64)
        indices = np.asarray(indices, dtype=np.int64)
        vals = np.asarray(vals)
        nrow = len(indptr) - 1
        row_of = np.repeat(np.arange(nrow, dtype=np.int64),
                           np.diff(indptr))
        order = np.argsort(indices, kind="stable")
        counts = np.bincount(indices, minlength=num_col)
        colptr = np.concatenate([[0], np.cumsum(counts)])
        return cls(colptr, row_of[order], vals[order], nrow, num_col)


def encode_dataset_sidecar(ds, arrays=None):
    """npz encoding of a CoreDataset MINUS its bin matrix: feature
    maps, names, bin mappers, bundle plan, metadata. ONE encoder for
    the two binary forms — the binary cache (save_binary, bins member
    added by the caller) and the block-store sidecar
    (data/block_store.py) — so their on-disk dictionaries cannot
    drift apart."""
    arrays = {} if arrays is None else arrays
    arrays.update({
        "used_feature_map": ds.used_feature_map,
        "real_feature_idx": ds.real_feature_idx,
        "num_total_features": np.asarray(ds.num_total_features),
        "label_idx": np.asarray(ds.label_idx),
        "feature_names": np.asarray(ds.feature_names, dtype=object),
    })
    for i, m in enumerate(ds.bin_mappers):
        for k, v in m.to_dict().items():
            arrays[f"mapper{i}_{k}"] = np.asarray(v)
    if ds.bundle_plan is not None:
        for k, v in ds.bundle_plan.to_dict().items():
            arrays[f"bundle_{k}"] = np.asarray(v)
    for k, v in ds.metadata.to_dict().items():
        arrays[f"meta_{k}"] = np.asarray(v)
    if getattr(ds, "profile", None) is not None:
        # the baseline distribution rides both binary forms (counts +
        # missing only — the mappers above already carry the bounds)
        ds.profile.encode_sidecar(arrays)
    return arrays


def decode_dataset_sidecar(ds, z, truncated):
    """Inverse of encode_dataset_sidecar: populate `ds` (everything but
    bins) from npz archive `z`. `truncated(msg)` builds the exception
    to raise on a structurally incomplete archive — each binary form
    keeps its own error type."""
    ds.used_feature_map = z["used_feature_map"]
    ds.real_feature_idx = z["real_feature_idx"]
    ds.num_total_features = int(z["num_total_features"])
    ds.label_idx = int(z["label_idx"])
    ds.feature_names = [str(x) for x in z["feature_names"]]
    n_used = len(ds.real_feature_idx)
    mappers = []
    for i in range(n_used):
        d = {k[len(f"mapper{i}_"):]: z[k] for k in z.files
             if k.startswith(f"mapper{i}_")}
        if "num_bin" not in d:
            raise truncated(f"missing bin mapper {i} of {n_used}")
        mappers.append(BinMapper.from_dict(d))
    ds.bin_mappers = mappers
    bundle = {k[7:]: z[k] for k in z.files if k.startswith("bundle_")}
    if bundle:
        from .bundling import BundlePlan
        ds.bundle_plan = BundlePlan.from_dict(bundle)
    meta = {k[5:]: z[k] for k in z.files if k.startswith("meta_")}
    ds.metadata = Metadata.from_dict(meta)
    from .profile import DatasetProfile
    ds.profile = DatasetProfile.decode_sidecar(z, ds)  # None pre-profile
    return ds


class CoreDataset:
    """Eagerly-binned dataset (the reference's `Dataset`, dataset.h:278-421)."""

    def __init__(self):
        self.bins = None              # (F_used, N) packed (bins_dtype), host
        self.bin_mappers = []         # per used feature
        self.used_feature_map = None  # (num_total_features,) int32: total->used or -1
        self.real_feature_idx = None  # (F_used,) int32: used -> total
        self.feature_names = []       # one per total feature
        self.num_total_features = 0
        self.label_idx = 0
        self.metadata = Metadata()
        self._device_bins = None
        self._bin_value_cache = None
        self.raw_data = None          # optional (N, C) float32 original values
        self.global_num_data = None   # set by per-rank loading (multi-host)
        self.bundle_plan = None       # io/bundling.py BundlePlan or None
        # training-time baseline distribution (io/profile.py
        # DatasetProfile): per-feature bin occupancy + missing counts,
        # captured once at binning and persisted through the binary
        # cache / block-store sidecar / model-file sidecar
        self.profile = None

    # ------------------------------------------------------------ properties
    @property
    def num_data(self):
        return 0 if self.bins is None else self.bins.shape[1]

    @property
    def num_features(self):
        return len(self.bin_mappers)

    @property
    def max_num_bin(self):
        return max((m.num_bin for m in self.bin_mappers), default=1)

    @property
    def max_stored_bin(self):
        """Histogram width of the STORED matrix (bundle slots can pack
        several features' bin ranges into one row)."""
        if self.bundle_plan is None:
            return self.max_num_bin
        return int(self.bundle_plan.slot_bins.max())

    def traversal_bins(self):
        """Bins indexable as [feature_array, row_array] in VIRTUAL feature
        space for host tree traversal; decodes bundle slots on the fly."""
        if self.bundle_plan is None:
            return self.bins
        return _VirtualBinsView(self.bins, self.bundle_plan,
                                self.num_bin_array())

    def num_bin_array(self):
        return np.asarray([m.num_bin for m in self.bin_mappers], dtype=np.int32)

    def bin_value_table(self):
        """(F, max_num_bin) float64 bin representative values
        (Feature::BinToValue) in VIRTUAL feature space — what linear
        leaves dot against when scoring in bin space (models/
        linear_leaves.py, Tree.predict_by_bins). Cached; aligned
        train/valid sets share bin mappers so their tables match."""
        if getattr(self, "_bin_value_cache", None) is None:
            table = np.zeros((self.num_features, self.max_num_bin),
                             dtype=np.float64)
            for i, m in enumerate(self.bin_mappers):
                vals = (m.bin_upper_bound if m.bin_type != CATEGORICAL
                        else m.bin_2_categorical.astype(np.float64))
                vals = np.asarray(vals, np.float64).copy()
                # the last numeric bin's upper bound is +inf (and a
                # degenerate first bound can be -inf): clamp each
                # non-finite bound to its nearest finite neighbor so
                # the linear-leaf dot products stay finite. Bounds are
                # monotone, so this is the previous (resp. next)
                # representative.
                bad = ~np.isfinite(vals)
                if bad.any():
                    good = np.nonzero(~bad)[0]
                    if len(good) == 0:
                        vals[:] = 0.0
                    else:
                        idx = np.clip(
                            np.searchsorted(good, np.nonzero(bad)[0]),
                            1, len(good)) - 1
                        vals[bad] = vals[good[idx]]
                table[i, :len(vals)] = vals
            self._bin_value_cache = table
        return self._bin_value_cache

    @property
    def stored_bins_dtype(self):
        """dtype of the stored bin matrix — resolvable without a
        resident matrix (the out-of-core dataset forwards its block
        store's dtype), so valid sets can align against either form."""
        return self.bins.dtype

    def feature_is_categorical(self):
        return np.asarray([m.bin_type == CATEGORICAL for m in self.bin_mappers])

    def device_bins(self):
        """The (F, N) bin matrix on the default device (cached)."""
        import jax.numpy as jnp
        if self._device_bins is None:
            self._device_bins = jnp.asarray(self.bins)
        return self._device_bins

    # ------------------------------------------------------------- alignment
    def check_align(self, other: "CoreDataset") -> bool:
        """Bin-mapper compatibility between train/valid (dataset.h CheckAlign)."""
        if self.num_features != other.num_features:
            return False
        if self.num_total_features != other.num_total_features:
            return False
        return all(a == b for a, b in zip(self.bin_mappers, other.bin_mappers))

    # ---------------------------------------------------------------- subset
    def subset(self, indices) -> "CoreDataset":
        """Row subset sharing bin mappers (dataset.cpp Subset; used by cv)."""
        indices = np.asarray(indices, dtype=np.int64)
        out = CoreDataset()
        out.bins = np.ascontiguousarray(self.bins[:, indices])
        out.bin_mappers = self.bin_mappers
        out.used_feature_map = self.used_feature_map
        out.real_feature_idx = self.real_feature_idx
        out.feature_names = self.feature_names
        out.num_total_features = self.num_total_features
        out.label_idx = self.label_idx
        out.bundle_plan = self.bundle_plan
        out.metadata = self.metadata.subset(indices)
        if self.raw_data is not None:
            out.raw_data = self.raw_data[indices]
        return out

    # --------------------------------------------------------- binary cache
    def save_binary(self, path):
        """Binary cache (reference dataset.cpp:133-212)."""
        arrays = encode_dataset_sidecar(self, {"bins": self.bins})
        from ..utils.checkpoint import atomic_open
        # crash-atomic: a kill mid-save must never leave a truncated
        # cache where a valid one stood (the loader would fatal on it).
        # The archive streams to the tmp file (savez keeps the exact
        # path; no .npz suffix is appended to an open handle).
        # UNCOMPRESSED members (np.savez = ZIP_STORED): the bins matrix
        # sits contiguous inside the archive, so the loader maps it
        # through the OS page cache (data/mmap_io.py) instead of
        # materializing a second copy — and packed uint8/int16 bins
        # barely deflate anyway.
        with atomic_open(path) as f:
            np.savez(f, magic=np.asarray(BINARY_MAGIC),
                     format_version=np.asarray(BINARY_FORMAT_VERSION),
                     **arrays)
        Log.info("Saved binary dataset to %s", str(path))

    @classmethod
    def load_binary(cls, path) -> "CoreDataset":
        """Load + validate a binary dataset cache. Every failure mode a
        truncated, bit-rotted, or foreign file can produce surfaces as
        a BinaryDatasetError naming the file and the defect — never a
        numpy reshape traceback (reference dataset.cpp:133-152 validates
        its magic token + version the same way)."""
        # probe before np.load: a text/garbage file is "never was
        # binary" (claimed=False), not a corrupt cache
        try:
            with open(path, "rb") as f:
                head = f.read(len(_ZIP_MAGIC))
        except OSError as e:
            raise BinaryDatasetError(f"cannot read {path}: {e}")
        if head != _ZIP_MAGIC:
            raise BinaryDatasetError(
                f"{path} is not a lightgbm_tpu binary dataset (bad magic)")
        try:
            z = np.load(path, allow_pickle=True)
            files = set(z.files)
        except Exception as e:
            raise BinaryDatasetError(
                f"{path} is truncated or corrupt (unreadable archive: "
                f"{e})", claimed=True)
        if "magic" not in files:
            raise BinaryDatasetError(
                f"{path} is an npz archive but not a lightgbm_tpu "
                "dataset (no magic entry)", claimed=True)
        try:
            if str(z["magic"]) != BINARY_MAGIC:
                raise BinaryDatasetError(
                    f"{path} has foreign magic {str(z['magic'])!r} "
                    f"(expected {BINARY_MAGIC})", claimed=True)
            version = (int(z["format_version"])
                       if "format_version" in files else 1)
            if version > BINARY_FORMAT_VERSION:
                raise BinaryDatasetError(
                    f"{path} is format version {version}; this build "
                    f"reads up to {BINARY_FORMAT_VERSION}", claimed=True)
            missing = [k for k in ("bins", "used_feature_map",
                                   "real_feature_idx",
                                   "num_total_features", "label_idx",
                                   "feature_names", "meta_label")
                       if k not in files]
            if missing:
                raise BinaryDatasetError(
                    f"{path} is truncated (missing entries: "
                    f"{', '.join(missing)})", claimed=True)
            ds = cls()
            # mapped-IO fast path: an uncompressed bins member is read
            # through the OS page cache (np.memmap) instead of a full
            # read() copy, so a warm cache load no longer doubles peak
            # RSS (the mapper verifies the member's zip CRC itself,
            # streamed). Compressed members (pre-mapped-IO
            # savez_compressed caches) and anything unmappable —
            # including a CRC mismatch — fall back to the copying load,
            # which surfaces the legacy BadZipFile on a rotten cache.
            from ..data.mmap_io import memmap_npz_member
            mapped = memmap_npz_member(path, "bins.npy")
            ds.bins = mapped if mapped is not None else z["bins"]
            decode_dataset_sidecar(
                ds, z, lambda msg: BinaryDatasetError(
                    f"{path} is truncated ({msg})", claimed=True))
        except BinaryDatasetError:
            raise
        except Exception as e:
            # zip-member CRC failures surface lazily at entry access
            raise BinaryDatasetError(
                f"{path} is truncated or corrupt ({e})", claimed=True)
        # length/shape cross-checks: a partially-written file whose
        # archive still opens must not survive to a reshape traceback
        if ds.bins.ndim != 2:
            raise BinaryDatasetError(
                f"{path}: bins matrix has {ds.bins.ndim} dims, "
                "expected 2", claimed=True)
        if ds.bins.dtype.name not in _BINS_CACHE_DTYPES:
            # a stale f32/f64/int64 matrix (foreign or pre-packing
            # build) must not reach the histogram engine, which streams
            # bins at their packed width
            raise BinaryDatasetError(
                f"{path}: bins matrix is {ds.bins.dtype.name}, expected "
                f"a packed bin matrix ({'/'.join(_BINS_CACHE_DTYPES)}) — "
                "stale or foreign cache", claimed=True)
        natural = bins_dtype(int(ds.max_stored_bin))
        if ds.bins.dtype != natural:
            # v1 caches stored uint16 where the packed contract says
            # int16; bin ids < max_stored_bin make the cast lossless
            ds.bins = ds.bins.astype(natural)
        n_rows = int(ds.bins.shape[1])
        n_label = int(np.asarray(z["meta_label"]).shape[0])
        if n_label != n_rows:
            raise BinaryDatasetError(
                f"{path}: bin matrix holds {n_rows} rows but the label "
                f"has {n_label} — truncated or foreign file",
                claimed=True)
        return ds


class DatasetLoader:
    """Text/matrix -> CoreDataset pipeline (dataset_loader.cpp:162-941)."""

    def __init__(self, config=None, predict_fun=None):
        from ..config import Config
        self.config = config if config is not None else Config()
        self.predict_fun = predict_fun  # init-score hook for continued training

    # ----------------------------------------------------------- from file
    def _apply_rank_partition(self, ds, rank, num_machines):
        """Per-rank row distribution for multi-host training
        (dataset_loader.cpp:505-550): contiguous query-aligned blocks;
        bin mappers stay global (built before the cut) so CheckAlign
        holds across ranks. Only active under jax.distributed."""
        import jax
        if (num_machines <= 1 or jax.process_count() <= 1
                or self.config.is_pre_partition
                # feature-parallel replicates rows on every machine
                # (config.cpp:173-176, application.cpp:125-131)
                or self.config.tree_learner == "feature"):
            return ds
        if jax.process_count() != num_machines:
            Log.fatal("num_machines=%d but %d jax processes are running; "
                      "the row partition would drop data",
                      num_machines, jax.process_count())
        if rank >= num_machines:
            Log.fatal("rank %d out of range for num_machines=%d",
                      rank, num_machines)
        from ..parallel.distributed import partition_rows
        n = ds.num_data
        qb = ds.metadata.query_boundaries
        lo, hi = partition_rows(n, rank, num_machines, qb)
        out = ds.subset(np.arange(lo, hi))
        out.global_num_data = n
        # query-aligned blocks can be uneven; every rank pads to the
        # LARGEST block so global array shapes agree (learners._pad_rows)
        out.local_rows_max = max(
            partition_rows(n, r, num_machines, qb)[1]
            - partition_rows(n, r, num_machines, qb)[0]
            for r in range(num_machines))
        Log.info("Rank %d/%d holds rows [%d, %d) of %d",
                 rank, num_machines, lo, hi, n)
        return out

    def load_from_file(self, filename, rank=0, num_machines=1) -> CoreDataset:
        cfg = self.config
        # out-of-core: bin once into the on-disk block store next to the
        # data file (reused across runs via its manifest signature) and
        # return the streaming dataset — the (F, N) matrix never
        # materializes (lightgbm_tpu/data/, docs/Out-of-Core.md)
        if getattr(cfg, "out_of_core", False):
            if self.predict_fun is not None:
                Log.fatal("out_of_core does not support continued "
                          "training (init scores need resident raw "
                          "values)")
            if cfg.max_bad_rows > 0:
                Log.warning("max_bad_rows=%d is not applied on the "
                            "out-of-core streaming load path: malformed "
                            "rows still abort the load", cfg.max_bad_rows)
            if num_machines > 1:
                # gang training over ONE shared store: rank 0 builds,
                # peers adopt their owned block ranges — no per-rank
                # re-binning (data/block_store.py, docs/Out-of-Core.md)
                from ..data.block_store import load_block_store_gang
                return load_block_store_gang(self, filename, rank,
                                             num_machines)
            from ..data.block_store import load_or_build_block_store
            return load_or_build_block_store(self, filename)
        bin_path = str(filename) + ".bin"
        # the binary cache stores no raw values, which continued training
        # needs for init scores — fall back to the text path then
        use_cache = cfg.enable_load_from_binary_file and self.predict_fun is None
        cache_incompatible = False
        # CheckCanLoadFromBin (dataset_loader.cpp:903-940): the data path
        # may BE a binary cache file, or have a sibling <data>.bin cache.
        if use_cache:
            for cand in (str(filename), bin_path):
                if not os.path.exists(cand):
                    continue
                try:
                    ds = CoreDataset.load_binary(cand)
                except BinaryDatasetError as e:
                    if e.claimed and cand == str(filename):
                        # the data file ITSELF is a (broken) binary
                        # dataset: the text parser would only produce
                        # garbage on it — fail with the real diagnosis
                        Log.fatal("%s", e)
                    if e.claimed:
                        # rotten sibling cache: fall past it to the
                        # text parse, like the checkpoint loader falls
                        # past a corrupt snapshot
                        Log.warning("ignoring unusable binary cache: %s",
                                    e)
                    continue  # not a binary cache; fall through
                if ds.bundle_plan is not None and (
                        not cfg.is_enable_sparse
                        or getattr(ds.bundle_plan, "conflict_rate", 0.0)
                        > cfg.max_conflict_rate):
                    # cache was built with bundling this run can't use
                    # (disabled, or a MORE tolerant plan than this
                    # config allows) — rebuild from text (WITHOUT
                    # overwriting the cache, so the original config
                    # keeps its bundling). (Feature-parallel handles
                    # bundled datasets since parallel/learners.py grew
                    # per-shard slot maps — no learner restriction.)
                    Log.warning("Binary cache %s contains a bundled "
                                "dataset incompatible with this config; "
                                "rebuilding from text", cand)
                    cache_incompatible = True
                    break
                Log.info("Loaded binary dataset %s", cand)
                self._attach_init_score(ds)
                return self._apply_rank_partition(ds, rank, num_machines)

        # two-round streaming path: peak memory O(block), the full float
        # matrix never materializes (dataset_loader.cpp:505-610). Continued
        # training needs raw values for init scores, so it keeps the
        # in-memory path. Wide LibSVM auto-streams even without
        # use_two_round_loading: the dense parse would materialize the
        # (N, F) float block the O(nnz) route exists to avoid (the
        # reference gets this from per-feature sparse bins,
        # sparse_bin.hpp; here the format sniff stands in for its
        # sparse_rate auto-selection, bin.cpp:291-302). The auto-route
        # carries the SAME weight/group guard as _load_two_round's
        # sparse_route: with those columns set the streamer falls back
        # to dense (65536, num_cols) parse blocks — multi-GB at the
        # widths that trigger the probe — so such configs keep the
        # in-memory path unless the user explicitly asked to stream.
        if self.predict_fun is None and (
                cfg.use_two_round_loading
                or (cfg.weight_column == "" and cfg.group_column == ""
                    and _libsvm_looks_wide(filename, cfg.has_header))):
            if cfg.max_bad_rows > 0:
                # the block streamer parses strictly; quarantine is an
                # in-memory-path feature. Say so loudly instead of
                # silently changing behavior between load routes.
                Log.warning("max_bad_rows=%d is not applied on the "
                            "two-round/streaming load path: malformed "
                            "rows still abort the load", cfg.max_bad_rows)
            ds = self._load_two_round(filename, rank, num_machines)
            if ds.global_num_data is not None:
                if cfg.is_save_binary_file:
                    Log.warning("is_save_binary_file ignored: rank-"
                                "filtered datasets hold only a row block")
                return ds  # already rank-filtered during the stream
            if cfg.is_save_binary_file and rank == 0 and not cache_incompatible:
                ds.save_binary(bin_path)  # one writer on shared storage
            return self._apply_rank_partition(ds, rank, num_machines)

        label, feats, names, fmt, label_idx = parse_text_file(
            filename, has_header=cfg.has_header, label_column=cfg.label_column,
            max_bad_rows=cfg.max_bad_rows)
        weight_idx, group_idx, ignore, categorical = self._resolve_columns(
            names, feats.shape[1])

        meta = Metadata(len(label))
        meta.set_label(label)
        if weight_idx >= 0:
            meta.set_weights(feats[:, weight_idx])
            ignore.add(weight_idx)
        if group_idx >= 0:
            # group column holds a query id per row; run-length encode in ROW
            # order (metadata.cpp:358-371) — np.unique would sort by qid value
            # and merge non-adjacent runs
            meta.set_query(_qid_to_counts(feats[:, group_idx]))
            ignore.add(group_idx)
        meta.load_side_files(filename)

        ds = self._construct(feats, names, ignore, categorical, meta)
        ds.label_idx = label_idx
        if self.predict_fun is not None:
            ds.raw_data = feats  # continued training needs raw values
        self._attach_init_score(ds)
        if cfg.is_save_binary_file and rank == 0 and not cache_incompatible:
            ds.save_binary(bin_path)  # one writer on shared storage
        return self._apply_rank_partition(ds, rank, num_machines)

    def load_from_file_align_with_other_dataset(self, filename, train_ds) -> CoreDataset:
        """Valid-set path: bin with the TRAIN mappers (dataset_loader.cpp:222-266)."""
        cfg = self.config
        from .parser import detect_format
        if (detect_format(filename) == "libsvm"
                and self.predict_fun is None
                and cfg.weight_column == "" and cfg.group_column == ""):
            # O(nnz) aligned route: stream triplets with the TRAIN
            # mappers + bundle plan, never a dense (N, F) parse (a wide
            # sparse valid file would OOM there). predict_fun needs raw
            # values -> dense fallback.
            return self._load_sparse_aligned(filename, train_ds)
        label, feats, names, fmt, _ = parse_text_file(
            filename, has_header=cfg.has_header, label_column=cfg.label_column,
            max_bad_rows=cfg.max_bad_rows)
        meta = Metadata(len(label))
        meta.set_label(label)
        weight_idx, group_idx, ignore, _ = self._resolve_columns(names, feats.shape[1])
        if weight_idx >= 0:
            meta.set_weights(feats[:, weight_idx])
        if group_idx >= 0:
            meta.set_query(_qid_to_counts(feats[:, group_idx]))
        meta.load_side_files(filename)
        ds = self._bin_with_mappers(feats, train_ds, meta)
        if self.predict_fun is not None:
            ds.raw_data = feats
        self._attach_init_score(ds)
        return ds

    # ------------------------------------------------- two-round streaming
    def _load_two_round(self, filename, rank=0, num_machines=1) -> CoreDataset:
        """Sample pass -> mappers -> binning pass (dataset_loader.cpp:505-610,
        pipeline_reader.h/text_reader.h semantics; see io/streaming.py).

        Under jax.distributed, round two is RANK-FILTERED
        (dataset_loader.cpp:505-550): every rank streams the file but
        stores only its contiguous row block, so peak memory is
        O(block + local rows + sample). The bin-construction sample is
        drawn from the GLOBAL stream with the shared data_random_seed,
        so every rank derives identical mappers with no network — the
        TPU answer to the reference's mapper Allgather
        (dataset_loader.cpp:697-760)."""
        from .parser import detect_format
        from .streaming import scan_file, iter_blocks, collect_sample_rows
        cfg = self.config
        fmt = detect_format(filename)
        n, names, num_cols = scan_file(filename, fmt, cfg.has_header)
        if n == 0:
            Log.fatal("Data file %s is empty", str(filename))

        label_idx = self._resolve_label_idx(names, fmt)
        feat_names = ([nm for i, nm in enumerate(names) if i != label_idx]
                      if names is not None else None)
        num_feats = num_cols - 1
        feat_cols = np.asarray([j for j in range(num_cols) if j != label_idx])

        weight_idx, group_idx, ignore, categorical = self._resolve_columns(
            feat_names, num_feats)
        if weight_idx >= 0:
            ignore.add(weight_idx)
        if group_idx >= 0:
            ignore.add(group_idx)

        # O(nnz) route for LibSVM: triplet blocks + CSC sample, never a
        # dense (rows, num_cols) float block — the streaming analog of
        # the reference's SparseBin push path (src/io/sparse_bin.hpp:
        # 17-331, auto-selected at sparse_rate >= 0.8, bin.cpp:291-302).
        # Weight/group column configs fall back to the dense route
        # (LibSVM files carry those via side files, not columns).
        sparse_route = (fmt == "libsvm" and weight_idx < 0
                        and group_idx < 0)

        # round one: sample rows, find mappers (identical draws and
        # therefore identical mappers to the in-memory path)
        cnt = min(cfg.bin_construct_sample_cnt, n)
        sample_idx = (np.arange(n, dtype=np.int64) if cnt == n
                      else Random(cfg.data_random_seed).sample(n, cnt).astype(np.int64))
        if sparse_route:
            from .streaming import collect_sample_csc
            _, s_colptr, s_rows, s_vals = collect_sample_csc(
                filename, cfg.has_header, num_feats, sample_idx)

            def sample_feat_col(j):
                out = np.zeros(cnt, dtype=np.float64)
                sl = slice(s_colptr[j], s_colptr[j + 1])
                out[s_rows[sl]] = s_vals[sl]
                return out
        else:
            sample_all = collect_sample_rows(filename, fmt, cfg.has_header,
                                             num_cols, sample_idx)
            sample_feats = sample_all[:, feat_cols]

            def sample_feat_col(j):
                return sample_feats[:, j]
        mappers, used_map, real_idx = self._make_mappers(
            sample_feat_col, num_feats, ignore, categorical)

        # bundling plan from the sample — identical to the in-memory
        # path's (same sample rows, same greedy pass); per-column
        # callable so planning never builds the (F, sample) bins stack
        from .bundling import plan_bundles
        plan = None
        if cfg.is_enable_sparse:
            plan = plan_bundles(
                mappers,
                lambda u: mappers[u].value_to_bin(
                    sample_feat_col(real_idx[u])),
                enable=True, max_conflict_rate=cfg.max_conflict_rate)
            if plan.is_identity:
                plan = None

        # rank filtering: only this rank's contiguous row block is stored
        # (query-grouped data and side files need global views — those
        # fall back to full-load + subset in _apply_rank_partition)
        import jax
        from .metadata import SIDE_FILE_EXTS
        side_files = any(os.path.exists(str(filename) + ext)
                         for ext in SIDE_FILE_EXTS)
        rank_filter = (num_machines > 1
                       and jax.process_count() == num_machines
                       and rank < num_machines
                       and not cfg.is_pre_partition
                       and cfg.tree_learner != "feature"
                       and group_idx < 0 and not side_files)
        if rank_filter:
            from ..parallel.distributed import partition_rows
            lo, hi = partition_rows(n, rank, num_machines)
            n_local = hi - lo
        else:
            lo, hi = 0, n
            n_local = n

        # round two: stream blocks, pushing binned values + metadata columns
        if sparse_route:
            bins, label = self._stream_sparse_libsvm(
                filename, mappers, used_map, plan, n_local, lo, hi)
            weights = qid = None
            bundle_conflicts = 0
        elif plan is None:
            dtype = bins_dtype(max(m.num_bin for m in mappers))
            check_bins_budget(len(mappers), n_local,
                              np.dtype(dtype).itemsize,
                              "Dense (unbundled) streaming load")
            bins = np.empty((len(mappers), n_local), dtype=dtype)
        else:
            dtype = bins_dtype(int(plan.slot_bins.max()))
            check_bins_budget(plan.num_slots, n_local,
                              np.dtype(dtype).itemsize,
                              "Bundled streaming load")
            bins = np.zeros((plan.num_slots, n_local), dtype=dtype)
        if not sparse_route:
            label = np.empty(n_local, dtype=np.float32)
            weights = (np.empty(n_local, dtype=np.float32)
                       if weight_idx >= 0 else None)
            qid = (np.empty(n_local, dtype=np.float64)
                   if group_idx >= 0 else None)
            bundle_conflicts = 0
            # double-buffered: the prefetch thread parses block k+1 while
            # this loop bins block k (pipeline_reader.h:18-70)
            from .streaming import prefetch_blocks
            for start, block in prefetch_blocks(
                    iter_blocks(filename, fmt, cfg.has_header, num_cols)):
                end = start + len(block)
                if start >= hi:
                    break  # past this rank's range: skip the rest
                s0, e0 = max(start, lo), min(end, hi)
                if e0 <= s0:
                    continue  # block before this rank's range
                block = block[s0 - start:e0 - start]
                ls, le = s0 - lo, e0 - lo   # local write positions
                label[ls:le] = block[:, label_idx]
                feats_block = block[:, feat_cols]
                if weights is not None:
                    weights[ls:le] = feats_block[:, weight_idx]
                if qid is not None:
                    qid[ls:le] = feats_block[:, group_idx]
                for u, j in enumerate(real_idx):
                    col = mappers[u].value_to_bin(feats_block[:, j])
                    if plan is None:
                        bins[u, ls:le] = col.astype(dtype)
                    else:
                        s = plan.feat_slot[u]
                        off = plan.feat_offset[u]
                        seg = bins[s, ls:le]
                        nz = col > 0
                        bundle_conflicts += int((nz & (seg != 0)).sum())
                        write = nz & (seg == 0)
                        seg[write] = (col[write] + off).astype(dtype)
        if bundle_conflicts:
            Log.warning("Feature bundling: %d conflicting cells kept their "
                        "first member's bin", bundle_conflicts)

        ds = CoreDataset()
        ds.num_total_features = num_feats
        ds.feature_names = (list(feat_names) if feat_names is not None
                            else [f"Column_{i}" for i in range(num_feats)])
        ds.bins = bins
        ds.bundle_plan = plan
        ds.bin_mappers = mappers
        ds.used_feature_map = used_map
        ds.real_feature_idx = np.asarray(real_idx, dtype=np.int32)
        ds.label_idx = label_idx

        meta = Metadata(n_local)
        meta.set_label(label)
        if weights is not None:
            meta.set_weights(weights)
        if qid is not None:
            meta.set_query(_qid_to_counts(qid))
        meta.load_side_files(filename)
        ds.metadata = meta
        if rank_filter:
            from ..parallel.distributed import partition_rows
            ds.global_num_data = n
            ds.local_rows_max = max(
                partition_rows(n, r, num_machines)[1]
                - partition_rows(n, r, num_machines)[0]
                for r in range(num_machines))
            Log.info("Rank %d/%d streamed rows [%d, %d) of %d (two-round)",
                     rank, num_machines, lo, hi, n)
        else:
            # baseline distribution over the full stored matrix (a
            # rank-filtered block would profile one shard's slice —
            # skip until the pod-scale mesh gathers global profiles)
            from .profile import DatasetProfile, profiling_enabled
            if profiling_enabled():
                ds.profile = DatasetProfile.from_dataset(ds)
        Log.info("Number of data: %d, number of features: %d (two-round)",
                 n_local, len(mappers))
        return ds

    def _stream_sparse_libsvm(self, filename, mappers, used_map, plan,
                              n_local, lo, hi):
        """Round two over LibSVM triplet blocks: O(block nnz) transient
        memory, and the ONLY (rows x cols) allocation is the stored bin
        matrix itself — (slots, N) when bundling engaged. Implicit
        zeros are never touched: each stored row is pre-filled with its
        feature's zero bin (bundle members have zero-bin 0 by the
        plan's candidate rule), so only nonzero entries are binned.
        The reference's equivalent storage is the delta-encoded nonzero
        list of src/io/sparse_bin.hpp:17-331."""
        cfg = self.config
        f_used = len(mappers)
        if plan is None:
            dtype = bins_dtype(max(m.num_bin for m in mappers))
            check_bins_budget(f_used, n_local, np.dtype(dtype).itemsize,
                              "Dense (unbundled) sparse-LibSVM load")
            bins = np.zeros((f_used, n_local), dtype=dtype)
            members = None
            for u, m in enumerate(mappers):
                b0 = int(m.value_to_bin(np.zeros(1))[0])
                if b0:
                    bins[u, :] = b0
        else:
            dtype = bins_dtype(int(plan.slot_bins.max()))
            check_bins_budget(plan.num_slots, n_local,
                              np.dtype(dtype).itemsize,
                              "Bundled sparse-LibSVM load")
            bins = np.zeros((plan.num_slots, n_local), dtype=dtype)
            members = np.bincount(plan.feat_slot, minlength=plan.num_slots)
            for u, m in enumerate(mappers):
                s = int(plan.feat_slot[u])
                if members[s] == 1:
                    b0 = int(m.value_to_bin(np.zeros(1))[0])
                    if b0:
                        bins[s, :] = b0
        label = np.empty(n_local, dtype=np.float32)
        conflicts = 0
        from .streaming import iter_sparse_blocks, prefetch_blocks
        for start, lab, rows, cols, vals in prefetch_blocks(
                iter_sparse_blocks(filename, cfg.has_header)):
            end = start + len(lab)
            if start >= hi:
                break  # past this rank's range: skip the rest
            s0, e0 = max(start, lo), min(end, hi)
            if e0 <= s0:
                continue  # block before this rank's range
            rlo, rhi = s0 - start, e0 - start
            label[s0 - lo:e0 - lo] = lab[rlo:rhi]
            keep = (rows >= rlo) & (rows < rhi)
            r = rows[keep] - rlo + (s0 - lo)   # local row positions
            c = cols[keep]
            # aligned (valid) files may mention feature ids past the
            # train set's feature space: those are simply unused
            u_arr = np.where(c < len(used_map),
                             used_map[np.minimum(c, len(used_map) - 1)],
                             np.int32(-1))
            v = np.nan_to_num(vals[keep], nan=0.0)
            used = u_arr >= 0
            r, v, u_arr = r[used], v[used], u_arr[used]
            # group entries by used feature, ASCENDING u: bundle
            # conflicts keep the first (lowest-u) member's bin, the
            # same rule as the dense routes
            order = np.argsort(u_arr, kind="stable")
            r, v, u_arr = r[order], v[order], u_arr[order]
            bounds = np.flatnonzero(np.diff(u_arr)) + 1
            starts = np.concatenate([[0], bounds])
            ends = np.concatenate([bounds, [len(u_arr)]])
            for g0, g1 in zip(starts, ends):
                if g1 <= g0:
                    continue
                u = int(u_arr[g0])
                b = mappers[u].value_to_bin(v[g0:g1]).astype(np.int64)
                rr = r[g0:g1]
                if plan is None:
                    bins[u, rr] = b.astype(dtype)
                    continue
                s = int(plan.feat_slot[u])
                if members[s] == 1:
                    bins[s, rr] = b.astype(dtype)
                    continue
                off = int(plan.feat_offset[u])
                nz = b > 0
                rnz = rr[nz]
                clash = bins[s, rnz] != 0
                conflicts += int(clash.sum())
                w = ~clash
                bins[s, rnz[w]] = (b[nz][w] + off).astype(dtype)
        if conflicts:
            Log.warning("Feature bundling: %d conflicting cells kept "
                        "their first member's bin", conflicts)
        return bins, label

    def _load_sparse_aligned(self, filename, train_ds) -> CoreDataset:
        """O(nnz) valid-set LibSVM load with the TRAIN mappers + bundle
        plan (the sparse analog of the dense aligned path below)."""
        from .streaming import count_rows
        cfg = self.config
        # only the row count is needed here (the train set fixed the
        # feature space) — skip scan_file's max-feature-id token pass
        n = count_rows(filename, cfg.has_header)
        if n == 0:
            Log.fatal("Data file %s is empty", str(filename))
        bins, label = self._stream_sparse_libsvm(
            filename, train_ds.bin_mappers, train_ds.used_feature_map,
            train_ds.bundle_plan, n, 0, n)
        ds = CoreDataset()
        ds.num_total_features = train_ds.num_total_features
        ds.label_idx = train_ds.label_idx
        ds.feature_names = train_ds.feature_names
        ds.bin_mappers = train_ds.bin_mappers
        ds.used_feature_map = train_ds.used_feature_map
        ds.real_feature_idx = train_ds.real_feature_idx
        ds.bundle_plan = train_ds.bundle_plan
        ds.bins = bins.astype(train_ds.stored_bins_dtype, copy=False)
        meta = Metadata(n)
        meta.set_label(label)
        meta.load_side_files(filename)
        ds.metadata = meta
        return ds

    # --------------------------------------------------------- from matrix
    def construct_from_matrix(self, data, label=None, reference=None,
                              categorical_features=()) -> CoreDataset:
        """In-memory path (c_api.cpp LGBM_DatasetCreateFromMat:268-315).
        `data` may also be a column source (CscColumns): sparse inputs
        bin column-by-column, never densified (c_api.cpp:317-427)."""
        if is_column_source(data):
            meta = Metadata(data.n)
            if label is not None:
                meta.set_label(label)
            if reference is not None:
                return self._bin_with_mappers(data, reference, meta)
            categorical = set(int(c) for c in categorical_features)
            return self._maybe_spill(
                self._construct(data, None, set(), categorical, meta))
        data = np.ascontiguousarray(np.asarray(data, dtype=np.float32))
        data = np.nan_to_num(data, nan=0.0)
        meta = Metadata(data.shape[0])
        if label is not None:
            meta.set_label(label)
        if reference is not None:
            return self._bin_with_mappers(data, reference, meta)
        categorical = set(int(c) for c in categorical_features)
        return self._maybe_spill(
            self._construct(data, None, set(), categorical, meta))

    def _maybe_spill(self, ds):
        """out_of_core on the in-memory (matrix) path: spill the freshly
        binned dataset into a block store and train from disk. Unlike
        the file path (which streams and never materializes the matrix),
        this bins in RAM first — it bounds TRAINING residency, not
        construction's. `ooc_dir` picks the store directory; default is
        a fresh temp dir (no reuse signature exists for an anonymous
        matrix)."""
        cfg = self.config
        if not getattr(cfg, "out_of_core", False):
            return ds
        import tempfile
        from ..data.block_store import effective_block_rows, spill_core_dataset
        anonymous = not cfg.ooc_dir
        directory = cfg.ooc_dir or tempfile.mkdtemp(
            prefix="lightgbm_tpu_blocks_")
        out = spill_core_dataset(ds, directory, effective_block_rows(cfg),
                                 verify=cfg.ooc_verify)
        if anonymous:
            # an unnamed spill dir has no reuse identity — reclaim the
            # full dataset's disk bytes when the dataset object dies
            # instead of leaking them in /tmp run after run
            import shutil
            import weakref
            weakref.finalize(out, shutil.rmtree, directory,
                             ignore_errors=True)
        return out

    # ------------------------------------------------------------ internals
    def _resolve_label_idx(self, names, fmt):
        """Label column resolution (parser semantics; LibSVM labels are
        always column 0). Shared by the two-round streaming path and the
        block-store builder (data/block_store.py)."""
        cfg = self.config
        if fmt == "libsvm" or cfg.label_column == "":
            return 0
        s = str(cfg.label_column)
        if s.startswith("name:"):
            if names is None or s[5:] not in names:
                Log.fatal("Could not find label column %s in data file",
                          s[5:])
            return names.index(s[5:])
        return int(s)

    def _resolve_columns(self, names, num_cols):
        """weight/group/ignore/categorical column resolution. Indices do not
        count the label column (config.h:116-131)."""
        cfg = self.config

        def resolve(spec):
            if spec == "" or spec is None:
                return -1
            s = str(spec)
            if s.startswith("name:"):
                if names is None:
                    Log.fatal("Cannot use name: column selector without header")
                return names.index(s[5:])
            return int(s)

        weight_idx = resolve(cfg.weight_column)
        group_idx = resolve(cfg.group_column)
        ignore = set()
        if cfg.ignore_column:
            for tok in str(cfg.ignore_column).split(","):
                idx = resolve(tok)
                if idx >= 0:
                    ignore.add(idx)
        categorical = set()
        if cfg.categorical_column:
            for tok in str(cfg.categorical_column).split(","):
                idx = resolve(tok)
                if idx >= 0:
                    categorical.add(idx)
        return weight_idx, group_idx, ignore, categorical

    def _sample_rows(self, n):
        cfg = self.config
        cnt = min(cfg.bin_construct_sample_cnt, n)
        if cnt == n:
            return np.arange(n, dtype=np.int64)
        rnd = Random(cfg.data_random_seed)
        return rnd.sample(n, cnt).astype(np.int64)

    def _make_mappers(self, sample_col, num_total, ignore, categorical):
        """Bin-mapper construction from sampled rows
        (ConstructBinMappersFromTextData, dataset_loader.cpp:612-760).
        `sample_col(j)` -> the j-th column's sampled values."""
        cfg = self.config
        used_map = np.full(num_total, -1, dtype=np.int32)
        mappers, real_idx = [], []
        for j in range(num_total):
            if j in ignore:
                continue
            col_sample = sample_col(j).astype(np.float64)
            nonzero = col_sample[np.abs(col_sample) > ZERO_THRESHOLD]
            btype = CATEGORICAL if j in categorical else NUMERICAL
            m = BinMapper().find_bin(nonzero, len(col_sample), cfg.max_bin, btype)
            if m.is_trivial:
                Log.warning("Ignoring Column_%d , only has one value", j)
                continue
            used_map[j] = len(mappers)
            real_idx.append(j)
            mappers.append(m)
        if not mappers:
            Log.fatal("Cannot construct Dataset since there are no useful features. "
                      "It should be at least two unique rows.")
        return mappers, used_map, real_idx

    def _construct(self, feats, names, ignore, categorical, meta) -> CoreDataset:
        """Bin-mapper construction + feature extraction
        (ConstructBinMappersFromTextData + ExtractFeatures, dataset_loader.cpp:612-841).

        `feats` is a dense (N, F) matrix or any column source with
        .n / .num_total / .col(j) (sparse FFI inputs bin one column at a
        time and never materialize the dense raw matrix, the TPU-side
        analog of c_api.cpp:317-427's row-iterator construction)."""
        cfg = self.config
        src = feats if is_column_source(feats) else DenseColumns(feats)
        n, num_total = src.n, src.num_total
        sample_idx = self._sample_rows(n)

        def sample_col(j):
            return src.col(j)[sample_idx]

        ds = CoreDataset()
        ds.num_total_features = num_total
        ds.feature_names = (list(names) if names is not None
                            else [f"Column_{i}" for i in range(num_total)])

        mappers, used_map, real_idx = self._make_mappers(
            sample_col, num_total, ignore, categorical)

        # exclusive feature bundling: sparse columns share dense slots
        # (io/bundling.py; replaces the reference's sparse_bin storage)
        from .bundling import plan_bundles, build_stored_matrix
        plan = None
        if cfg.is_enable_sparse:
            # per-column callable: planning a wide-sparse input never
            # builds the dense (F, sample) bins stack
            plan = plan_bundles(
                mappers,
                lambda u: mappers[u].value_to_bin(sample_col(real_idx[u])),
                enable=True, max_conflict_rate=cfg.max_conflict_rate)
            if plan.is_identity:
                plan = None

        if plan is None:
            dtype = bins_dtype(max(m.num_bin for m in mappers))
            check_bins_budget(len(real_idx), n, np.dtype(dtype).itemsize,
                              "Dense (unbundled) dataset construction")
            dev_bins = (_bin_dense_on_device(src._m,
                                             np.asarray(real_idx),
                                             mappers, dtype)
                        if isinstance(src, DenseColumns) else None)
            ds.bins = dev_bins if dev_bins is not None else np.stack(
                _bin_columns_threaded(
                    lambda u: mappers[u].value_to_bin(
                        src.col(real_idx[u])).astype(dtype),
                    len(real_idx)), axis=0)
        else:
            dtype = bins_dtype(int(plan.slot_bins.max()))
            check_bins_budget(plan.num_slots, n, np.dtype(dtype).itemsize,
                              "Bundled dataset construction")
            ds.bins = build_stored_matrix(
                plan,
                lambda u: mappers[u].value_to_bin(src.col(real_idx[u])),
                dtype)
            ds.bundle_plan = plan
        ds.bin_mappers = mappers
        ds.used_feature_map = used_map
        ds.real_feature_idx = np.asarray(real_idx, dtype=np.int32)
        ds.metadata = meta
        # baseline distribution: one bincount pass over the fresh bin
        # matrix (+ NaN counts where the raw matrix is at hand) — the
        # training-time half of the serving drift story
        from .profile import DatasetProfile, count_missing, profiling_enabled
        if profiling_enabled():
            missing = (count_missing(src._m, ds.real_feature_idx)
                       if isinstance(src, DenseColumns) else None)
            ds.profile = DatasetProfile.from_dataset(ds, missing=missing)
        Log.info("Number of data: %d, number of features: %d", n, len(mappers))
        return ds

    def _bin_with_mappers(self, feats, ref_ds: CoreDataset, meta) -> CoreDataset:
        src = feats if is_column_source(feats) else DenseColumns(feats)
        ds = CoreDataset()
        ds.num_total_features = ref_ds.num_total_features
        ds.label_idx = ref_ds.label_idx
        ds.feature_names = ref_ds.feature_names
        ds.bin_mappers = ref_ds.bin_mappers
        ds.used_feature_map = ref_ds.used_feature_map
        ds.real_feature_idx = ref_ds.real_feature_idx
        if src.num_total < ref_ds.num_total_features:
            Log.fatal("Validation data has fewer features than training data")
        real = ref_ds.real_feature_idx
        mappers = ref_ds.bin_mappers
        if ref_ds.bundle_plan is not None:
            # valid sets share the train plan so a wide-sparse valid set
            # stores the same O(slots x N) matrix (scoring and traversal
            # decode slots exactly like the train set's)
            from .bundling import build_stored_matrix
            check_bins_budget(ref_ds.bundle_plan.num_slots, src.n,
                              ref_ds.stored_bins_dtype.itemsize,
                              "Bundled aligned (valid set) construction")
            ds.bins = build_stored_matrix(
                ref_ds.bundle_plan,
                lambda u: mappers[u].value_to_bin(src.col(real[u])),
                ref_ds.stored_bins_dtype)
            ds.bundle_plan = ref_ds.bundle_plan
            ds.metadata = meta
            return ds
        check_bins_budget(len(mappers), src.n,
                          ref_ds.stored_bins_dtype.itemsize,
                          "Aligned (valid set) dataset construction")
        cols = _bin_columns_threaded(
            lambda u: mappers[u].value_to_bin(
                src.col(real[u])).astype(ref_ds.stored_bins_dtype),
            len(mappers))
        ds.bins = np.stack(cols, axis=0)
        ds.metadata = meta
        return ds

    def _attach_init_score(self, ds):
        """Continued-training init scores via predictor hook
        (application.cpp:108-115)."""
        if self.predict_fun is not None and ds.metadata.init_score is None:
            raw = self.predict_fun(ds)
            ds.metadata.set_init_score(np.asarray(raw, dtype=np.float64).reshape(-1, order="F"))
