"""Metadata: labels, weights, query boundaries, init scores.

Reference: include/LightGBM/dataset.h:36-246, src/io/metadata.cpp.
Side files `<data>.weight`, `<data>.query`, `<data>.init` are auto-loaded
(metadata.cpp:382-457). Query weights are derived when both weights and
queries exist (sum of weights per query / query count).
"""

import os

import numpy as np

from ..utils.log import Log

# per-row side files auto-loaded next to the data file; anything that
# partitions rows (io/dataset.py rank filtering) must treat data with
# ANY of these as global-length
SIDE_FILE_EXTS = (".weight", ".query", ".init")


class Metadata:
    def __init__(self, num_data=0):
        self.num_data = int(num_data)
        self.label = np.zeros(self.num_data, dtype=np.float32)
        self.weights = None            # (N,) float32 or None
        self.query_boundaries = None   # (num_queries+1,) int32 or None
        self.query_weights = None
        self.init_score = None         # (N*num_class,) float64 or None

    # ------------------------------------------------------------ side files
    def load_side_files(self, data_filename):
        wf = str(data_filename) + SIDE_FILE_EXTS[0]
        qf = str(data_filename) + SIDE_FILE_EXTS[1]
        inf = str(data_filename) + SIDE_FILE_EXTS[2]
        if os.path.exists(wf):
            self.set_weights(np.loadtxt(wf, dtype=np.float32, ndmin=1))
            Log.info("Loading weights...")
        if os.path.exists(qf):
            counts = np.loadtxt(qf, dtype=np.int64, ndmin=1)
            self.set_query(counts)
            Log.info("Loading query boundaries...")
        if os.path.exists(inf):
            self.set_init_score(np.loadtxt(inf, dtype=np.float64, ndmin=1))
            Log.info("Loading initial scores...")

    # --------------------------------------------------------------- setters
    def set_label(self, label):
        label = np.asarray(label, dtype=np.float32).reshape(-1)
        if self.num_data and len(label) != self.num_data:
            Log.fatal("Length of label is not same with #data")
        self.label = label
        self.num_data = len(label)

    def set_weights(self, weights):
        if weights is None:
            self.weights = None
            return
        weights = np.asarray(weights, dtype=np.float32).reshape(-1)
        if self.num_data and len(weights) != self.num_data:
            Log.fatal("Length of weights is not same with #data")
        self.weights = weights
        self._maybe_query_weights()

    def set_query(self, group):
        """group: per-query doc counts (the `.query` file / `group` field)."""
        if group is None:
            self.query_boundaries = None
            return
        group = np.asarray(group, dtype=np.int64).reshape(-1)
        bounds = np.zeros(len(group) + 1, dtype=np.int32)
        np.cumsum(group, out=bounds[1:])
        if self.num_data and bounds[-1] != self.num_data:
            Log.fatal("Sum of query counts (%d) is not same with #data (%d)",
                      int(bounds[-1]), self.num_data)
        self.query_boundaries = bounds
        self._maybe_query_weights()

    def set_init_score(self, init_score):
        if init_score is None:
            self.init_score = None
            return
        self.init_score = np.asarray(init_score, dtype=np.float64).reshape(-1)

    def _maybe_query_weights(self):
        # metadata.cpp: query weight = mean of record weights inside the query
        if self.weights is not None and self.query_boundaries is not None:
            nq = len(self.query_boundaries) - 1
            sums = np.add.reduceat(self.weights, self.query_boundaries[:-1])
            cnts = np.diff(self.query_boundaries)
            self.query_weights = (sums / np.maximum(cnts, 1)).astype(np.float32)

    @property
    def num_queries(self):
        if self.query_boundaries is None:
            return 0
        return len(self.query_boundaries) - 1

    def subset(self, indices):
        """Row subset preserving side data (used by Dataset.subset / cv)."""
        indices = np.asarray(indices, dtype=np.int64)
        out = Metadata(len(indices))
        out.label = self.label[indices]
        if self.weights is not None:
            out.weights = self.weights[indices]
        if self.init_score is not None:
            ncls = len(self.init_score) // max(self.num_data, 1)
            parts = [self.init_score[k * self.num_data + indices] for k in range(ncls)]
            out.init_score = np.concatenate(parts)
        # queries: only valid when indices keep whole queries in order; the
        # reference has the same constraint (metadata.cpp CheckOrPartition).
        if self.query_boundaries is not None:
            qb = self.query_boundaries
            qid = np.searchsorted(qb, indices, side="right") - 1
            keep, first_pos = np.unique(qid, return_index=True)
            counts = np.bincount(qid - qid.min(), minlength=len(keep))
            counts = counts[counts > 0]
            out.set_query(counts)
        out._maybe_query_weights()
        return out

    def to_dict(self):
        d = {"label": self.label, "num_data": self.num_data}
        if self.weights is not None:
            d["weights"] = self.weights
        if self.query_boundaries is not None:
            d["query_boundaries"] = self.query_boundaries
        if self.init_score is not None:
            d["init_score"] = self.init_score
        return d

    @classmethod
    def from_dict(cls, d):
        m = cls(int(d["num_data"]))
        m.label = np.asarray(d["label"], dtype=np.float32)
        if "weights" in d:
            m.weights = np.asarray(d["weights"], dtype=np.float32)
        if "query_boundaries" in d:
            m.query_boundaries = np.asarray(d["query_boundaries"], dtype=np.int32)
        if "init_score" in d:
            m.init_score = np.asarray(d["init_score"], dtype=np.float64)
        m._maybe_query_weights()
        return m
