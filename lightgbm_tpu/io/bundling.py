"""Exclusive feature bundling: sparse columns share dense bin slots.

Reference capability being replaced: src/io/sparse_bin.hpp:17-331 and
ordered_sparse_bin.hpp:25-133 store sparse features as (index, bin)
pairs, auto-selected at sparse_rate >= 0.8 (src/io/bin.cpp:291-302).
Those are CPU pointer-chasing layouts; on TPU the histogram kernel
wants one dense integer matrix. Instead of storing a mostly-zero dense
row per sparse feature, mutually-exclusive sparse features are BUNDLED
into one shared row: member i's nonzero bins 1..nb_i-1 occupy the slot
range [off_i+1, off_i+nb_i-1], slot bin 0 means "every member at its
zero bin". A 10^4-column one-hot-ish dataset collapses to tens of
stored rows, shrinking both HBM and histogram passes by the same
factor.

Training stays EXACT for conflict-free bundles: the (S, B, 3) stored
histogram expands to per-feature virtual histograms by gathers (member
ranges) plus a subtraction for bin 0 (slot total minus member range —
exclusivity puts every other member's row at the member's zero bin),
and the split scan / model see only ORIGINAL feature ids. Rows that
violate exclusivity (conflicts) keep the first member's bin, the same
tolerance as the greedy bundling literature; planning happens on the
binning sample and conflicts are counted + logged during the full pass.
"""

import numpy as np

from ..utils.log import Log

SPARSE_THRESHOLD = 0.8   # bin.cpp:291-302 auto-sparse threshold
MAX_SLOT_BINS = 256      # keep stored histogram width = one bin tile


class BundlePlan:
    """Static description: stored slot + bin offset per virtual feature.
    `conflict_rate` records the max_conflict_rate the plan was built
    with, so a binary cache holding a tolerant (approximate) plan is
    not silently reused by an exact-bundling config."""

    def __init__(self, feat_slot, feat_offset, slot_bins, num_slots,
                 conflict_rate=0.0):
        self.feat_slot = np.asarray(feat_slot, dtype=np.int32)      # (F,)
        self.feat_offset = np.asarray(feat_offset, dtype=np.int32)  # (F,)
        self.slot_bins = np.asarray(slot_bins, dtype=np.int32)      # (S,)
        self.num_slots = int(num_slots)
        self.conflict_rate = float(conflict_rate)

    @property
    def is_identity(self):
        return self.num_slots == len(self.feat_slot) and \
            bool((self.feat_offset == 0).all())

    def to_dict(self):
        return {"feat_slot": self.feat_slot, "feat_offset": self.feat_offset,
                "slot_bins": self.slot_bins,
                "num_slots": np.asarray(self.num_slots),
                "conflict_rate": np.asarray(self.conflict_rate)}

    @classmethod
    def from_dict(cls, d):
        return cls(d["feat_slot"], d["feat_offset"], d["slot_bins"],
                   int(d["num_slots"]),
                   float(d.get("conflict_rate", 0.0)))


def plan_bundles(mappers, sample_bins, enable=True, max_conflict_rate=0.0):
    """Greedy bundling on the binning sample.

    Args:
      mappers: per (used) feature BinMapper.
      sample_bins: (F, S_rows) int bins of the sample rows, OR a
        callable j -> (S_rows,) bins so a wide-sparse dataset plans in
        O(one column + bundles x S_rows) memory instead of the dense
        (F, S_rows) stack (the planning analog of the reference never
        densifying sparse features, src/io/sparse_bin.hpp:17-331).
      enable: config is_enable_sparse.
      max_conflict_rate: fraction of sample rows a bundle may hold in
        conflict (conflicting cells keep the FIRST member's bin at
        materialization). 0.0 keeps the exact greedy-EFB rule:
        perfectly-exclusive features only. Near-exclusive wide data
        (sparse text) needs a small tolerance to bundle at all — the
        capacity the reference v0 gets from per-feature sparse bins
        (sparse_bin.hpp) without any bundling.

    Returns a BundlePlan (identity when nothing bundles).
    """
    f = len(mappers)
    col_bins = sample_bins if callable(sample_bins) \
        else (lambda j: sample_bins[j])
    identity = BundlePlan(np.arange(f), np.zeros(f, np.int32),
                          [m.num_bin for m in mappers], f)
    if not enable or f == 0:
        return identity

    candidates = []
    for j, m in enumerate(mappers):
        # numerical, zero maps to bin 0, genuinely sparse
        if (m.bin_type == 0 and m.sparse_rate >= SPARSE_THRESHOLD
                and int(m.value_to_bin(np.zeros(1))[0]) == 0):
            candidates.append(j)
    if len(candidates) < 2:
        return identity

    nnz = {j: np.count_nonzero(col_bins(j)) for j in candidates}
    order = sorted(candidates, key=lambda j: -nnz[j])
    # First-fit greedy with a vectorized signature prefilter. Occupancy
    # is bit-packed (cnt/8 bytes per bundle) and the first SIG bytes
    # double as a per-bundle signature: a signature hit IS a real
    # conflict on those rows (never a false positive), so one (B, SIG)
    # AND prunes almost every conflicting bundle and the exact packed
    # check runs only on survivors — same packing as the naive
    # O(F x B x cnt) loop, at wide-sparse (news20-like) planning cost
    # O(F x B x SIG).
    cnt = len(col_bins(order[0]))
    SIG = min(64, (cnt + 7) // 8)
    cap = MAX_SLOT_BINS - 1
    budget = int(max_conflict_rate * cnt)
    max_b = len(order)
    sig_mat = np.zeros((max_b, SIG), np.uint8)
    used_arr = np.zeros(max_b, np.int64)
    conf_arr = np.zeros(max_b, np.int64)   # conflicts accrued per bundle
    occ = []         # per-bundle packed occupancy, (cnt/8,) uint8
    members_l = []   # per-bundle member lists
    popcount = np.unpackbits(np.arange(256, dtype=np.uint8)[:, None],
                             axis=1).sum(axis=1).astype(np.int64)
    for j in order:
        col_nz = col_bins(j) > 0
        cp = np.packbits(col_nz)
        csig = cp[:SIG]
        nb = mappers[j].num_bin
        b = len(occ)
        placed = -1
        if b and budget == 0:
            # exact mode: a signature hit IS a real conflict (the first
            # SIG bytes are real rows) — boolean any() suffices and is
            # the planning hot path every default-config run takes
            viable = ~((sig_mat[:b] & csig).any(axis=1)) \
                & (used_arr[:b] + (nb - 1) <= cap)
            for idx in np.flatnonzero(viable):
                if not (occ[idx] & cp).any():
                    placed = int(idx)
                    break
        elif b:
            # tolerant mode: signature overlap popcount is an exact
            # LOWER bound on the real overlap, so bundles it alone
            # pushes past budget are rejected without the full check
            sig_lb = popcount[sig_mat[:b] & csig].sum(axis=1)
            viable = (conf_arr[:b] + sig_lb <= budget) \
                & (used_arr[:b] + (nb - 1) <= cap)
            for idx in np.flatnonzero(viable):
                overlap = int(popcount[occ[idx] & cp].sum())
                if conf_arr[idx] + overlap <= budget:
                    placed = int(idx)
                    conf_arr[idx] += overlap
                    break
        if placed >= 0:
            members_l[placed].append(j)
            occ[placed] |= cp
            sig_mat[placed] |= csig
            used_arr[placed] += nb - 1
        else:
            members_l.append([j])
            occ.append(cp)
            sig_mat[b] = csig
            used_arr[b] = nb - 1

    bundles = [(m,) for m in members_l if len(m) >= 2]
    if not bundles:
        return identity

    bundled = set()
    feat_slot = np.zeros(f, np.int32)
    feat_offset = np.zeros(f, np.int32)
    slot_bins = []
    slot_id = 0
    for (members,) in bundles:
        off = 0
        for j in members:
            bundled.add(j)
            feat_slot[j] = slot_id
            feat_offset[j] = off
            off += mappers[j].num_bin - 1
        slot_bins.append(off + 1)
        slot_id += 1
    for j in range(f):
        if j not in bundled:
            feat_slot[j] = slot_id
            feat_offset[j] = 0
            slot_bins.append(mappers[j].num_bin)
            slot_id += 1
    Log.info("Bundled %d sparse features into %d slots (%d stored rows "
             "for %d features)", len(bundled), len(bundles), slot_id, f)
    return BundlePlan(feat_slot, feat_offset, slot_bins, slot_id,
                      conflict_rate=max_conflict_rate)


def build_stored_matrix(plan, bin_cols, dtype):
    """Full-data pass: write per-feature bin columns into their slots.
    `bin_cols(j)` -> (N,) int bins of virtual feature j. Conflicting rows
    keep the first member's bin (greedy-EFB tolerance)."""
    f = len(plan.feat_slot)
    col0 = bin_cols(0)
    n = len(col0)
    stored = np.zeros((plan.num_slots, n), dtype=dtype)
    conflicts = 0
    for j in range(f):
        s = plan.feat_slot[j]
        off = plan.feat_offset[j]
        col = col0 if j == 0 else bin_cols(j)
        nz = col > 0
        taken = stored[s] > 0
        clash = nz & taken
        conflicts += int(clash.sum())
        write = nz & ~taken
        stored[s, write] = (col[write] + off).astype(dtype)
    if conflicts:
        Log.warning("Feature bundling: %d conflicting cells kept their "
                    "first member's bin", conflicts)
    return stored


def expansion_maps(plan, mappers, b_virtual):
    """Static gather maps for stored->virtual histogram expansion.

    Returns (src_idx (F, b_virtual) int32 into the flattened
    (S*B_stored (+1 zero pad),) stored histogram, slot_of (F,)):
      hist_v[f, b] = hist_s_flat[src_idx[f, b]]        for b >= 1
      hist_v[f, 0] = slot_total[slot_of[f]] - sum_b>=1 hist_v[f, b]
    """
    f = len(plan.feat_slot)
    b_stored = int(plan.slot_bins.max())
    pad = plan.num_slots * b_stored  # index of an always-zero pad cell
    src = np.full((f, b_virtual), pad, dtype=np.int32)
    for j in range(f):
        nb = mappers[j].num_bin
        s, off = plan.feat_slot[j], plan.feat_offset[j]
        for b in range(1, nb):
            src[j, b] = s * b_stored + off + b
    return src, plan.feat_slot.copy()
