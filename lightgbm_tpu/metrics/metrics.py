"""Evaluation metrics.

Reference: src/metric/ (regression_metric.hpp, binary_metric.hpp,
rank_metric.hpp, multiclass_metric.hpp), factory src/metric/metric.cpp:9-28.

Metrics evaluate on host (numpy) — they run once per metric_freq
iterations on scores pulled from device, which is never the training
bottleneck. Each metric exposes `factor_to_bigger_better` for early
stopping, exactly like the reference.

Note the reference's `l2` metric reports sqrt(mean squared error)
(regression_metric.hpp:95-97 overrides AverageLoss with sqrt) — i.e. it
is RMSE under the name "l2"; reproduced as-is.
"""

import numpy as np

from ..utils.log import Log
from .dcg_calculator import DCGCalculator

K_EPSILON = 1e-15


class Metric:
    names = ()
    factor_to_bigger_better = -1.0

    def __init__(self, config=None):
        pass

    def init(self, metadata, num_data):
        self.num_data = num_data
        self.label = np.asarray(metadata.label, dtype=np.float64)
        self.weights = (None if metadata.weights is None
                        else np.asarray(metadata.weights, dtype=np.float64))
        self.sum_weights = (float(num_data) if self.weights is None
                            else float(np.sum(self.weights)))

    def eval(self, score):
        """score: flat (K*N,) host array, class-major. Returns list of doubles."""
        raise NotImplementedError

    def _weighted_mean(self, loss):
        if self.weights is None:
            return float(np.sum(loss) / self.sum_weights)
        return float(np.sum(loss * self.weights) / self.sum_weights)


class L2Metric(Metric):
    names = ("l2",)

    def eval(self, score):
        d = np.asarray(score, dtype=np.float64)[:self.num_data] - self.label
        return [float(np.sqrt(self._weighted_mean(d * d)))]


class L1Metric(Metric):
    names = ("l1",)

    def eval(self, score):
        d = np.abs(np.asarray(score, dtype=np.float64)[:self.num_data] - self.label)
        return [self._weighted_mean(d)]


class _BinaryMetric(Metric):
    def __init__(self, config):
        self.sigmoid = float(config.sigmoid)
        if self.sigmoid <= 0.0:
            Log.fatal("Sigmoid parameter %f should greater than zero", self.sigmoid)

    def _prob(self, score):
        s = np.asarray(score, dtype=np.float64)[:self.num_data]
        return 1.0 / (1.0 + np.exp(-2.0 * self.sigmoid * s))


class BinaryLoglossMetric(_BinaryMetric):
    names = ("logloss",)  # display name per binary_metric.hpp:119

    def eval(self, score):
        p = np.clip(self._prob(score), K_EPSILON, 1.0 - K_EPSILON)
        loss = np.where(self.label == 0, -np.log(1.0 - p), -np.log(p))
        return [self._weighted_mean(loss)]


class BinaryErrorMetric(_BinaryMetric):
    names = ("error",)  # display name per binary_metric.hpp:138

    def eval(self, score):
        p = self._prob(score)
        loss = np.where(p < 0.5, self.label, 1.0 - self.label)
        return [self._weighted_mean(loss)]


class AUCMetric(Metric):
    """Sort-based weighted AUC (binary_metric.hpp:145-251)."""

    names = ("auc",)
    factor_to_bigger_better = 1.0

    def eval(self, score):
        s = np.asarray(score, dtype=np.float64)[:self.num_data]
        w = self.weights if self.weights is not None else np.ones_like(s)
        order = np.argsort(-s, kind="stable")
        lab = self.label[order]
        ws = w[order]
        pos = lab * ws
        neg = (1.0 - lab) * ws
        # group ties on score: accumulate trapezoid per distinct score
        ss = s[order]
        # boundaries of equal-score groups
        new_group = np.empty(len(ss), dtype=bool)
        if len(ss):
            new_group[0] = True
            new_group[1:] = ss[1:] != ss[:-1]
        gid = np.cumsum(new_group) - 1
        ngroups = gid[-1] + 1 if len(ss) else 0
        gpos = np.bincount(gid, weights=pos, minlength=ngroups)
        gneg = np.bincount(gid, weights=neg, minlength=ngroups)
        sum_pos_before = np.concatenate([[0.0], np.cumsum(gpos)[:-1]])
        accum = float(np.sum(gneg * (gpos * 0.5 + sum_pos_before)))
        sum_pos = float(np.sum(gpos))
        if sum_pos > 0.0 and sum_pos != self.sum_weights:
            return [accum / (sum_pos * (self.sum_weights - sum_pos))]
        return [1.0]


class _MulticlassMetric(Metric):
    def __init__(self, config):
        self.num_class = int(config.num_class)

    def _probs(self, score):
        s = np.asarray(score, dtype=np.float64)
        n = self.num_data
        mat = np.stack([s[k * n:(k + 1) * n] for k in range(self.num_class)], axis=1)
        m = mat.max(axis=1, keepdims=True)
        e = np.exp(mat - m)
        return e / e.sum(axis=1, keepdims=True)  # (N, K)


class MultiLoglossMetric(_MulticlassMetric):
    names = ("multi_logloss",)

    def eval(self, score):
        p = self._probs(score)
        idx = self.label.astype(np.int64)
        pl = np.clip(p[np.arange(self.num_data), idx], K_EPSILON, None)
        return [self._weighted_mean(-np.log(pl))]


class MultiErrorMetric(_MulticlassMetric):
    names = ("multi_error",)

    def eval(self, score):
        p = self._probs(score)
        pred = np.argmax(p, axis=1)
        loss = (pred != self.label.astype(np.int64)).astype(np.float64)
        return [self._weighted_mean(loss)]


class NDCGMetric(Metric):
    """NDCG@k averaged over queries with query weights (rank_metric.hpp:16-165)."""

    factor_to_bigger_better = 1.0

    def __init__(self, config):
        self.eval_at = tuple(config.ndcg_eval_at)
        self.names = tuple(f"ndcg@{k}" for k in self.eval_at)
        self.dcg = DCGCalculator(config.label_gain)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            Log.fatal("The NDCG metric requires query information")
        self.query_boundaries = np.asarray(metadata.query_boundaries)
        self.num_queries = len(self.query_boundaries) - 1
        self.query_weights = metadata.query_weights
        from ..objectives.rank_device import PaddedQueryLayout
        self.layout = PaddedQueryLayout(self.query_boundaries, num_data)

    def eval(self, score):
        """Vectorized padded-query NDCG (one argsort for all queries)
        instead of the reference's per-query loop (rank_metric.hpp)."""
        from ..objectives.rank_device import ndcg_eval_padded
        s = np.asarray(score, dtype=np.float64)[:self.num_data]
        return ndcg_eval_padded(self.layout, self.label, self.dcg.label_gain,
                                self.eval_at, s, self.query_weights)


def create_metric(name, config):
    """Factory (metric.cpp:9-28). Returns None for unknown names."""
    name = str(name).lower()
    if name == "l2":
        return L2Metric()
    if name == "l1":
        return L1Metric()
    if name == "binary_logloss":
        return BinaryLoglossMetric(config)
    if name == "binary_error":
        return BinaryErrorMetric(config)
    if name == "auc":
        return AUCMetric(config)
    if name == "ndcg":
        return NDCGMetric(config)
    if name == "multi_logloss":
        return MultiLoglossMetric(config)
    if name == "multi_error":
        return MultiErrorMetric(config)
    return None
