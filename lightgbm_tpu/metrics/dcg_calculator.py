"""DCG/NDCG calculator.

Reference: include/LightGBM/metric.h:56-123, src/metric/dcg_calculator.cpp:13-136.
Discount LUT 1/log2(2+i) for positions up to 10000; label gains 2^i - 1.
"""

import numpy as np

K_MAX_POSITION = 10000


class DCGCalculator:
    def __init__(self, label_gain):
        self.label_gain = np.asarray(label_gain, dtype=np.float64)
        self.discount = 1.0 / np.log2(2.0 + np.arange(K_MAX_POSITION, dtype=np.float64))

    def cal_dcg_at_k(self, k, labels, scores):
        """DCG@k of `scores` ranking against relevance `labels`."""
        labels = np.asarray(labels)
        order = np.argsort(-np.asarray(scores), kind="stable")
        k = min(int(k), len(labels))
        top = labels[order[:k]].astype(np.int64)
        return float(np.sum(self.label_gain[top] * self.discount[:k]))

    def cal_maxdcg_at_k(self, k, labels):
        """Ideal DCG@k (labels sorted descending)."""
        labels = np.asarray(labels).astype(np.int64)
        srt = np.sort(self.label_gain[labels])[::-1]
        k = min(int(k), len(labels))
        return float(np.sum(srt[:k] * self.discount[:k]))
