from .metrics import Metric, create_metric
from .dcg_calculator import DCGCalculator

__all__ = ["Metric", "create_metric", "DCGCalculator"]
