"""Training callbacks.

Reference: python-package/lightgbm/callback.py:6-192. Same callback
contract: callables taking a `CallbackEnv`, ordered by `.order`, run
before each iteration when `.before_iteration` is set, else after;
`early_stopping` signals by raising `EarlyStopException`.
"""

import collections


class EarlyStopException(Exception):
    """Raised by the early_stopping callback (callback.py:6-15)."""

    def __init__(self, best_iteration):
        super().__init__()
        self.best_iteration = best_iteration


CallbackEnv = collections.namedtuple(
    "LightGBMCallbackEnv",
    ["model", "cvfolds", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"])


def _format_eval_result(value, show_stdv=True):
    """4-tuple (data, name, value, bigger_better) or 5-tuple (+std)."""
    if len(value) == 4:
        return "%s's %s:%g" % (value[0], value[1], value[2])
    if len(value) == 5:
        if show_stdv:
            return "%s's %s:%g+%g" % (value[0], value[1], value[2], value[4])
        return "%s's %s:%g" % (value[0], value[1], value[2])
    raise ValueError("Wrong metric value")


def print_evaluation(period=1, show_stdv=True):
    """Print evaluation results every `period` iterations (callback.py:40-65)."""

    def callback(env):
        if not env.evaluation_result_list or period <= 0:
            return
        if (env.iteration + 1) % period == 0:
            result = "\t".join(_format_eval_result(x, show_stdv)
                               for x in env.evaluation_result_list)
            print("[%d]\t%s" % (env.iteration + 1, result))
    callback.order = 10
    return callback


def record_evaluation(eval_result):
    """Record evaluation history into `eval_result` dict (callback.py:68-97)."""
    if not isinstance(eval_result, dict):
        raise TypeError("Eval_result should be a dictionary")
    eval_result.clear()

    def init(env):
        for item in env.evaluation_result_list:
            eval_result.setdefault(item[0], collections.defaultdict(list))

    def callback(env):
        if not eval_result:
            init(env)
        # items are 4-tuples from train() and 5-tuples (+stdv) from cv()
        for item in env.evaluation_result_list:
            eval_result[item[0]][item[1]].append(item[2])
    callback.order = 20
    return callback


def reset_parameter(**kwargs):
    """Reset parameters (e.g. learning_rate schedules) before each
    iteration (callback.py:100-129). Values are lists (indexed by round)
    or functions of the current round."""

    def callback(env):
        for key, value in kwargs.items():
            if isinstance(value, list):
                if len(value) != env.end_iteration - env.begin_iteration:
                    raise ValueError(
                        "Length of list {} has to equal to 'num_boost_round'."
                        .format(repr(key)))
                env.model.reset_parameter(
                    {key: value[env.iteration - env.begin_iteration]})
            else:
                env.model.reset_parameter(
                    {key: value(env.iteration - env.begin_iteration)})
    callback.before_iteration = True
    callback.order = 10
    return callback


def early_stopping(stopping_rounds, verbose=True):
    """Stop when no validation metric improved in `stopping_rounds`
    rounds (callback.py:132-192). Checks ALL metrics of all valid sets."""
    factor_to_bigger_better = {}
    best_score = {}
    best_iter = {}
    best_msg = {}

    def init(env):
        if not env.evaluation_result_list:
            raise ValueError("For early stopping, at least one dataset or "
                             "eval metric is required for evaluation")
        if verbose:
            print("Train until valid scores didn't improve in {} rounds."
                  .format(stopping_rounds))
        for i, ret in enumerate(env.evaluation_result_list):
            best_score[i] = float("-inf")
            best_iter[i] = 0
            best_msg[i] = ""
            factor_to_bigger_better[i] = 1.0 if ret[3] else -1.0

    def callback(env):
        if not best_score:
            init(env)
        for i, ret in enumerate(env.evaluation_result_list):
            score = ret[2] * factor_to_bigger_better[i]
            if score > best_score[i]:
                best_score[i] = score
                best_iter[i] = env.iteration
                if verbose:
                    best_msg[i] = "[%d]\t%s" % (
                        env.iteration + 1,
                        "\t".join(_format_eval_result(x)
                                  for x in env.evaluation_result_list))
            elif env.iteration - best_iter[i] >= stopping_rounds:
                if env.model is not None:
                    env.model.set_attr(best_iteration=str(best_iter[i]))
                if verbose:
                    print("Early stopping, best iteration is:")
                    print(best_msg[i])
                raise EarlyStopException(best_iter[i])
    callback.order = 30
    return callback
