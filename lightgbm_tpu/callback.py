class EarlyStopException(Exception): pass
def print_evaluation(*a, **k): pass
def record_evaluation(*a, **k): pass
def reset_parameter(*a, **k): pass
def early_stopping(*a, **k): pass
