"""Training callbacks.

Reference CONTRACT being kept (python-package/lightgbm/callback.py:6-192,
relied on by the reference's own tests and user code): callables taking
a `CallbackEnv` namedtuple with these exact fields, ordered by `.order`
(print=10, record=20, early-stop=30), run before each iteration when
`.before_iteration` is set and after it otherwise; `early_stopping`
signals by raising `EarlyStopException(best_iteration)`; console lines
keep LightGBM's `[n]\\tdata's metric:value` shape.

The implementation below is callback-objects rather than the
reference's closure style: each factory returns a small stateful class
instance whose `__call__` is the callback. State lives in attributes
(inspectable, picklable-ish) instead of captured dicts.
"""

import collections


class EarlyStopException(Exception):
    """Raised by the early_stopping callback (callback.py:6-15)."""

    def __init__(self, best_iteration):
        super().__init__()
        self.best_iteration = best_iteration


CallbackEnv = collections.namedtuple(
    "LightGBMCallbackEnv",
    ["model", "cvfolds", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"])


def _entry_to_text(entry, with_stdv=True):
    """One evaluation entry -> console text. Entries are 4-tuples
    (data, metric, value, bigger_better) from train() and 5-tuples
    (+stdv) from cv()."""
    if len(entry) == 4:
        data_name, metric_name, value = entry[0], entry[1], entry[2]
        return f"{data_name}'s {metric_name}:{value:g}"
    if len(entry) == 5:
        data_name, metric_name, value, _, stdv = entry
        if with_stdv:
            return f"{data_name}'s {metric_name}:{value:g}+{stdv:g}"
        return f"{data_name}'s {metric_name}:{value:g}"
    raise ValueError(
        f"evaluation entries must be 4- or 5-tuples, got {len(entry)}")


class _PrintEvaluation:
    def __init__(self, period, show_stdv):
        # instance attrs, not class attrs: engine._configure_callbacks
        # setdefaults 'order' into user callbacks' __dict__
        self.order = 10
        self.period = period
        self.show_stdv = show_stdv

    def __call__(self, env):
        if self.period <= 0 or not env.evaluation_result_list:
            return
        done = env.iteration + 1
        if done % self.period:
            return
        line = "\t".join(_entry_to_text(e, self.show_stdv)
                         for e in env.evaluation_result_list)
        print(f"[{done}]\t{line}")


def print_evaluation(period=1, show_stdv=True):
    """Print evaluation results every `period` iterations
    (callback.py:40-65)."""
    return _PrintEvaluation(period, show_stdv)


class _RecordEvaluation:
    def __init__(self, target):
        self.order = 20
        self.target = target

    def __call__(self, env):
        for data_name, metric_name, value, *_ in env.evaluation_result_list:
            history = self.target.setdefault(
                data_name, collections.defaultdict(list))
            history[metric_name].append(value)

    # -- checkpoint protocol (callback.checkpoint collects/restores this)
    def state_dict(self):
        return {"history": {d: {m: list(v) for m, v in h.items()}
                            for d, h in self.target.items()}}

    def load_state_dict(self, state):
        self.target.clear()
        for data_name, metrics in state.get("history", {}).items():
            history = self.target.setdefault(
                data_name, collections.defaultdict(list))
            for metric_name, values in metrics.items():
                history[metric_name] = list(values)


def record_evaluation(eval_result):
    """Record evaluation history into `eval_result` dict
    (callback.py:68-97)."""
    if not isinstance(eval_result, dict):
        raise TypeError(
            "record_evaluation needs a dict to write history into, got "
            + type(eval_result).__name__)
    eval_result.clear()
    return _RecordEvaluation(eval_result)


class _ResetParameter:
    def __init__(self, schedules):
        self.order = 10
        self.before_iteration = True
        self.schedules = schedules

    def __call__(self, env):
        round_idx = env.iteration - env.begin_iteration
        n_rounds = env.end_iteration - env.begin_iteration
        new_params = {}
        for name, schedule in self.schedules.items():
            if isinstance(schedule, list):
                if len(schedule) != n_rounds:
                    raise ValueError(
                        f"the {name!r} schedule list must have exactly "
                        f"num_boost_round (= {n_rounds}) entries")
                new_params[name] = schedule[round_idx]
            else:
                new_params[name] = schedule(round_idx)
        for name, value in new_params.items():
            env.model.reset_parameter({name: value})


def reset_parameter(**kwargs):
    """Per-round parameter schedules (e.g. learning_rate decay), applied
    before each iteration (callback.py:100-129). Values are lists
    (indexed by round) or callables of the round index."""
    return _ResetParameter(kwargs)


class _EarlyStopping:
    def __init__(self, patience, verbose):
        self.order = 30
        self.patience = patience
        self.verbose = verbose
        self.trackers = None  # per-metric [sign, best_score, best_it, msg]

    def _start(self, env):
        if not env.evaluation_result_list:
            raise ValueError("early stopping needs at least one validation "
                             "dataset and metric to watch")
        if self.verbose:
            print("Train until valid scores didn't improve in "
                  f"{self.patience} rounds.")
        self.trackers = [
            [1.0 if entry[3] else -1.0, float("-inf"), 0, ""]
            for entry in env.evaluation_result_list]

    # -- checkpoint protocol (callback.checkpoint collects/restores this)
    def state_dict(self):
        return {"trackers": [list(t) for t in self.trackers]
                if self.trackers is not None else None}

    def load_state_dict(self, state):
        trackers = state.get("trackers")
        self.trackers = ([list(t) for t in trackers]
                         if trackers is not None else None)

    def __call__(self, env):
        if self.trackers is None:
            self._start(env)
        for tracker, entry in zip(self.trackers, env.evaluation_result_list):
            sign, best, best_it, _ = tracker
            score = sign * entry[2]
            if score > best:
                tracker[1] = score
                tracker[2] = env.iteration
                if self.verbose:
                    line = "\t".join(_entry_to_text(e)
                                     for e in env.evaluation_result_list)
                    tracker[3] = f"[{env.iteration + 1}]\t{line}"
            elif env.iteration - best_it >= self.patience:
                if env.model is not None:
                    env.model.set_attr(best_iteration=str(best_it))
                if self.verbose:
                    print("Early stopping, best iteration is:")
                    print(tracker[3])
                raise EarlyStopException(best_it)


def early_stopping(stopping_rounds, verbose=True):
    """Stop when no validation metric improved in `stopping_rounds`
    rounds; checks ALL metrics of all valid sets (callback.py:132-192)."""
    return _EarlyStopping(stopping_rounds, verbose)


class _Checkpoint:
    """Periodic full-state snapshots (utils/checkpoint.py).

    `is_checkpoint` marks it for engine.train: the fused blockwise path
    keeps this callback OUT of the per-iteration replay (mid-block the
    model list already holds the whole block's trees, so a mid-block
    snapshot would capture the future) and instead fires it at block
    boundaries, clamping the block size to `period` so boundaries land
    on the snapshot cadence."""

    def __init__(self, manager, period):
        self.order = 40             # after print/record/early-stop
        self.is_checkpoint = True
        self.manager = manager
        self.period = int(period)
        self.last_saved_path = None
        self._peers = ()            # set by engine.train: stateful siblings

    def bind_peers(self, callbacks):
        """Stateful sibling callbacks (early stopping trackers, eval
        history) whose state rides inside the snapshot."""
        self._peers = tuple(cb for cb in callbacks
                            if cb is not self and hasattr(cb, "state_dict"))

    def save_now(self, booster):
        """Snapshot the booster's CURRENT state, keyed by its own
        completed-iteration count (independent of any init_model
        offset)."""
        import time
        t0 = time.time()
        state = booster.gbdt.capture_training_state()
        state["booster_attrs"] = dict(booster._attr)
        state["callback_states"] = [
            (type(cb).__name__, cb.state_dict()) for cb in self._peers]
        self.last_saved_path = self.manager.save(state, booster.gbdt.iter)
        write_s = time.time() - t0
        # supervisor heartbeats advertise the newest resumable snapshot
        # (parallel/heartbeat.py); no-op when no service is running
        from .parallel import heartbeat
        heartbeat.notify_checkpoint(booster.gbdt.iter, self.last_saved_path)
        # checkpoint write latency: registry histogram + journal event
        # (telemetry/; both no-ops shrink to dict lookups when off)
        gbdt = booster.gbdt
        gbdt.metrics.observe("checkpoint_write_s", write_s)
        if gbdt.journal is not None:
            gbdt.journal.event("checkpoint", iteration=int(gbdt.iter),
                               path=str(self.last_saved_path),
                               write_s=round(write_s, 6))
        return self.last_saved_path

    def restore_into(self, booster, state, all_callbacks):
        """Apply a loaded snapshot: booster state, attrs, and sibling
        callback state (matched by class name, in order)."""
        booster.gbdt.restore_training_state(state)
        booster._attr = dict(state.get("booster_attrs", {}))
        saved = list(state.get("callback_states", []))
        candidates = [cb for cb in all_callbacks
                      if hasattr(cb, "load_state_dict")]
        for name, cb_state in saved:
            for cb in candidates:
                if type(cb).__name__ == name:
                    cb.load_state_dict(cb_state)
                    candidates.remove(cb)
                    break

    def __call__(self, env):
        if env.model is None:
            return  # cv folds have no single resumable state
        if self.period <= 0:
            return
        done = env.model.gbdt.iter
        if done > 0 and done % self.period == 0:
            self.save_now(env.model)


def checkpoint(directory_or_manager, period=1, keep_last_k=3):
    """Snapshot full training state every `period` iterations into a
    rotated, digest-validated checkpoint directory; resume with
    `engine.train(..., resume_from=...)`. Accepts a directory path or a
    prebuilt utils.checkpoint.CheckpointManager."""
    from .utils.checkpoint import CheckpointManager
    if isinstance(directory_or_manager, CheckpointManager):
        manager = directory_or_manager
    else:
        manager = CheckpointManager(directory_or_manager,
                                    keep_last_k=keep_last_k)
    return _Checkpoint(manager, period)
