"""CLI application: train / predict lifecycle.

Reference: include/LightGBM/application.h:25-87,
src/application/application.cpp, src/application/predictor.hpp,
src/main.cpp. Same parameter layering (command line overrides config
file, application.cpp:46-104), same data-loading order (train set with
its metrics, then aligned valid sets, application.cpp:106-184), the
same training loop with per-iteration timing (application.cpp:222-238)
and the same predict-to-TSV output (predictor.hpp:82-130).

The reference's Network::Init TCP/MPI handshake (application.cpp:189)
has no analog: parallel learners run on the JAX mesh, so
`num_machines`/`machine_list_file` select mesh width instead of opening
sockets.
"""

import os
import time

import numpy as np

from .config import Config, load_config_file, str2map
from .io.dataset import DatasetLoader
from .metrics import create_metric
from .models.gbdt import create_boosting
from .objectives import create_objective
from .utils.log import Log


class Predictor:
    """Batch prediction from a parsed data file (predictor.hpp:24-155).
    Also provides the init-score hook used for continued training
    (application.cpp:108-115)."""

    def __init__(self, boosting, is_raw_score=False, is_predict_leaf_index=False,
                 num_iteration=-1):
        self.boosting = boosting
        self.is_raw_score = is_raw_score
        self.is_predict_leaf_index = is_predict_leaf_index
        self.num_iteration = num_iteration

    def predict_matrix(self, feats):
        if self.is_predict_leaf_index:
            return self.boosting.predict_leaf_index(feats, self.num_iteration)
        if self.is_raw_score:
            return self.boosting.predict_raw(feats, self.num_iteration)
        return self.boosting.predict(feats, self.num_iteration)

    def predict_file(self, data_filename, result_filename, has_header=False,
                     label_column="", max_bad_rows=0, chunk_rows=65536):
        """Stream the input in bounded `chunk_rows`-row chunks (a
        serving-scale scoring file never materializes as one matrix)
        and append each chunk's predictions to the TSV as it lands —
        same output as the one-shot parse, O(chunk) peak memory."""
        from .io.parser import iter_text_file_chunks
        n_feat = self.boosting.max_feature_idx + 1
        n_done = 0
        with open(result_filename, "w") as fout:
            # keep_nan: a missing cell must ride the model's default-
            # direction routing (right child), exactly like a null sent
            # to the serving endpoint — not collapse to literal 0.0
            for _, feats in iter_text_file_chunks(
                    data_filename, chunk_rows, has_header=has_header,
                    label_column=label_column, max_bad_rows=max_bad_rows,
                    keep_nan=True):
                if feats.shape[1] < n_feat:
                    # LibSVM chunk width is per-chunk (trailing absent
                    # features); the model defines the true width
                    feats = np.pad(feats,
                                   ((0, 0), (0, n_feat - feats.shape[1])))
                out = np.atleast_2d(self.predict_matrix(feats))
                for row in out:
                    fout.write("\t".join(f"{v:g}"
                                         for v in np.atleast_1d(row)) + "\n")
                n_done += len(out)
        Log.info("Finished prediction of %d rows and saved result to %s",
                 n_done, str(result_filename))

    def init_score_fun(self):
        """PredictFunction used by DatasetLoader to seed init scores from a
        loaded model during continued training."""

        def predict_fun(ds):
            if ds.raw_data is None:
                Log.fatal("Cannot compute init scores without raw data")
            raw = self.boosting.predict_raw(ds.raw_data, self.num_iteration)
            return raw.T.reshape(-1)  # class-major flat
        return predict_fun


class Application:
    """CLI lifecycle (application.h:25-87)."""

    def __init__(self, argv):
        params = self._load_parameters(argv)
        self.config = Config.from_params(params)
        self.boosting = None
        self.objective = None
        self.train_data = None
        self.valid_datas = []
        self.train_metrics = []
        self.valid_metrics = []

    @staticmethod
    def _load_parameters(argv):
        """Command line `k=v` tokens override config-file entries
        (application.cpp:46-104)."""
        cmd_params = str2map(" ".join(argv))
        params = {}
        config_path = cmd_params.get("config_file", "")
        if config_path:
            params.update(load_config_file(config_path))
        params.update(cmd_params)
        params.pop("config_file", None)
        return params

    def run(self):
        start = time.time()
        if self.config.task == "train":
            self.init_train()
            self.train()
        elif self.config.task == "predict":
            self.init_predict()
            self.predict()
        else:
            Log.fatal("Unknown task: %s", self.config.task)
        Log.info("Finished, elapsed: %f seconds", time.time() - start)

    # -------------------------------------------------------------- training
    def init_train(self):
        cfg = self.config
        if cfg.telemetry and not cfg.telemetry_dir:
            # default the journal next to the other shared run state
            # (heartbeats, snapshots, restart barrier) so the whole
            # run's timeline lives in one directory
            cfg.telemetry_dir = (cfg.snapshot_dir
                                 or cfg.output_model + ".snapshots")
        if cfg.is_parallel:
            # multi-host membership (the reference's Network::Init TCP
            # handshake, application.cpp:189) -> jax.distributed
            from .parallel.distributed import init_from_config
            init_from_config(cfg)
            Log.info("Parallel training over a %d-device mesh "
                     "(tree_learner=%s)", cfg.num_machines, cfg.tree_learner)
            if cfg.telemetry_port > 0:
                # rank-offset the /trainz port so every rank of a
                # single-host gang binds (same-port ranks would
                # silently lose all but one endpoint) and the fleet
                # aggregator's targets are derivable: rank r serves on
                # telemetry_port + r (docs/Observability.md)
                import jax
                cfg.telemetry_port += jax.process_index()
        self.boosting = create_boosting(cfg.boosting_type, cfg.input_model)
        self.objective = create_objective(cfg.objective, cfg)
        self._load_data()
        if self.objective is not None:
            self.objective.init(self.train_data.metadata,
                                self.train_data.num_data)
        self.boosting.init(cfg, self.train_data, self.objective,
                           self.train_metrics)
        for vd, vm in zip(self.valid_datas, self.valid_metrics):
            self.boosting.add_valid_dataset(vd, vm)
        Log.info("Finished initializing training")

    def _load_data(self):
        """application.cpp:106-184."""
        cfg = self.config
        start = time.time()
        predict_fun = None
        if cfg.input_model:
            with open(cfg.input_model) as f:
                self.boosting.load_model_from_string(f.read())
            predictor = Predictor(self.boosting, is_raw_score=True)
            predict_fun = predictor.init_score_fun()
        import jax
        loader = DatasetLoader(cfg, predict_fun=predict_fun)
        self.train_data = loader.load_from_file(
            cfg.data, rank=jax.process_index(), num_machines=cfg.num_machines)
        if cfg.is_training_metric:
            for name in cfg.metric:
                m = create_metric(name, cfg)
                if m is not None:
                    m.init(self.train_data.metadata, self.train_data.num_data)
                    self.train_metrics.append(m)
        self.valid_datas = []
        self.valid_metrics = []
        for vfile in cfg.valid_data:
            vd = loader.load_from_file_align_with_other_dataset(
                vfile, self.train_data)
            self.valid_datas.append(vd)
            ms = []
            for name in cfg.metric:
                m = create_metric(name, cfg)
                if m is not None:
                    m.init(vd.metadata, vd.num_data)
                    ms.append(m)
            self.valid_metrics.append(ms)
        Log.info("Finished loading data in %f seconds", time.time() - start)

    def train(self):
        """application.cpp:222-238.

        With `snapshot_freq` > 0, full training state is checkpointed
        every `snapshot_freq` iterations (atomic + rotated, see
        utils/checkpoint.py) and a restart auto-resumes from the newest
        valid snapshot (`snapshot_resume`), producing the bit-identical
        model of an uninterrupted run. The fused paths clamp their
        block size to the snapshot cadence so snapshots land on block
        boundaries."""
        cfg = self.config
        tracer = self.boosting.tracer  # per-Booster (telemetry/trace.py)
        import jax
        from .parallel import heartbeat
        # shared scratch dir: snapshots, heartbeats, watchdog markers,
        # supervisor restart barrier all live under it
        snap_dir = cfg.snapshot_dir or cfg.output_model + ".snapshots"
        if cfg.heartbeat_timeout_s > 0 or cfg.collective_timeout_s > 0:
            # heartbeat publisher + peer monitor (multi-process) and/or
            # the collective watchdog (parallel/heartbeat.py): a dead or
            # straggling rank is detected within a bounded time instead
            # of hanging every survivor in a jax.lax collective forever
            heartbeat.configure(
                cfg, snap_dir, jax.process_index(), jax.process_count(),
                iteration_fn=lambda: self.boosting.iter)
        manager = None
        if cfg.snapshot_freq > 0:
            from .parallel.distributed import process_rank
            from .utils.checkpoint import CheckpointManager
            if process_rank() == 0:  # one writer on shared storage
                manager = CheckpointManager(snap_dir,
                                            keep_last_k=cfg.snapshot_keep)
            state = None
            if cfg.snapshot_resume and os.path.isdir(snap_dir):
                # every rank restores the same state (the model is
                # replicated); only rank 0 writes
                reader = manager or CheckpointManager(
                    snap_dir, keep_last_k=cfg.snapshot_keep)
                state, _ = reader.load_latest()
            import jax
            if jax.process_count() > 1:
                # agree on the resume point BEFORE the restore: the
                # multi-host restore itself runs collectives (global
                # score re-slice, models/gbdt.py), so a rank that
                # cannot see the snapshot dir must fail fast HERE —
                # otherwise its desync-check allgather below would
                # pair with the restoring ranks' restore collectives
                from jax.experimental import multihost_utils
                found = np.asarray(multihost_utils.process_allgather(
                    np.asarray([state["iter"] if state is not None
                                else -1], dtype=np.int64))).reshape(-1)
                if len({int(v) for v in found}) != 1:
                    Log.fatal("snapshot resume desync: ranks found "
                              "different snapshots (iterations %s) — "
                              "snapshot_dir (%s) must be shared "
                              "storage visible to every rank",
                              sorted(int(v) for v in found), snap_dir)
            if state is not None:
                self.boosting.restore_training_state(state)
                if self.boosting.journal is not None:
                    self.boosting.journal.event(
                        "resume", iteration=int(self.boosting.iter))
            if jax.process_count() > 1:
                # every rank must restore the SAME iteration: a rank
                # that cannot see the snapshot dir would cold-start and
                # silently desync the allreduced histograms
                from jax.experimental import multihost_utils
                iters = np.asarray(multihost_utils.process_allgather(
                    np.asarray([self.boosting.iter],
                               dtype=np.int64))).reshape(-1)
                if len({int(v) for v in iters}) != 1:
                    Log.fatal("snapshot resume desync: ranks restored "
                              "different iterations %s — snapshot_dir "
                              "(%s) must be shared storage visible to "
                              "every rank",
                              sorted(int(v) for v in iters), snap_dir)

        def maybe_snapshot():
            b = self.boosting
            if (cfg.snapshot_freq <= 0 or b.iter <= 0
                    or b.iter % cfg.snapshot_freq):
                return
            import jax
            if manager is None and jax.process_count() <= 1:
                return
            # multi-host row-sharded capture is COLLECTIVE (the global
            # train score is allgathered, models/gbdt.py), so every
            # rank captures at the cadence point; only rank 0 writes
            # timed from capture (device sync + transfer) through the
            # atomic write, matching callback._Checkpoint.save_now so
            # `checkpoint_write_s` is one comparable quantity everywhere
            t0 = time.time()
            state = b.capture_training_state()
            if manager is not None:
                path = manager.save(state, b.iter)
                write_s = time.time() - t0
                heartbeat.notify_checkpoint(b.iter, path)
                b.metrics.observe("checkpoint_write_s", write_s)
                if b.journal is not None:
                    b.journal.event("checkpoint", iteration=int(b.iter),
                                    path=str(path),
                                    write_s=round(write_s, 6))
            if jax.process_count() > 1:
                # hold every rank HERE while rank 0 writes, under a
                # guard that NAMES the snapshot barrier: otherwise the
                # peers would spend rank 0's checkpoint I/O blocked in
                # the next iteration's collective, and a slow shared-
                # storage write would fire their watchdogs with a
                # misleading hung-collective diagnosis.
                # `collective_timeout_s` must therefore also cover the
                # worst-case snapshot write (docs/Parameters.md).
                from jax.experimental import multihost_utils
                with heartbeat.collective_guard("snapshot_write_barrier"):
                    multihost_utils.process_allgather(
                        np.asarray([b.iter], dtype=np.int64))

        def snap_clamp(step):
            """Clamp a fused block so the next snapshot-cadence point
            is a block boundary."""
            if manager is None:
                return step
            b = self.boosting
            boundary = ((b.iter // cfg.snapshot_freq) + 1) * cfg.snapshot_freq
            return min(step, max(1, boundary - b.iter))
        tracer.reset()
        trace_dir = None
        if cfg.profile:
            import jax
            trace_dir = cfg.profile if isinstance(cfg.profile, str) and \
                cfg.profile not in ("1", "true") else "/tmp/lightgbm_tpu_trace"
            jax.profiler.start_trace(trace_dir)
        start = time.time()
        try:
            fused = getattr(self.boosting, "_fused_eligible", None)
            if fused is not None and fused():
                # whole boosting block as one device program
                # (gbdt.train_many); snapshotting chops it into
                # cadence-sized blocks (same trees — block size only
                # moves the host-sync points)
                b = self.boosting
                if manager is None:
                    b.train_many(cfg.num_iterations - b.iter)
                else:
                    stopped = False
                    while b.iter < cfg.num_iterations and not stopped:
                        stopped = b.train_many(
                            snap_clamp(cfg.num_iterations - b.iter))
                        maybe_snapshot()
                Log.info("%f seconds elapsed, finished iteration %d (fused)",
                         time.time() - start, self.boosting.iter)
            elif (fused is not None and cfg.metric_freq > 0
                    and fused(ignore_train_metrics=True)):
                # metric output (train and/or valid) is the only blocker:
                # run fused blocks of metric_freq iterations, catching up
                # valid scores from the block's trees and printing between
                b = self.boosting
                done = b.iter
                while done < cfg.num_iterations:
                    # next boundary on the metric cadence, clamped to
                    # the snapshot cadence (boundaries land on BOTH, so
                    # metric output keeps its cadence and snapshots
                    # theirs; the clamped lengths recur, so at most a
                    # few scan lengths ever compile)
                    nxt = min(((done // cfg.metric_freq) + 1)
                              * cfg.metric_freq, cfg.num_iterations)
                    step = snap_clamp(nxt - done)
                    if step == cfg.metric_freq or manager is not None:
                        stopped = b.train_many(step,
                                               ignore_train_metrics=True)
                    else:
                        # one-off tail shorter than a block: the per-
                        # iteration loop avoids compiling a second scan
                        # length
                        stopped = False
                        for _ in range(step):
                            if b.train_one_iter(is_eval=False):
                                stopped = True
                                break
                    if b.iter > done:  # block trained something
                        done = b.iter
                        b.output_metric(done)
                        Log.info("%f seconds elapsed, finished iteration %d "
                                 "(fused block)", time.time() - start, done)
                    elif not stopped:
                        # no forward progress (e.g. nonfinite_guard=
                        # warn_skip skipping a persistently-poisoned
                        # round): bail instead of spinning forever
                        Log.warning("no training progress at iteration "
                                    "%d; stopping", done)
                        break
                    if stopped:
                        break
                    maybe_snapshot()
            else:
                for it in range(self.boosting.iter + 1,
                                cfg.num_iterations + 1):
                    is_finished = self.boosting.train_one_iter(is_eval=True)
                    Log.info("%f seconds elapsed, finished iteration %d",
                             time.time() - start, it)
                    if is_finished:
                        break
                    maybe_snapshot()
        finally:
            if trace_dir is not None:
                import jax
                jax.profiler.stop_trace()
                Log.info("Wrote jax.profiler trace to %s", trace_dir)
        if tracer.acc:
            Log.debug("Per-phase timers:\n%s", tracer.report())
        import jax
        if jax.process_index() == 0:  # every rank has the identical model
            self.boosting.save_model_to_file(-1, cfg.output_model)
        b = self.boosting
        if b.journal is not None:
            # final memory/compile drain + span-ring dump land BEFORE
            # run_end so that record stays the timeline's last event
            b.finalize_introspection()
            b.journal.event("run_end", iterations=int(b.iter),
                            train_s=round(time.time() - start, 3))
            if jax.process_count() > 1:
                # hold every rank here until all run_end records are on
                # shared storage — without it rank 0's merge below
                # could permanently miss a straggling peer's tail
                from jax.experimental import multihost_utils
                with heartbeat.collective_guard("journal_merge_barrier"):
                    multihost_utils.process_allgather(
                        np.asarray([b.iter], dtype=np.int64))
        if cfg.run_history and jax.process_index() == 0:
            # one compact run_summary per training run: the trend line
            # tools/sentinel.py judges (telemetry/history.py)
            from .telemetry import history
            history.append_run_summary(
                cfg.run_history, "train",
                **history.booster_summary(
                    b, train_s=round(time.time() - start, 3)))
        # final `done` beat + monitor stop: a cleanly finished rank must
        # never be declared dead by peers still tearing down
        heartbeat.shutdown(done=True)
        # rank 0 merges every rank's journal into one wall-time-sorted
        # timeline (journal.jsonl); peers that aborted in an earlier
        # incarnation left their abort records in the same rank files
        b.close_telemetry(merge=jax.process_index() == 0)
        Log.info("Finished training")

    # ------------------------------------------------------------ prediction
    def init_predict(self):
        cfg = self.config
        if not cfg.input_model:
            Log.fatal("Please specify the model file for prediction")
        self.boosting = create_boosting("gbdt", cfg.input_model)
        with open(cfg.input_model) as f:
            self.boosting.load_model_from_string(f.read())
        # a predict-only booster never runs reset_training_data, so the
        # routing knobs must be applied here or they would be dead on
        # the one path documented to consume them
        self.boosting.apply_predict_config(cfg)
        Log.info("Finished initializing prediction")

    def predict(self):
        cfg = self.config
        predictor = Predictor(
            self.boosting,
            is_raw_score=cfg.is_predict_raw_score,
            is_predict_leaf_index=cfg.is_predict_leaf_index,
            num_iteration=cfg.num_iteration_predict)
        predictor.predict_file(cfg.data, cfg.output_result,
                               has_header=cfg.has_header,
                               label_column=cfg.label_column,
                               max_bad_rows=cfg.max_bad_rows,
                               chunk_rows=cfg.predict_chunk_rows)
        Log.info("Finished prediction")


def main(argv=None):
    """src/main.cpp:4-23."""
    import sys
    if argv is None:
        argv = sys.argv[1:]
    try:
        Application(argv).run()
    except Exception as ex:  # main.cpp catches and reports all exceptions
        Log.warning("Met Exceptions:")
        Log.warning("%s", str(ex))
        raise SystemExit(1)
