"""Prometheus text exposition of the metrics registry.

`?format=prometheus` on /metricz (serving/server.py), /trainz /
/metricz (telemetry/trainz.py) and the fleet aggregator
(telemetry/aggregate.py) renders the SAME single registry that backs
the JSON views in the text exposition format (version 0.0.4), so a
standard scrape job works against training, serving and aggregator
processes with zero extra dependencies:

    scrape_configs:
      - job_name: lightgbm_tpu
        metrics_path: /metricz
        params: {format: [prometheus]}

Counters render as `counter`, gauges as `gauge`, registry histograms
as `summary` (quantile series from the ring's nearest-rank
percentiles, plus `_sum`/`_count` over the process lifetime).

NAMING CONTRACT (the audit `lint_names` enforces and a test renders
every registry against): one canonical `lightgbm_tpu_` prefix, base
units with unit suffixes — times are `_seconds` (values converted:
internal `_ms` metrics are scaled to seconds at render), byte counts
`_bytes`, fractions `_ratio` (internal `_pct` values scaled /100),
rates `_per_second`, and every counter ends `_total`. Internal
registry names keep their short forms (`sync_wait_s`, `latency_ms`) —
`canonical_name` maps them at the exposition boundary, so the JSON
views and in-process consumers are untouched while every scraped
dashboard sees one consistent naming scheme. Names are sanitized to
the exposition charset; non-numeric extra values are skipped rather
than corrupting the page.
"""

import re

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")

# legacy internal suffix -> (canonical suffix, value scale). Order
# matters: `_per_s` must match before `_s`.
_UNIT_MAP = (("_per_s", "_per_second", 1.0),
             ("_ms", "_seconds", 1e-3),
             ("_s", "_seconds", 1.0),
             ("_secs", "_seconds", 1.0),
             ("_pct", "_ratio", 1e-2))

# suffixes the lint rejects: a name still carrying one escaped the
# canonical mapping (or was minted after this audit without a unit)
_LEGACY_SUFFIXES = ("_s", "_ms", "_secs", "_sec", "_pct", "_millis")


def sanitize_name(name, prefix="lightgbm_tpu"):
    """Metric name -> exposition-legal name (`[a-zA-Z_:][a-zA-Z0-9_:]*`),
    prefixed. Every illegal char becomes `_`."""
    name = _BAD_CHARS.sub("_", str(name))
    if not name or not _NAME_OK.match(name):
        name = "_" + name
    return f"{prefix}_{name}" if prefix else name


def canonical_name(name, kind="gauge"):
    """Internal metric name -> (canonical exposition name, value
    scale): unit suffixes normalized to base units (`_s`/`_ms` ->
    `_seconds`, `_pct` -> `_ratio` with the matching value scale,
    `_per_s` -> `_per_second`), counters forced to end `_total`
    (`_count` counters are renamed, not double-suffixed). Applied
    AFTER sanitize/prefix by the render path; pure so the lint and the
    tests can call it standalone."""
    name = name.lower()   # the contract is lowercase (feature-derived
    #                       names like drift_psi_<Feature> arrive mixed)
    scale = 1.0
    for suffix, repl, sc in _UNIT_MAP:
        if name.endswith(suffix):
            name = name[: -len(suffix)] + repl
            scale = sc
            break
    if kind == "counter":
        if name.endswith("_count"):
            name = name[: -len("_count")] + "_total"
        elif not name.endswith("_total"):
            name += "_total"
    return name, scale


def _fmt(v):
    """Exposition float formatting (no exponent-less NaN/Inf issues:
    Prometheus accepts NaN/+Inf/-Inf literals, but the registry never
    stores them — JSON-sanitized upstream)."""
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


def _label_str(labels, extra=None):
    """{k: v} -> '{k="v",...}' ('' when empty). Label values escape
    backslash/quote/newline per the exposition format."""
    items = list((labels or {}).items()) + list((extra or {}).items())
    if not items:
        return ""
    def esc(v):
        return (str(v).replace("\\", r"\\").replace('"', r'\"')
                .replace("\n", r"\n"))
    return "{" + ",".join(f'{k}="{esc(v)}"' for k, v in items) + "}"


def _scaled(v, scale):
    if scale == 1.0 or not isinstance(v, (int, float)) \
            or isinstance(v, bool):
        return v
    return v * scale


def families(snapshot, prefix="lightgbm_tpu", extra_gauges=None,
             labels=None):
    """Registry snapshot -> ordered {family_name: (kind, [sample
    lines])}. The shared core of `render` (one source) and
    `render_multi` (the aggregator's many labeled sources, where each
    family's TYPE line must appear exactly once across all of them)."""
    out = {}
    lab = _label_str(labels)

    def add(name, kind, samples):
        existing = out.get(name)
        if existing is None:
            out[name] = (kind, list(samples))
        else:
            existing[1].extend(samples)

    for name, value in sorted((snapshot.get("counters") or {}).items()):
        if not isinstance(value, (int, float)):
            continue
        n, scale = canonical_name(sanitize_name(name, prefix), "counter")
        add(n, "counter", [f"{n}{lab} {_fmt(_scaled(value, scale))}"])
    for name, value in sorted((snapshot.get("gauges") or {}).items()):
        if not isinstance(value, (int, float)):
            continue
        n, scale = canonical_name(sanitize_name(name, prefix), "gauge")
        add(n, "gauge", [f"{n}{lab} {_fmt(_scaled(value, scale))}"])
    for name, summ in sorted((snapshot.get("histograms") or {}).items()):
        if not isinstance(summ, dict):
            continue
        n, scale = canonical_name(sanitize_name(name, prefix), "summary")
        samples = []
        for pct, q in ((50, "0.5"), (95, "0.95"), (99, "0.99")):
            v = summ.get(f"p{pct}")
            if isinstance(v, (int, float)):
                samples.append(
                    f'{n}{_label_str(labels, {"quantile": q})} '
                    f"{_fmt(_scaled(v, scale))}")
        if isinstance(summ.get("total"), (int, float)):
            samples.append(
                f"{n}_sum{lab} {_fmt(_scaled(summ['total'], scale))}")
        if isinstance(summ.get("count"), (int, float)):
            # observation counts are unitless — never unit-scaled
            samples.append(f"{n}_count{lab} {_fmt(summ['count'])}")
        if samples:
            add(n, "summary", samples)
    for name, value in sorted((extra_gauges or {}).items()):
        if not isinstance(value, (int, float)):
            continue
        n, scale = canonical_name(sanitize_name(name, prefix), "gauge")
        add(n, "gauge", [f"{n}{lab} {_fmt(_scaled(value, scale))}"])
    return out


def _emit(fam):
    lines = []
    for name, (kind, samples) in fam.items():
        lines.append(f"# TYPE {name} {kind}")
        lines.extend(samples)
    return "\n".join(lines) + "\n"


def render(snapshot, prefix="lightgbm_tpu", extra_gauges=None,
           labels=None):
    """Registry snapshot (MetricsRegistry.snapshot(): counters/gauges/
    histograms) -> exposition text. `extra_gauges` is a flat
    {name: number} dict appended as gauges (serving warmup stats,
    queue depth, roofline numbers...); `labels` attach to every sample
    (the aggregator's `rank`/`role`)."""
    return _emit(families(snapshot, prefix, extra_gauges, labels))


def render_multi(parts, prefix="lightgbm_tpu"):
    """Many labeled sources -> ONE exposition page with each family's
    TYPE line emitted exactly once (repeating it per source is a
    format violation a real Prometheus server rejects). `parts` is an
    iterable of (labels, snapshot, extra_gauges); sources sharing a
    family must carry distinguishing labels or the duplicate-sample
    rule trips downstream. On a kind conflict across sources the first
    wins and later samples of that family are dropped (conflicting
    types in one family are unscrapable anyway)."""
    merged = {}
    for labels, snapshot, extra in parts:
        for name, (kind, samples) in families(
                snapshot or {}, prefix, extra, labels).items():
            existing = merged.get(name)
            if existing is None:
                merged[name] = (kind, list(samples))
            elif existing[0] == kind:
                existing[1].extend(samples)
    return _emit(merged)


def lint_family_name(base, kind=None):
    """Violation strings for ONE family name against the naming
    contract (empty = conformant). The per-name core of `lint_names`,
    and the SINGLE implementation graftlint's `prometheus-naming`
    static rule imports (lightgbm_tpu/analysis/rules/prom_naming.py) —
    the runtime page audit and the static literal audit cannot
    diverge because they are the same function."""
    if not base.startswith("lightgbm_tpu_"):
        return [f"{base!r} lacks the lightgbm_tpu_ prefix"]
    violations = []
    if not re.fullmatch(r"[a-z][a-z0-9_]*", base) or "__" in base:
        violations.append(
            f"{base!r} is not lowercase [a-z0-9_] without __ runs")
    for suffix in _LEGACY_SUFFIXES:
        if base.endswith(suffix):
            violations.append(
                f"{base!r} ends with legacy unit suffix {suffix!r} "
                "(use _seconds/_bytes/_ratio/_total)")
            break
    if kind == "counter" and not base.endswith("_total"):
        violations.append(f"counter {base!r} must end _total")
    return violations


def lint_names(text):
    """Audit one exposition page against the naming contract. Returns
    a list of violation strings (empty = conformant):

    - every family carries the `lightgbm_tpu_` prefix and is
      lowercase `[a-z0-9_]` (no `__` runs);
    - no family ends with a legacy unit suffix (`_s`, `_ms`, `_pct`,
      ...) — times must be `_seconds`, fractions `_ratio`;
    - every `counter` family ends `_total`;
    - no duplicate samples, and every sample parses.

    Per-family checks are `lint_family_name`; this adds the page-level
    ones (duplicates, summary sub-series attribution).
    """
    violations = []
    kinds = {}
    seen = set()
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                kinds[parts[2]] = parts[3]
            continue
        name = line.rsplit(" ", 1)[0]
        if name in seen:
            violations.append(f"line {lineno}: duplicate sample {name!r}")
        seen.add(name)
        base = name.split("{", 1)[0]
        # summary sub-series lint against their family name
        for sub in ("_sum", "_count"):
            if base.endswith(sub) and base[: -len(sub)] in kinds:
                base = base[: -len(sub)]
                break
        violations.extend(f"line {lineno}: {v}"
                          for v in lint_family_name(base, kinds.get(base)))
    return violations


def parse(text):
    """Minimal exposition parser: {name: value} for plain samples,
    {name{labels}: value} kept verbatim for labeled ones. Raises
    ValueError on a malformed line — the round-trip check tests and
    `make verify-obs` rely on."""
    out = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.rsplit(" ", 1)
        if len(parts) != 2:
            raise ValueError(f"line {lineno}: not 'name value': {line!r}")
        name, value = parts
        base = name.split("{", 1)[0]
        if not _NAME_OK.match(base):
            raise ValueError(f"line {lineno}: bad metric name {base!r}")
        if name in out:
            # the exposition format forbids duplicate series — a real
            # Prometheus server rejects the whole scrape on one
            raise ValueError(f"line {lineno}: duplicate sample {name!r}")
        out[name] = float(value)   # ValueError on a bad float
    return out
