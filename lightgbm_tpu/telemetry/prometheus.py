"""Prometheus text exposition of the metrics registry.

`?format=prometheus` on /metricz (serving/server.py) and /trainz /
/metricz (telemetry/trainz.py) renders the SAME single registry that
backs the JSON views in the text exposition format (version 0.0.4), so
a standard scrape job works against both the training and serving
processes with zero extra dependencies:

    scrape_configs:
      - job_name: lightgbm_tpu
        metrics_path: /metricz
        params: {format: [prometheus]}

Counters render as `counter`, gauges as `gauge`, registry histograms
as `summary` (quantile series from the ring's nearest-rank
percentiles, plus `_sum`/`_count` over the process lifetime). Names
are prefixed `lightgbm_tpu_` and sanitized to the exposition charset;
non-numeric extra values are skipped rather than corrupting the page.
"""

import re

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize_name(name, prefix="lightgbm_tpu"):
    """Metric name -> exposition-legal name (`[a-zA-Z_:][a-zA-Z0-9_:]*`),
    prefixed. Every illegal char becomes `_`."""
    name = _BAD_CHARS.sub("_", str(name))
    if not name or not _NAME_OK.match(name):
        name = "_" + name
    return f"{prefix}_{name}" if prefix else name


def _fmt(v):
    """Exposition float formatting (no exponent-less NaN/Inf issues:
    Prometheus accepts NaN/+Inf/-Inf literals, but the registry never
    stores them — JSON-sanitized upstream)."""
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


def render(snapshot, prefix="lightgbm_tpu", extra_gauges=None):
    """Registry snapshot (MetricsRegistry.snapshot(): counters/gauges/
    histograms) -> exposition text. `extra_gauges` is a flat
    {name: number} dict appended as gauges (serving warmup stats,
    queue depth, roofline numbers...)."""
    lines = []

    def emit(name, kind, samples):
        lines.append(f"# TYPE {name} {kind}")
        lines.extend(samples)

    for name, value in sorted((snapshot.get("counters") or {}).items()):
        if not isinstance(value, (int, float)):
            continue
        n = sanitize_name(name, prefix)
        emit(n, "counter", [f"{n} {_fmt(value)}"])
    for name, value in sorted((snapshot.get("gauges") or {}).items()):
        if not isinstance(value, (int, float)):
            continue
        n = sanitize_name(name, prefix)
        emit(n, "gauge", [f"{n} {_fmt(value)}"])
    for name, summ in sorted((snapshot.get("histograms") or {}).items()):
        if not isinstance(summ, dict):
            continue
        n = sanitize_name(name, prefix)
        samples = []
        for pct, q in ((50, "0.5"), (95, "0.95"), (99, "0.99")):
            v = summ.get(f"p{pct}")
            if isinstance(v, (int, float)):
                samples.append(f'{n}{{quantile="{q}"}} {_fmt(v)}')
        if isinstance(summ.get("total"), (int, float)):
            samples.append(f"{n}_sum {_fmt(summ['total'])}")
        if isinstance(summ.get("count"), (int, float)):
            samples.append(f"{n}_count {_fmt(summ['count'])}")
        if samples:
            emit(n, "summary", samples)
    for name, value in sorted((extra_gauges or {}).items()):
        if not isinstance(value, (int, float)):
            continue
        n = sanitize_name(name, prefix)
        emit(n, "gauge", [f"{n} {_fmt(value)}"])
    return "\n".join(lines) + "\n"


def parse(text):
    """Minimal exposition parser: {name: value} for plain samples,
    {name{labels}: value} kept verbatim for labeled ones. Raises
    ValueError on a malformed line — the round-trip check tests and
    `make verify-obs` rely on."""
    out = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.rsplit(" ", 1)
        if len(parts) != 2:
            raise ValueError(f"line {lineno}: not 'name value': {line!r}")
        name, value = parts
        base = name.split("{", 1)[0]
        if not _NAME_OK.match(base):
            raise ValueError(f"line {lineno}: bad metric name {base!r}")
        if name in out:
            # the exposition format forbids duplicate series — a real
            # Prometheus server rejects the whole scrape on one
            raise ValueError(f"line {lineno}: duplicate sample {name!r}")
        out[name] = float(value)   # ValueError on a bad float
    return out
