"""Run-history store: one compact `run_summary` record per run.

The bench trajectory has holes (BENCH_r02/r03 were silent timeouts)
because per-run results live in scattered JSON files with no machine-
readable trend line. This module gives every training / bench /
verify run one append-only home — `RUN_HISTORY.jsonl` — holding the
handful of numbers that define "did we get worse": train wall
seconds, eval metrics, peak memory, collective bytes per tree, comm /
prefetch overlap, serving p99 when benched. `tools/sentinel.py` does
robust trend detection over the last K records (median + MAD, not a
single-baseline compare) and `tools/verify_perf.py` runs it as a
history-aware gate whenever the file exists.

Writers: the CLI at run_end (`run_history` knob, docs/Parameters.md),
bench.py after each measured rung, verify_perf after its gated run.
Write discipline is the journal's (telemetry/journal.py): one
O_APPEND `os.write` of a complete line, so concurrent writers
interleave at line granularity and a killed run can tear at most its
own record. The record schema is `run_summary` in journal.SCHEMA —
`tools/check_journal.py` lints history files with the same machinery
as run journals. jax-free, stdlib-only.
"""

import os
import time

from ..utils.log import Log
from . import journal as journal_mod

HISTORY_NAME = "RUN_HISTORY.jsonl"


def default_path(base_dir="."):
    return os.path.join(os.fspath(base_dir), HISTORY_NAME)


def append_run_summary(path, kind, **fields):
    """Append one `run_summary` record. None-valued fields are
    dropped; the record is schema-validated before the write (a
    violation logs a warning but still writes — history must not be
    lost to a typo'd optional field, and unknown extras are legal).
    Returns the path, or None when the write failed."""
    rec = {"ts": time.time(), "mono": round(time.monotonic(), 6),
           "event": "run_summary", "rank": 0, "kind": str(kind)}
    rec.update({k: v for k, v in fields.items() if v is not None})
    rec = journal_mod._sanitize(rec)
    errors = journal_mod.validate_record(rec)
    if errors:
        Log.warning("run_summary record has schema violations "
                    "(written anyway): %s", "; ".join(errors))
    import json
    line = json.dumps(rec, separators=(",", ":"), allow_nan=False,
                      default=str) + "\n"
    try:
        parent = os.path.dirname(os.fspath(path))
        if parent:
            os.makedirs(parent, exist_ok=True)
        fd = os.open(os.fspath(path),
                     os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644)
        try:
            os.write(fd, line.encode("utf-8"))
        finally:
            os.close(fd)
    except OSError as e:
        Log.warning("run history append failed (%s): %s", path, e)
        return None
    return os.fspath(path)


def read_history(path):
    """Parsed, valid `run_summary` records (oldest first). Torn lines
    and foreign/invalid records are skipped — an old or co-written
    file must not break trend detection."""
    records, _ = journal_mod.read_journal(path)
    return [r for r in records
            if isinstance(r, dict) and r.get("event") == "run_summary"
            and not journal_mod.validate_record(r)]


def booster_summary(booster, train_s=None, rows=None):
    """Assemble the summary fields one trained GBDT can attest to:
    iteration count, last eval metric values, memory watermarks
    (telemetry/ledger.py), total collective bytes (+ per tree), the
    comm profiler's latest overlap, and the streaming learner's
    prefetch overlap. Used by the CLI's run_end write; bench.py builds
    its own dict because its numbers come from child-process JSON."""
    fields = {"iterations": int(getattr(booster, "iter", 0) or 0)}
    if train_s is not None:
        fields["train_s"] = round(float(train_s), 3)
    if rows is None:
        data = getattr(booster, "train_data", None)
        rows = getattr(data, "global_num_data", None) \
            or getattr(data, "num_data", None)
    if rows:
        fields["rows"] = int(rows)
    metrics = getattr(booster, "_last_metric_values", None)
    if metrics:
        fields["metrics"] = {str(k): float(v)
                             for k, v in metrics.items()
                             if isinstance(v, (int, float))}
        auc = fields["metrics"].get("auc")
        if auc is not None:
            fields["auc"] = auc
    try:
        from . import ledger
        mem = ledger.sample_memory()
        peak = mem.get("device_peak_bytes") or mem.get(
            "host_peak_rss_bytes")
        if peak:
            fields["peak_memory_bytes"] = int(peak)
    except Exception:
        pass
    reg = getattr(booster, "metrics", None)
    if reg is not None:
        snap = reg.snapshot()
        total = snap["counters"].get("collective_bytes")
        if total:
            fields["collective_bytes"] = int(total)
            trees = len(getattr(booster, "models", ()) or ())
            if trees:
                fields["collective_bytes_per_tree"] = round(
                    total / trees, 1)
        pf = snap["gauges"].get("prefetch_overlap_pct")
        if pf:
            fields["prefetch_overlap_pct"] = float(pf)
    prof = getattr(booster, "comm_profile", None)
    if prof is not None and prof.last:
        # run-aggregate overlap (cum wait over cum wall) — trending a
        # single iteration's number would gate on noise
        overlap = prof.snapshot().get("run_overlap_pct")
        if overlap is not None:
            fields["comm_overlap_pct"] = float(overlap)
    return fields
