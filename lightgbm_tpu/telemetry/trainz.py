"""Live training introspection endpoint: GET /trainz.

A tiny opt-in stdlib HTTP thread (the serving layer's stdlib-only
pattern, serving/server.py — the telemetry surface must not add
dependencies the training image lacks) exposing the CURRENT state of a
training run as one JSON document:

- `iteration`: the booster's completed-iteration count
- `phases`: the span tracer's per-phase accumulated seconds
- `spans`: the most recent completed spans (path/start/duration)
- `metrics`: the metrics registry snapshot (counters/gauges/histograms)
- `heartbeats`: per-rank seconds since each peer's beat last changed
  (multi-host runs with the heartbeat service up; parallel/heartbeat.py)
- `journal_tail`: the last records of this rank's run journal
- `memory`: device/host memory watermarks (telemetry/ledger.py)
- `compile`: the jit-lowering ledger (counts, seconds, cache hits)
- `roofline`: live per-kernel achieved bandwidth vs the measured
  STREAM peak (telemetry/roofline.py)
- `comm`: per-collective wait attribution, comm_overlap_pct and the
  per-rank straggler deltas (telemetry/comm_profile.py; the fleet
  aggregator `python -m lightgbm_tpu.telemetry.aggregate` merges this
  source across every rank)

Also serves /healthz (liveness) and /metricz (the registry alone —
the training-side scrape target mirroring the serving layer's).
`?format=prometheus` on /trainz and /metricz renders the registry in
text exposition format (telemetry/prometheus.py) so standard scrapers
work without a sidecar.

Enabled by `telemetry_port > 0` (docs/Parameters.md);
`start_trainz(..., port=0)` binds an ephemeral port (tests). The
handler thread only READS shared state — it can never stall the
training loop.

Sources are held weakly-ish via zero-arg callables so a finished
booster is not kept alive by a lingering server thread.
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlsplit

from ..utils.log import Log
from . import journal as journal_mod
from . import prometheus


class TrainzHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    sources = None   # bound by start_trainz

    def log_message(self, fmt, *args):   # route access logs through ours
        Log.debug("trainz: " + fmt, *args)

    def _reply(self, code, obj):
        data = json.dumps(obj, default=str).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _reply_text(self, code, text, content_type):
        data = text.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _source(self, name):
        fn = (self.sources or {}).get(name)
        if fn is None:
            return None
        try:
            return fn()
        except Exception:   # a dead source must not 500 the page
            return None

    def _prometheus(self):
        """The single registry (plus the scalar extras a scraper
        wants: iteration, compile totals, memory watermarks, per-
        kernel roofline bandwidth) in text exposition format."""
        snapshot = self._source("metrics") or {}
        extra = {}
        it = self._source("iteration")
        if it is not None:
            extra["iteration"] = it
        comp = self._source("compile")
        if isinstance(comp, dict):
            extra.update({f"compile_{k}": v for k, v in comp.items()
                          if isinstance(v, (int, float))})
        mem = self._source("memory")
        if isinstance(mem, dict):
            extra.update(mem)
        roof = self._source("roofline")
        if isinstance(roof, dict):
            if roof.get("peak_bytes_per_s"):
                extra["stream_peak_bytes_per_s"] = roof["peak_bytes_per_s"]
            for kname, k in (roof.get("kernels") or {}).items():
                for field in ("bytes_per_s", "rows_per_s", "calls"):
                    if isinstance(k.get(field), (int, float)):
                        extra[f"roofline_{kname}_{field}"] = k[field]
        # GBDT mirrors the memory sample into registry gauges — drop
        # any extra whose name the registry already owns: a duplicate
        # metric name makes a real Prometheus server reject the WHOLE
        # scrape (the exposition format forbids it)
        owned = (set(snapshot.get("counters") or ())
                 | set(snapshot.get("gauges") or ())
                 | set(snapshot.get("histograms") or ()))
        extra = {k: v for k, v in extra.items() if k not in owned}
        return prometheus.render(snapshot, extra_gauges=extra)

    def do_GET(self):
        parts = urlsplit(self.path)
        path = parts.path
        fmt = (parse_qs(parts.query).get("format") or [""])[0]
        if path.startswith("/healthz"):
            self._reply(200, {"status": "ok"})
            return
        if not (path.startswith("/trainz") or path.startswith("/metricz")):
            self._reply(404, {"error": f"unknown path {self.path}"})
            return
        if fmt == "prometheus":
            self._reply_text(200, self._prometheus(),
                             prometheus.CONTENT_TYPE)
            return
        if path.startswith("/metricz"):
            # the registry alone: the training-side scrape document
            out = {"metrics": self._source("metrics")}
            for name in ("iteration", "memory", "compile"):
                val = self._source(name)
                if val is not None:
                    out[name] = val
            self._reply(200, out)
            return
        out = {}
        for name, fn in (self.sources or {}).items():
            try:
                out[name] = fn()
            except Exception as e:   # a dead source must not 500 the page
                out[name] = {"error": str(e)}
        self._reply(200, out)


def build_sources(iteration_fn=None, tracer=None, registry=None,
                  journal=None, tail_n=20, roofline_warn_fraction=0.0,
                  quality_fn=None, comm_fn=None):
    """Assemble the /trainz source map from whatever exists. The
    heartbeat service is resolved lazily per request (it may start
    after the endpoint does); memory/compile/roofline read the
    process-wide telemetry singletons."""
    sources = {}
    if iteration_fn is not None:
        sources["iteration"] = lambda: int(iteration_fn())
    if tracer is not None:
        sources["phases"] = tracer.snapshot
        sources["spans"] = tracer.recent
    if registry is not None:
        sources["metrics"] = registry.snapshot
    if quality_fn is not None:
        # split-ledger totals + top features by gain
        # (telemetry/quality.py QualityTracker.snapshot)
        sources["quality"] = quality_fn
    if comm_fn is not None:
        # collective latency attribution: per-collective waits,
        # comm_overlap_pct, per-rank straggler deltas
        # (telemetry/comm_profile.py CommProfiler.snapshot)
        sources["comm"] = comm_fn

    def heartbeats():
        from ..parallel import heartbeat
        svc = heartbeat.service()
        if svc is None:
            return None
        return {"rank": svc.rank,
                "peer_age_s": {str(r): round(a, 3)
                               for r, a in svc.peer_ages().items()},
                "dead_peers": svc.dead_peers()}

    sources["heartbeats"] = heartbeats
    if journal is not None:
        sources["journal_tail"] = lambda: journal_mod.tail(journal.path,
                                                           tail_n)

    def memory():
        from . import ledger
        return ledger.sample_memory()

    def compile_ledger():
        from . import ledger
        return ledger.LEDGER.snapshot()

    def roofline_view():
        from . import roofline
        return roofline.TABLE.snapshot(
            warn_fraction=roofline_warn_fraction)

    sources["memory"] = memory
    sources["compile"] = compile_ledger
    sources["roofline"] = roofline_view
    return sources


def start_trainz(sources, port, host="127.0.0.1"):
    """Start the daemon /trainz server; returns it (server_address[1]
    carries the bound port — pass port=0 for ephemeral). Returns None
    when the bind fails: telemetry must never kill training."""
    handler = type("BoundTrainzHandler", (TrainzHandler,),
                   {"sources": dict(sources)})
    try:
        srv = ThreadingHTTPServer((host, int(port)), handler)
    except OSError as e:
        Log.warning("/trainz disabled (cannot bind %s:%s: %s)",
                    host, port, e)
        return None
    srv.daemon_threads = True
    thread = threading.Thread(target=srv.serve_forever, daemon=True,
                              name="lgbm-tpu-trainz")
    thread.start()
    Log.info("/trainz live on http://%s:%d/trainz", host,
             srv.server_address[1])
    return srv


def stop_trainz(srv):
    if srv is None:
        return
    try:
        srv.shutdown()
        srv.server_close()
    except Exception:
        pass
