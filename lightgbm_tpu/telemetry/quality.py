"""Model-quality ledger: per-tree split records + feature importance.

The reference's core model-introspection primitive is gain/split
feature importance (gbdt.cpp:585-610 counts splits for the model file's
"feature importances:" block; the C API's feature_importance adds the
gain variant: gain summed over every split a feature made). This module
is the ONE place those semantics live: every learner path — serial
masked/compacted, fused scan, out-of-core streaming, and the parallel
learners — materializes plain `Tree` objects carrying
(split_feature_real, split_gain, threshold, decision_type,
internal_count, leaf_count, leaf_value), so a ledger derived from the
model list is identical across engines by construction. That is the
agreement contract tests/test_quality.py pins: trees pinned identical
=> importance vectors bit-identical.

Two consumers:

- the public importance APIs (`Booster.feature_importance`,
  sklearn `feature_importances_`) call `feature_importance_from_models`
  on demand;
- the `quality_telemetry` knob attaches a `QualityTracker` to the
  booster, which consumes newly-appended trees at every
  iteration/block boundary and journals one `quality` record
  (splits/gain deltas, top features by gain, leaf-value distribution,
  importance drift) next to the run's iteration records — the
  training-side half of the drift story (serving/drift.py watches the
  data; this watches the model).

jax-free like the rest of the telemetry package.
"""

import threading

import numpy as np

IMPORTANCE_TYPES = ("split", "gain", "coeff")


def _materialize(tree):
    """LazyTree (models/gbdt.py) or Tree -> Tree."""
    return tree.materialize() if hasattr(tree, "materialize") else tree


def tree_split_records(tree):
    """One tree's per-split ledger rows as a dict of aligned arrays:
    feature (real column idx), gain, threshold (real-valued),
    decision_type (0 numerical / 1 categorical), count (rows through
    the split node), left/right child. Missing values route RIGHT on
    every node in this build (reference default-direction semantics),
    so the default direction is a constant, not a per-split field."""
    tree = _materialize(tree)
    ns = max(int(tree.num_leaves) - 1, 0)
    return {
        "feature": np.asarray(tree.split_feature_real[:ns], np.int64),
        "gain": np.asarray(tree.split_gain[:ns], np.float64),
        "threshold": np.asarray(tree.threshold[:ns], np.float64),
        "decision_type": np.asarray(tree.decision_type[:ns], np.int64),
        "count": np.asarray(tree.internal_count[:ns], np.int64),
        "left_child": np.asarray(tree.left_child[:ns], np.int64),
        "right_child": np.asarray(tree.right_child[:ns], np.int64),
    }


def tree_coeff_importance(tree, num_features):
    """Per-feature coefficient importance of one tree's linear leaves
    (models/linear_leaves.py): for every linear leaf l and coefficient
    j, importance[feature(l, j)] += |coef[l, j]| * gain(parent(l)) —
    the magnitude of the leaf model's use of the feature, weighted by
    the gain of the split that carved the leaf out, so coefficients in
    high-signal regions count more than equal-magnitude ones in noise
    leaves. Derived from the materialized Tree's arrays only, so it is
    bit-identical across engines by the same contract as split/gain.
    Constant-leaf trees contribute an all-zero vector."""
    out = np.zeros(int(num_features), np.float64)
    tree = _materialize(tree)
    if not getattr(tree, "is_linear", False):
        return out
    gain = np.asarray(tree.split_gain, np.float64)
    for leaf in range(int(tree.num_leaves)):
        k = int(tree.leaf_coeff_count[leaf])
        if k == 0:
            continue
        parent = int(tree.leaf_parent[leaf])
        w = gain[parent] if parent >= 0 else 0.0
        np.add.at(out, tree.leaf_coeff_feat[leaf, :k],
                  np.abs(tree.leaf_coeff[leaf, :k]) * w)
    return out


class SplitLedger:
    """Per-feature split/gain/coeff accumulator with reference
    semantics: `split` importance counts how many splits used the
    feature, `gain` sums split_gain over them, `coeff` sums gain-
    weighted linear-leaf coefficient magnitudes (tree_coeff_importance).
    add_tree() is pure numpy over one tree's flat arrays —
    O(num_leaves) per tree."""

    def __init__(self, num_features):
        self.num_features = int(num_features)
        self.split_counts = np.zeros(self.num_features, np.int64)
        self.gain_sums = np.zeros(self.num_features, np.float64)
        self.coeff_sums = np.zeros(self.num_features, np.float64)
        self.n_trees = 0
        self.n_splits = 0

    def add_tree(self, tree):
        rec = tree_split_records(tree)
        feat = rec["feature"]
        if len(feat):
            np.add.at(self.split_counts, feat, 1)
            np.add.at(self.gain_sums, feat, rec["gain"])
        # probe the wrapper, not the materialization: LazyTree carries
        # is_linear=False as a class attribute (builder output is
        # always constant-leaf), so this never forces a host transfer
        if getattr(tree, "is_linear", False):
            self.coeff_sums += tree_coeff_importance(tree,
                                                     self.num_features)
        self.n_trees += 1
        self.n_splits += len(feat)
        return rec

    def importance(self, importance_type="split"):
        if importance_type == "split":
            return self.split_counts.copy()
        if importance_type == "gain":
            return self.gain_sums.copy()
        if importance_type == "coeff":
            return self.coeff_sums.copy()
        raise ValueError(
            f"Unknown importance type {importance_type!r} "
            f"(expected one of {IMPORTANCE_TYPES})")


def feature_importance_from_models(models, num_features,
                                   importance_type="split"):
    """Reference-semantics importance vector over a model list (any
    mix of Tree/LazyTree): int64 split counts or float64 gain sums,
    length `num_features` (total feature space)."""
    ledger = SplitLedger(num_features)
    for tree in models:
        ledger.add_tree(tree)
    return ledger.importance(importance_type)


def _normalized(vec):
    total = float(vec.sum())
    return vec / total if total > 0 else np.zeros_like(vec, np.float64)


class QualityTracker:
    """Incremental quality telemetry over a booster's model list.

    `sync(models)` consumes trees appended since the last call and
    returns one journal-ready delta dict (None when nothing changed).
    A shrunk list (rollback / early-stop truncation) rebuilds the
    ledger from scratch — rare, and O(total trees). The tracker also
    keeps the previous normalized gain-importance vector so each sync
    reports `importance_shift`: the L1 distance between consecutive
    normalized importance vectors, the "is the model still learning
    the same features" drift signal."""

    TOP_K = 5

    def __init__(self, num_features, feature_names=()):
        self.num_features = int(num_features)
        self.feature_names = list(feature_names)
        self.ledger = SplitLedger(self.num_features)
        self._n_seen = 0
        self._version_seen = None
        self._prev_norm = np.zeros(self.num_features, np.float64)
        # sync() runs on the training thread while snapshot() serves
        # /trainz scrapes from HTTP threads — guard against torn reads
        self._lock = threading.Lock()

    def _name(self, idx):
        if idx < len(self.feature_names) and self.feature_names[idx]:
            return str(self.feature_names[idx])
        return f"Column_{idx}"

    def sync(self, models):
        with self._lock:
            return self._sync_locked(models)

    def _sync_locked(self, models):
        version = getattr(models, "version", None)
        if (len(models) < self._n_seen
                or (len(models) == self._n_seen
                    and version != self._version_seen)):
            # rollback / truncation dropped trees (possibly already
            # retrained back to the SAME length — the _VersionedList
            # mutation counter catches that): rebuild the ledger
            # against the surviving list SILENTLY (no delta — the
            # dropped trees' deltas were already journaled, and the
            # timeline shows the truncate event next to them; totals
            # and gauges snap to the surviving model)
            ledger = SplitLedger(self.num_features)
            for tree in models:
                ledger.add_tree(tree)
            self.ledger = ledger
            self._n_seen = len(models)
            self._version_seen = version
            self._prev_norm = _normalized(self.ledger.gain_sums)
            return None
        if len(models) == self._n_seen:
            return None
        gain_before = self.ledger.gain_sums.copy()
        splits_before = self.ledger.n_splits
        leaf_vals = []
        new_trees = 0
        for idx in range(self._n_seen, len(models)):
            self.ledger.add_tree(models[idx])
            tree = _materialize(models[idx])
            leaf_vals.append(
                np.asarray(tree.leaf_value[:tree.num_leaves], np.float64))
            new_trees += 1
        self._n_seen = len(models)
        self._version_seen = version
        gain_delta = self.ledger.gain_sums - gain_before
        order = np.argsort(-gain_delta)[:self.TOP_K]
        top_gain = {self._name(int(i)): round(float(gain_delta[i]), 6)
                    for i in order if gain_delta[i] > 0}
        lv = (np.concatenate(leaf_vals) if leaf_vals
              else np.zeros(0, np.float64))
        leaf_values = ({"min": float(lv.min()), "max": float(lv.max()),
                        "mean": float(lv.mean()),
                        "rms": float(np.sqrt(np.mean(lv * lv)))}
                       if lv.size else {})
        norm = _normalized(self.ledger.gain_sums)
        shift = float(np.abs(norm - self._prev_norm).sum())
        self._prev_norm = norm
        return {
            "trees": int(new_trees),
            "splits": int(self.ledger.n_splits - splits_before),
            "gain_total": float(gain_delta.sum()),
            "top_gain": top_gain,
            "leaf_values": leaf_values,
            "importance_shift": round(shift, 6),
        }

    def snapshot(self):
        """JSON-ready cumulative view (the /trainz `quality` source):
        totals plus the current top features by gain and split count.
        Locked against a concurrent training-thread sync()."""
        with self._lock:
            return self._snapshot_locked()

    def _snapshot_locked(self):
        gain = self.ledger.gain_sums
        splits = self.ledger.split_counts
        order = np.argsort(-gain)[:self.TOP_K]
        return {
            "trees": int(self.ledger.n_trees),
            "splits": int(self.ledger.n_splits),
            "gain_total": float(gain.sum()),
            "top_features": [
                {"feature": self._name(int(i)),
                 "gain": round(float(gain[i]), 6),
                 "splits": int(splits[i])}
                for i in order if gain[i] > 0],
        }
