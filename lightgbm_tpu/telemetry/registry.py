"""Metrics registry: counters, gauges, histograms under one lock.

No reference equivalent — the reference's only counters are the
cumulative network timers (include/LightGBM/network.h). The registry
follows the same lock discipline as the serving layer's request
accounting (serving/metrics.py, which is refactored onto these
primitives): every writer path takes the registry's single lock, every
reader snapshot is consistent, and histograms are fixed-size rings of
the most recent observations so percentiles track CURRENT behavior in
bounded memory.

Training-side coverage (wired in models/gbdt.py / parallel/heartbeat.py
/ callback.py): per-iteration gradient/hessian norms, leaf counts,
histogram-kernel (tree-build) dispatch counts, compile-cache hits,
host<->device transfer bytes, collective sync-wait seconds, checkpoint
write latency. `snapshot()` is what `/trainz` serializes.
"""

import threading

import numpy as np

DEFAULT_RING = 4096


def nearest_rank(sorted_values, p):
    """Nearest-rank percentile of an ascending-sorted sequence:
    ceil(n*p/100) - 1 (int() would bias one rank high — p50 of 2
    samples must be the lower one, and p99 of 100 samples rank 98, not
    the absolute max). THE percentile convention every surface shares:
    the serving /metricz latency ring and the fleet load generator's
    gated p99-during-swap must never diverge."""
    n = len(sorted_values)
    # int(): a float p (the router's hedge_quantile * 100) floor-divides
    # to a float rank, which numpy refuses as an index
    return float(sorted_values[int(max(0, -(-n * p // 100) - 1))])


class Counter:
    """Monotonic counter (int/float adds)."""

    __slots__ = ("_lock", "value")

    def __init__(self, lock):
        self._lock = lock
        self.value = 0

    def inc(self, n=1):
        with self._lock:
            self.value += n
        return self


class Gauge:
    """Last-write-wins value."""

    __slots__ = ("_lock", "value")

    def __init__(self, lock):
        self._lock = lock
        self.value = 0.0

    def set(self, v):
        with self._lock:
            self.value = v
        return self


class Histogram:
    """Ring of the most recent observations with nearest-rank
    percentiles (the serving latency ring's semantics, shared)."""

    __slots__ = ("_lock", "_ring", "_n", "_sum", "last")

    def __init__(self, lock, ring_size=DEFAULT_RING):
        self._lock = lock
        self._ring = np.zeros(int(ring_size), dtype=np.float64)
        self._n = 0          # total observations ever recorded
        self._sum = 0.0
        self.last = 0.0

    def observe(self, v):
        v = float(v)
        with self._lock:
            self._ring[self._n % len(self._ring)] = v
            self._n += 1
            self._sum += v
            self.last = v
        return self

    @property
    def count(self):
        return self._n

    @property
    def total(self):
        return self._sum

    @property
    def window(self):
        """Observations currently inside the ring."""
        return min(self._n, len(self._ring))

    def percentiles(self, pcts=(50, 95, 99)):
        """{p: value} over the ring's recorded window; empty dict
        before the first observation (nearest-rank — see
        `nearest_rank`)."""
        with self._lock:
            n = min(self._n, len(self._ring))
            if n == 0:
                return {}
            window = np.sort(self._ring[:n])
        return {p: nearest_rank(window, p) for p in pcts}

    def summary(self):
        pct = self.percentiles()
        with self._lock:
            return {"count": self._n, "total": round(self._sum, 6),
                    "last": round(self.last, 6),
                    "p50": round(pct.get(50, 0.0), 6),
                    "p95": round(pct.get(95, 0.0), 6),
                    "p99": round(pct.get(99, 0.0), 6)}


class MetricsRegistry:
    """Named counters/gauges/histograms sharing ONE lock (writers are
    short critical sections; a single lock keeps snapshot() consistent
    without lock ordering concerns — the serving metrics' discipline).
    get-or-create accessors are themselves locked so concurrent first
    touches of the same name return the same instrument.

    The lock is REENTRANT and exposed (`lock`) so a caller updating
    several instruments that must stay mutually consistent (e.g. the
    serving layer's request counters + latency ring) can hold it across
    the whole group while the individual `inc`/`observe` calls
    re-acquire it harmlessly."""

    def __init__(self):
        self._lock = threading.RLock()
        self._counters = {}
        self._gauges = {}
        self._hists = {}

    @property
    def lock(self):
        return self._lock

    # ------------------------------------------------------ instruments
    def counter(self, name):
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(self._lock)
        return c

    def gauge(self, name):
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(self._lock)
        return g

    def histogram(self, name, ring_size=DEFAULT_RING):
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram(self._lock, ring_size)
        return h

    # ------------------------------------------------------ conveniences
    def inc(self, name, n=1):
        return self.counter(name).inc(n)

    def set(self, name, v):
        return self.gauge(name).set(v)

    def observe(self, name, v):
        return self.histogram(name).observe(v)

    # ----------------------------------------------------------- readers
    def snapshot(self):
        """One JSON-ready dict: counters and gauges verbatim, histograms
        as {count,total,last,p50,p95,p99} summaries."""
        with self._lock:
            counters = {k: c.value for k, c in self._counters.items()}
            gauges = {k: g.value for k, g in self._gauges.items()}
            hist_names = list(self._hists)
        hists = {k: self._hists[k].summary() for k in hist_names}
        return {"counters": counters, "gauges": gauges,
                "histograms": hists}
