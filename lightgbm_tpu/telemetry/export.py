"""Chrome trace-event export: the run journal as one zoomable timeline.

`tools/export_trace.py <journal dir>` turns a run's journal files
(plus the tracer's span-ring dump when `telemetry_trace=true`) into
trace-event JSON (the Chrome `chrome://tracing` / Perfetto format), so
a multi-rank crash → restart → resume run reads as one timeline:

- one **process track per rank** (pid = rank, named `rank N`), with a
  `train` thread for training records and a `supervisor` thread for
  the supervisor's restart bookkeeping (`source:"supervisor"`);
- **iteration / fused-block records** become duration slices whose
  children are the record's per-phase deltas laid end to end — the
  per-iteration breakdown, zoomable;
- **checkpoints** (`write_s`) and **compiles** (`seconds`) are slices;
  **aborts / restarts / resumes / run boundaries** are flagged instant
  events, so the watchdog's exit-117 story is visible at a glance;
- **grad/hess norms, leaf counts, metric values, memory watermarks
  and model-quality deltas** (`quality` records: gain/split deltas,
  importance shift, eval values, drift psi_max / skew counts) become
  counter tracks (Perfetto plots them);
- a journal `spans` record (the recent-span ring dumped at close)
  becomes fine-grained slices on per-thread lanes — concurrent
  batcher/heartbeat threads get their own tracks via the span tid;
- **`comm` records** (collective latency attribution,
  telemetry/comm_profile.py) get a dedicated `comm` lane per rank:
  one slice per collective wait, a `comm_overlap` counter track, and
  cross-rank **flow events** (the Chrome `s`/`t`/`f` arrows)
  connecting the SAME iteration's matching collective slice on every
  rank — a hung or skewed exchange is a visibly broken/stretched
  arrow between rank tracks.

Everything maps through wall-clock epoch seconds (journal `ts`; span
offsets + the dump's `epoch_ts`), rebased to the run's first event so
Perfetto opens at t=0. Output is a single JSON object
(`{"traceEvents": [...]}`), valid for Perfetto's legacy-JSON loader.
stdlib-only, jax-free, like the rest of the telemetry package.
"""

import json
import os

from . import journal as journal_mod

# fixed thread lanes inside each rank's process track
TID_TRAIN = 0
TID_SUPERVISOR = 1
TID_COMM = 2         # collective wait slices (`comm` records)
TID_TRACE = 3        # distributed-request spans (`trace` records)
TID_SPAN_BASE = 16   # span recording threads map to 16, 17, ...

_INSTANT_EVENTS = {"run_start", "run_end", "resume", "truncate",
                   "abort", "restart", "note", "config", "mesh",
                   "promote", "reject", "rollback"}


def collect_records(source):
    """Journal records from a directory (every `journal.rank*.jsonl`;
    the merged file is redundant with them) or a single JSONL file.
    Returns (records, n_torn)."""
    source = os.fspath(source)
    paths = ([source] if os.path.isfile(source)
             else journal_mod.rank_files(source))
    if not paths and os.path.isdir(source):
        merged = os.path.join(source, journal_mod.MERGED_NAME)
        if os.path.exists(merged):
            paths = [merged]
    records, torn = [], 0
    for path in paths:
        recs, bad = journal_mod.read_journal(path)
        records.extend(recs)
        torn += bad
    records.sort(key=lambda r: r.get("ts", 0.0))
    return records, torn


def _num(v, default=0.0):
    return float(v) if isinstance(v, (int, float)) \
        and not isinstance(v, bool) else default


class _TraceBuilder:
    def __init__(self, t0):
        self.t0 = t0
        self.events = []
        self._procs = {}       # rank -> set of named tids
        self._span_tids = {}   # (rank, raw span tid) -> lane

    def _us(self, ts):
        return max(0, int(round((ts - self.t0) * 1e6)))

    def _ensure_thread(self, rank, tid, name):
        rank = int(rank)
        named = self._procs.setdefault(rank, set())
        if not named:
            self.events.append({"name": "process_name", "ph": "M",
                                "pid": rank, "tid": 0,
                                "args": {"name": f"rank {rank}"}})
        if tid not in named:
            named.add(tid)
            self.events.append({"name": "thread_name", "ph": "M",
                                "pid": rank, "tid": tid,
                                "args": {"name": name}})

    def _span_lane(self, rank, raw_tid):
        key = (int(rank), raw_tid)
        lane = self._span_tids.get(key)
        if lane is None:
            lane = TID_SPAN_BASE + len(
                [k for k in self._span_tids if k[0] == int(rank)])
            self._span_tids[key] = lane
            self._ensure_thread(rank, lane, f"spans thread-{raw_tid}")
        return lane

    def slice(self, rank, tid, name, start_ts, dur_s, args=None):
        self.events.append({"name": str(name), "ph": "X", "cat": "journal",
                            "ts": self._us(start_ts),
                            "dur": max(1, int(round(dur_s * 1e6))),
                            "pid": int(rank), "tid": tid,
                            **({"args": args} if args else {})})

    def instant(self, rank, tid, name, ts, args=None):
        self.events.append({"name": str(name), "ph": "i", "cat": "journal",
                            "s": "p",   # process-scoped flag line
                            "ts": self._us(ts), "pid": int(rank),
                            "tid": tid,
                            **({"args": args} if args else {})})

    def counter(self, rank, name, ts, values):
        values = {k: _num(v) for k, v in values.items()
                  if isinstance(v, (int, float))
                  and not isinstance(v, bool)}
        if values:
            self.events.append({"name": str(name), "ph": "C",
                                "cat": "journal", "ts": self._us(ts),
                                "pid": int(rank), "tid": TID_TRAIN,
                                "args": values})


def build_trace(records):
    """Journal records (any order; each carries `rank`/`ts`) -> the
    trace-event JSON object. Raises ValueError when there is nothing
    to export."""
    records = [r for r in records
               if isinstance(r, dict) and isinstance(r.get("ts"),
                                                     (int, float))]
    if not records:
        raise ValueError("no journal records to export")
    records.sort(key=lambda r: r["ts"])
    # rebase to the earliest wall time any event can start: iteration /
    # checkpoint / compile slices start their duration BEFORE the
    # record's ts, and a spans dump can reach back to its tracer epoch
    # — missing one would clamp that slice at t=0 and shift its end
    t0 = records[0]["ts"]
    for rec in records:
        event = rec.get("event")
        if event == "iteration":
            t0 = min(t0, rec["ts"] - sum(
                _num(v) for v in (rec.get("phases") or {}).values()))
        elif event == "checkpoint":
            t0 = min(t0, rec["ts"] - _num(rec.get("write_s")))
        elif event == "compile":
            t0 = min(t0, rec["ts"] - _num(rec.get("seconds")))
        elif event == "spans":
            starts = [_num(s.get("start_s")) for s in rec.get("spans", [])
                      if isinstance(s, dict)]
            if starts:
                t0 = min(t0, _num(rec.get("epoch_ts"), t0) + min(starts))
        elif event == "trace":
            # request spans carry their own wall start, earlier than
            # the journal ts the fragment was flushed at
            t0 = min(t0, _num(rec.get("start"), rec["ts"]))
    b = _TraceBuilder(t0)
    # (iteration, collective) -> [(rank, anchor_ts_us)] for the
    # cross-rank flow pass below
    comm_anchors = {}
    # trace_id -> [(anchor_ts_us, rank)] for the cross-process
    # request-flow pass (router track -> replica track arrows)
    trace_anchors = {}

    for rec in records:
        event = rec.get("event")
        rank = int(rec.get("rank", 0) or 0)
        ts = rec["ts"]
        supervisor = rec.get("source") == "supervisor"
        tid = TID_SUPERVISOR if supervisor else TID_TRAIN
        b._ensure_thread(rank, tid,
                         "supervisor" if supervisor else "train")

        if event == "iteration":
            phases = {k: _num(v) for k, v in (rec.get("phases")
                                              or {}).items()}
            dur = sum(phases.values())
            it = rec.get("iteration", 0)
            name = (f"block -> iter {it}" if rec.get("fused")
                    else f"iteration {it}")
            args = {k: rec[k] for k in ("iteration", "block", "leaf_count",
                                        "compile_cache_hit")
                    if k in rec and rec[k] is not None}
            b.slice(rank, tid, name, ts - dur, max(dur, 1e-6), args)
            cursor = ts - dur
            for pname, psecs in phases.items():
                if psecs > 0:
                    b.slice(rank, tid, pname, cursor, psecs)
                    cursor += psecs
            b.counter(rank, "training_health", ts,
                      {k: rec[k] for k in ("grad_norm", "hess_norm",
                                           "leaf_count") if k in rec})
            comm = rec.get("collective_bytes")
            if isinstance(comm, dict):
                # meshed-learner wire-byte track (parallel/mesh.py
                # CommPlan deltas): plots hist_reduce/split_gather/
                # leaf_sync next to the phase slices, so a comms-bound
                # iteration is visible at a glance
                vals = {k: v for k, v in comm.items()
                        if isinstance(v, (int, float))}
                if vals:
                    b.counter(rank, "collective_bytes", ts, vals)
        elif event == "comm":
            # one slice per collective wait on the rank's comm lane,
            # laid end to end backwards from the record's ts (the
            # per-phase convention); each slice's midpoint is the flow
            # anchor — the arrow binds to the enclosing slice
            b._ensure_thread(rank, TID_COMM, "comm")
            waits = {k: _num(v) for k, v in (rec.get("waits")
                                             or {}).items()}
            it = rec.get("iteration", 0)
            cursor = ts - sum(waits.values())
            for cname, csecs in sorted(waits.items()):
                if csecs <= 0:
                    continue
                b.slice(rank, TID_COMM, cname, cursor, csecs,
                        {"iteration": it})
                anchor = b._us(cursor + csecs / 2.0)
                comm_anchors.setdefault((it, cname), []).append(
                    (rank, anchor))
                cursor += csecs
            b.counter(rank, "comm_overlap", ts,
                      {k: rec[k] for k in ("overlap_pct", "wait_s",
                                           "dispatch_s") if k in rec})
        elif event == "metrics":
            b.counter(rank, "metrics", ts, rec.get("values") or {})
        elif event == "quality":
            # model-quality counter track (quality_telemetry knob):
            # split/gain deltas, importance drift, plus the serving-
            # side psi_max/skew_count when a drift e2e journaled them;
            # the record's eval values ride the same track so the
            # metric curve lines up with the gain curve
            vals = {k: rec[k] for k in ("gain_total", "splits", "trees",
                                        "importance_shift", "psi_max",
                                        "skew_count") if k in rec}
            vals.update(rec.get("values") or {})
            b.counter(rank, "quality", ts, vals)
        elif event == "memory":
            b.counter(rank, "memory_bytes", ts,
                      {k: rec[k] for k in ("device_bytes_in_use",
                                           "device_peak_bytes",
                                           "host_rss_bytes",
                                           "host_peak_rss_bytes")
                       if k in rec})
        elif event == "checkpoint":
            dur = _num(rec.get("write_s"), 1e-6)
            b.slice(rank, tid, f"checkpoint @{rec.get('iteration')}",
                    ts - dur, dur, {"path": str(rec.get("path", ""))})
        elif event == "compile":
            dur = _num(rec.get("seconds"), 0.0)
            label = rec.get("label") or "jit"
            b.slice(rank, tid, f"compile {label}", ts - dur,
                    max(dur, 1e-6),
                    {"cache_hit": bool(rec.get("cache_hit"))})
        elif event == "trace":
            # one distributed-request span per record on the rank's
            # `requests` lane; the trace_id groups them and the flow
            # pass below draws the cross-process arrows
            trace_id = rec.get("trace_id")
            if not isinstance(trace_id, str) or not trace_id:
                continue
            b._ensure_thread(rank, TID_TRACE, "requests")
            dur = max(_num(rec.get("duration_s")), 1e-6)
            start = _num(rec.get("start"), ts)
            args = {"trace_id": trace_id,
                    "span_id": rec.get("span_id", ""),
                    "status": rec.get("status", "ok"),
                    "service": rec.get("service", "")}
            tags = rec.get("tags")
            if isinstance(tags, dict) and tags:
                args["tags"] = tags
            b.slice(rank, TID_TRACE, rec.get("name", "span"),
                    start, dur, args)
            trace_anchors.setdefault(trace_id, []).append(
                (b._us(start + dur / 2.0), rank))
        elif event == "spans":
            epoch = _num(rec.get("epoch_ts"), ts)
            for span in rec.get("spans") or []:
                if not isinstance(span, dict):
                    continue
                dur = _num(span.get("duration_s"), 0.0)
                lane = b._span_lane(rank, span.get("tid", 0))
                b.slice(rank, lane, span.get("name", "span"),
                        epoch + _num(span.get("start_s")), max(dur, 1e-6),
                        {"path": span.get("path", "")})
        elif event in _INSTANT_EVENTS:
            args = {k: v for k, v in rec.items()
                    if k not in ("ts", "event", "rank") and v is not None}
            name = event
            if event == "abort":
                name = f"abort exit={rec.get('exit_code')}"
            elif event == "restart":
                name = f"restart attempt={rec.get('attempt')}"
            elif event == "resume":
                name = f"resume @{rec.get('iteration')}"
            elif event == "mesh":
                # mesh (re-)derivation marker: across an elastic shrink
                # the shards/f_loc args change between two of these
                name = f"mesh {rec.get('shards')} shard(s)"
            elif event in ("promote", "reject", "rollback"):
                # fleet registry transitions: model generations as
                # markers on the same timeline as training progress
                name = f"{event} v{rec.get('version')}"
            b.instant(rank, tid, name, ts, args or None)
        # unknown events are skipped: the exporter must keep working on
        # journals from a newer schema

    # cross-rank flow events: one arrow chain per (iteration,
    # collective) that >= 2 ranks recorded — start (`s`) on the
    # lowest rank's slice, steps (`t`) through the middle, finish
    # (`f`) on the last; matching name+cat+id is what the Chrome/
    # Perfetto loaders chain on, and each event's ts lies inside its
    # rank's slice so the arrow binds to it
    flow_id = 0
    for (it, cname), anchors in sorted(comm_anchors.items()):
        ranks = sorted(set(anchors))
        if len({r for r, _ in ranks}) < 2:
            continue
        flow_id += 1
        last = len(ranks) - 1
        for idx, (rank, ts_us) in enumerate(ranks):
            ph = "s" if idx == 0 else ("f" if idx == last else "t")
            ev = {"name": f"{cname} it{it}", "ph": ph,
                  "cat": "comm_flow", "id": flow_id, "pid": rank,
                  "tid": TID_COMM, "ts": ts_us}
            if ph == "f":
                ev["bp"] = "e"   # bind to the enclosing slice
            b.events.append(ev)

    # cross-process request flows: one arrow chain per trace_id whose
    # spans landed on >= 2 process tracks (router pid -> replica pid).
    # String flow ids ("trace:<id>") keep the namespace disjoint from
    # the integer comm-flow ids above; same one-`s`-one-`f` rule
    for trace_id, anchors in sorted(trace_anchors.items()):
        anchors = sorted(set(anchors))
        if len({r for _, r in anchors}) < 2:
            continue
        last = len(anchors) - 1
        for idx, (ts_us, rank) in enumerate(anchors):
            ph = "s" if idx == 0 else ("f" if idx == last else "t")
            ev = {"name": f"request {trace_id[:8]}", "ph": ph,
                  "cat": "trace_flow", "id": f"trace:{trace_id}",
                  "pid": rank, "tid": TID_TRACE, "ts": ts_us}
            if ph == "f":
                ev["bp"] = "e"
            b.events.append(ev)

    # stable nesting: same-timestamp slices sort longest-first so
    # children fall inside their parent when Perfetto infers stacks
    b.events.sort(key=lambda e: (e.get("pid", 0), e.get("tid", 0),
                                 e.get("ts", 0), -e.get("dur", 0)))
    return {"traceEvents": b.events, "displayTimeUnit": "ms"}


def validate_trace(trace):
    """Invariant check of a built/loaded trace object; returns a list
    of error strings (empty = valid). The `make verify-obs` round-trip
    runs this on the re-loaded JSON."""
    errors = []
    if not isinstance(trace, dict):
        return ["trace is not an object"]
    events = trace.get("traceEvents")
    if not isinstance(events, list) or not events:
        return ["traceEvents missing or empty"]
    for i, e in enumerate(events):
        if not isinstance(e, dict):
            errors.append(f"event {i}: not an object")
            continue
        if not isinstance(e.get("name"), str) or not e.get("name"):
            errors.append(f"event {i}: missing name")
        if e.get("ph") not in ("X", "i", "C", "M", "s", "t", "f"):
            errors.append(f"event {i}: unknown phase {e.get('ph')!r}")
        if e.get("ph") != "M":
            ts = e.get("ts")
            if not isinstance(ts, (int, float)) or ts < 0:
                errors.append(f"event {i}: bad ts {ts!r}")
        for key in ("pid", "tid"):
            if not isinstance(e.get(key), int):
                errors.append(f"event {i}: missing int {key}")
        if e.get("ph") == "X":
            dur = e.get("dur")
            if not isinstance(dur, (int, float)) or dur <= 0:
                errors.append(f"event {i}: X event needs dur > 0")
        if e.get("ph") in ("s", "t", "f"):
            # flow events must carry a binding id, and a flow id used
            # by only one event draws nothing — every id needs a
            # start AND a finish
            if not isinstance(e.get("id"), (int, str)):
                errors.append(f"event {i}: flow event needs an id")
        if e.get("ph") == "C":
            # counter tracks (training_health, metrics, memory_bytes,
            # quality) must carry a non-empty all-numeric args dict —
            # Perfetto silently drops anything else
            args = e.get("args")
            if not isinstance(args, dict) or not args:
                errors.append(f"event {i}: C event needs non-empty args")
            elif any(not isinstance(v, (int, float))
                     or isinstance(v, bool) for v in args.values()):
                errors.append(f"event {i}: C event args must be numeric")
    flows = {}
    for e in events:
        if isinstance(e, dict) and e.get("ph") in ("s", "t", "f"):
            flows.setdefault(e.get("id"), []).append(e.get("ph"))
    for fid, phases in flows.items():
        if phases.count("s") != 1 or phases.count("f") != 1:
            errors.append(f"flow id {fid!r}: needs exactly one 's' and "
                          f"one 'f', got {sorted(phases)}")
    try:
        json.dumps(trace, allow_nan=False)
    except (TypeError, ValueError) as exc:
        errors.append(f"not strict-JSON serializable: {exc}")
    return errors


def export_trace(source, out_path=None):
    """Journal dir/file -> trace JSON written to `out_path` (default
    `<dir>/trace.json`). Returns (trace_object, out_path)."""
    records, torn = collect_records(source)
    if torn:
        from ..utils.log import Log
        Log.warning("trace export: skipped %d torn journal line(s)", torn)
    trace = build_trace(records)
    if out_path is None:
        base = source if os.path.isdir(source) else os.path.dirname(source)
        out_path = os.path.join(base or ".", "trace.json")
    tmp = f"{out_path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(trace, f, separators=(",", ":"), allow_nan=False)
    os.replace(tmp, out_path)
    return trace, out_path
