"""Device-memory sampling + compile ledger.

Two halves of the "where did the device go" question the span tracer
cannot answer:

- **Memory** (`sample_memory`): allocator watermarks — device
  `bytes_in_use` / `peak_bytes_in_use` from `Device.memory_stats()`
  (TPU/GPU allocators publish them; this image's CPU jax returns None,
  so the host RSS / peak-RSS pair from `/proc` + `getrusage` always
  rides along). GBDT samples at iteration/block boundaries into
  registry gauges + journal `memory` records, so an OOM-shaped run is
  diagnosable from the timeline instead of a post-mortem.
- **Compiles** (`CompileLedger`): every jit lowering the process pays
  for, attributed to a caller-named shape bucket. jax's monitoring
  stream has the raw events (`/jax/core/compile/backend_compile_duration`
  per backend compile, `/jax/compilation_cache/cache_hits|misses` for
  the persistent cache) but no attribution; the ledger adds a
  thread-local label stack (`with LEDGER.label("fused_scan_10it"):`)
  so the fused trainer's lowerings and the serving warmup's per-bucket
  compiles are separable line items on /trainz and /metricz.

The module is jax-free until `CompileLedger.install()` runs (a no-op
without jax); `sample_memory` only touches jax when the embedder
already imported it. Process-wide singleton (`LEDGER`) — jax's
monitoring stream is process-global, same shape as journal.current().
"""

import os
import threading
import time
from collections import deque

RECENT_COMPILES = 256

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"


class CompileLedger:
    """Process-wide ledger of jit lowerings (see module docstring).

    `install()` registers the jax.monitoring listeners once;
    `label(name)` attributes compiles on the current thread;
    `snapshot()` is the /trainz / /metricz view; `drain()` hands new
    entries to the journal writer exactly once each.
    """

    def __init__(self, ring=RECENT_COMPILES):
        self._lock = threading.Lock()
        self._local = threading.local()
        self._recent = deque(maxlen=ring)
        self._undrained = []
        self.compiles = 0
        self.total_s = 0.0
        self.cache_hits = 0
        self.cache_misses = 0
        self._installed = False

    # ----------------------------------------------------------- labels
    def _labels(self):
        stack = getattr(self._local, "labels", None)
        if stack is None:
            stack = self._local.labels = []
        return stack

    def current_label(self):
        stack = self._labels()
        return stack[-1] if stack else ""

    def label(self, name):
        """Context manager attributing compiles inside it to `name`
        (innermost label wins)."""
        return _LabelContext(self, str(name))

    # -------------------------------------------------------- listeners
    def install(self):
        """Register the jax.monitoring listeners (idempotent; a no-op
        when jax is absent — the ledger then just stays empty)."""
        with self._lock:
            if self._installed:
                return self
            self._installed = True
        try:
            import jax
            jax.monitoring.register_event_duration_secs_listener(
                self._on_duration)
            jax.monitoring.register_event_listener(self._on_event)
        except Exception:
            # monitoring API drift / missing jax must never break
            # training; the ledger simply records nothing
            pass
        return self

    def _append(self, entry):
        self._recent.append(entry)
        self._undrained.append(entry)

    def _on_duration(self, name, secs, **kwargs):
        if name != _COMPILE_EVENT:
            return
        entry = {"label": self.current_label(), "seconds": float(secs),
                 "ts": time.time(), "cache_hit": False}
        with self._lock:
            self.compiles += 1
            self.total_s += float(secs)
            self._append(entry)

    def _on_event(self, name, **kwargs):
        if name == _CACHE_HIT_EVENT:
            # a hit deserializes the executable instead of compiling:
            # no backend_compile_duration fires, so the hit IS the
            # ledger entry for that lowering
            entry = {"label": self.current_label(), "seconds": 0.0,
                     "ts": time.time(), "cache_hit": True}
            with self._lock:
                self.cache_hits += 1
                self._append(entry)
        elif name == _CACHE_MISS_EVENT:
            with self._lock:
                self.cache_misses += 1

    # ----------------------------------------------------------- readers
    def snapshot(self, recent_n=32):
        """JSON-ready totals + the most recent entries."""
        with self._lock:
            recent = (list(self._recent)[-int(recent_n):]
                      if recent_n else [])
            return {"compiles": self.compiles,
                    "total_s": round(self.total_s, 6),
                    "cache_hits": self.cache_hits,
                    "cache_misses": self.cache_misses,
                    "recent": [dict(e) for e in recent]}

    def drain(self):
        """Entries recorded since the previous drain (journal writer's
        read-once view)."""
        with self._lock:
            out, self._undrained = self._undrained, []
        return out

    def reset(self):
        """Zero the totals (tests; the listeners stay installed)."""
        with self._lock:
            self._recent.clear()
            self._undrained = []
            self.compiles = 0
            self.total_s = 0.0
            self.cache_hits = 0
            self.cache_misses = 0


class _LabelContext:
    __slots__ = ("_ledger", "_name")

    def __init__(self, ledger, name):
        self._ledger = ledger
        self._name = name

    def __enter__(self):
        self._ledger._labels().append(self._name)
        return self

    def __exit__(self, exc_type, exc, tb):
        stack = self._ledger._labels()
        if stack and stack[-1] == self._name:
            stack.pop()
        return False


LEDGER = CompileLedger()


# ------------------------------------------------------- memory sampling

_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def _host_rss_bytes():
    """Current RSS from /proc/self/statm (one read, ~microseconds)."""
    try:
        with open("/proc/self/statm", "rb") as f:
            return int(f.read().split()[1]) * _PAGE_SIZE
    except (OSError, ValueError, IndexError):
        return None


def _host_peak_rss_bytes():
    try:
        import resource
        # linux ru_maxrss is kilobytes
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:
        return None


def _device_memory():
    """(bytes_in_use, peak_bytes_in_use) from the first local device's
    allocator, or (None, None) when unavailable (CPU jax publishes no
    stats; jax not imported means no device to ask)."""
    import sys
    jax = sys.modules.get("jax")
    if jax is None:
        return None, None
    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return None, None
    if not stats:
        return None, None
    in_use = stats.get("bytes_in_use")
    peak = stats.get("peak_bytes_in_use", in_use)
    return (int(in_use) if in_use is not None else None,
            int(peak) if peak is not None else None)


def sample_memory():
    """One point-in-time memory sample: only the fields that exist on
    this backend (journal `memory` records carry exactly these keys)."""
    out = {}
    dev, dev_peak = _device_memory()
    if dev is not None:
        out["device_bytes_in_use"] = dev
    if dev_peak is not None:
        out["device_peak_bytes"] = dev_peak
    rss = _host_rss_bytes()
    if rss is not None:
        out["host_rss_bytes"] = rss
    peak = _host_peak_rss_bytes()
    if peak is not None:
        out["host_peak_rss_bytes"] = peak
    return out
