"""Collective latency & overlap attribution across ranks.

PR 10's mesh layer counts collective *bytes* exactly (parallel/mesh.py
CommPlan) but bytes moved say nothing about latency hidden: the whole
point of the `comm_groups` reduce-scatter split is that group g+1's
all_to_all flies while group g's split search runs, and until now
nothing measured whether that overlap actually happens, or which rank
is the straggler everyone else waits for. Distributed-GBDT scaling
claims live or die on per-phase timing breakdowns (arXiv:1706.08359,
arXiv:1806.11248) — this module is that instrument for the comm side.

What the host CAN measure: XLA collectives execute inside the traced
program, invisible to Python. But with jax's async dispatch, any comm
latency NOT hidden under compute surfaces as host-visible blocking at
the points where results are consumed — exactly the sections the
collective watchdog already brackets (`heartbeat.collective_guard`:
`leaf_count_sync`, `row_leaf_gather`, `leaf_value_fetch`, ...). The
profiler rides the existing `bind_timing_sink` hook, attributes each
guarded section's elapsed seconds to its collective name, and splits
them into

- **sync waits** — sections that only wait for a device/cross-rank
  result (everything except the dispatch windows); residual comm
  latency the overlap failed to hide, plus straggler skew;
- **dispatch windows** — sections that contain the compute itself
  (`*tree_build`, `fused_block`); reported separately, never counted
  as wait.

Per journal record (one per iteration/fused block):

    comm_overlap_pct = 100 * (1 - wait_s / wall_s)

the mesh analogue of the out-of-core prefetcher's
`prefetch_overlap_pct` (data/prefetch.py): 100 means every byte of
collective latency hid under compute; a drop means ranks are stalling
at the sync points — comm-bound or straggling.

Straggler attribution needs peer data: each rank publishes its
cumulative wait through the heartbeat piggyback
(`heartbeat.bind_beat_extra` -> beat field `comm_wait_s`), so
`straggler_deltas` can report, per rank, how much more that rank has
waited than the fleet's fastest — the slowest rank is the victim of
the straggler, the rank with delta ~0 is the straggler itself.

jax-free, stdlib-only, like the rest of the telemetry package. Wired
by models/gbdt.py under the `comm_telemetry` knob; journal `comm`
records (telemetry/journal.py SCHEMA), the /trainz `comm` source, the
fleet aggregator and bench.py's dist_probe all read this one class.
"""

import threading
import time

# guarded sections whose elapsed time CONTAINS the tree build's compute
# (the collectives inside them are the ones overlap is supposed to
# hide) — attributed as dispatch, never as wait
DISPATCH_SECTIONS = ("tree_build", "fused_block")


def is_dispatch(name):
    return str(name).endswith(DISPATCH_SECTIONS)


def overlap_pct(wait_s, wall_s):
    """100 = all collective latency hidden under compute; clipped to
    [0, 100] (a wait can span a wall boundary by a rounding hair)."""
    if wall_s <= 0:
        return 100.0
    return max(0.0, min(100.0, 100.0 * (1.0 - wait_s / wall_s)))


class CommProfiler:
    """Per-process collective timing accumulator (see module
    docstring). `record` is the timing-sink callback — a dict update
    under one lock, cheap enough for every guarded section; `flush`
    closes one iteration/block window and returns the journal-ready
    `comm` record."""

    def __init__(self, rank=0):
        self.rank = int(rank)
        self._lock = threading.Lock()
        self._window = {}    # collective name -> seconds since flush
        self._totals = {}    # collective name -> [count, seconds]
        self._mark = time.monotonic()
        self.cum_wait_s = 0.0       # sync waits only, process-cumulative
        self.cum_dispatch_s = 0.0
        self.cum_wall_s = 0.0       # wall covered by flushed windows
        self.last = {}               # last flushed record (live views)

    # ------------------------------------------------------------ writers
    def record(self, name, seconds):
        """Timing-sink callback: one guarded section completed."""
        name = str(name)
        seconds = float(seconds)
        with self._lock:
            self._window[name] = self._window.get(name, 0.0) + seconds
            tot = self._totals.get(name)
            if tot is None:
                tot = self._totals[name] = [0, 0.0]
            tot[0] += 1
            tot[1] += seconds
            if is_dispatch(name):
                self.cum_dispatch_s += seconds
            else:
                self.cum_wait_s += seconds

    def flush(self, iteration):
        """Close the current window: per-collective waits since the
        last flush, the wall seconds the window covered, and the
        derived overlap. Returns the `comm` journal record, or None
        when nothing was measured (no sink-armed sections ran — e.g.
        telemetry off, or a serial run before the first sync)."""
        now = time.monotonic()
        with self._lock:
            wall = max(now - self._mark, 1e-9)
            self._mark = now
            self.cum_wall_s += wall
            if not self._window:
                return None
            waits = {n: round(s, 6) for n, s in self._window.items()}
            self._window = {}
        wait = sum(s for n, s in waits.items() if not is_dispatch(n))
        dispatch = sum(s for n, s in waits.items() if is_dispatch(n))
        rec = {"iteration": int(iteration), "waits": waits,
               "wait_s": round(wait, 6),
               "dispatch_s": round(dispatch, 6),
               "wall_s": round(wall, 6),
               "overlap_pct": round(overlap_pct(wait, wall), 2)}
        self.last = rec
        return rec

    # ------------------------------------------------------------ readers
    def totals(self):
        """{collective: {count, seconds}} over the process lifetime."""
        with self._lock:
            return {n: {"count": c, "seconds": round(s, 6)}
                    for n, (c, s) in sorted(self._totals.items())}

    def straggler_deltas(self, service=None):
        """{rank: seconds} of extra cumulative collective wait vs the
        fleet's fastest rank, from the heartbeat beats (peers publish
        `comm_wait_s` via the beat piggyback). None without a running
        heartbeat service or before peers have published. Reads the
        beat files directly — the monitor thread owns the service's
        freshness state, a scrape must not mutate it."""
        if service is None:
            from ..parallel import heartbeat
            service = heartbeat.service()
        if service is None:
            return None
        from ..parallel import heartbeat
        waits = {self.rank: self.cum_wait_s}
        for rank in range(service.num_ranks):
            if rank == self.rank:
                continue
            beat = heartbeat.read_heartbeat(
                heartbeat.heartbeat_path(service.directory, rank))
            if beat is not None and isinstance(
                    beat.get("comm_wait_s"), (int, float)):
                waits[rank] = float(beat["comm_wait_s"])
        if len(waits) < 2:
            return None
        fastest = min(waits.values())
        return {str(r): round(w - fastest, 6)
                for r, w in sorted(waits.items())}

    def snapshot(self, service=None):
        """The /trainz + aggregator view: lifetime per-collective
        totals, cumulative wait/dispatch split, the last flushed
        per-iteration record, and the straggler deltas when a
        heartbeat service is running."""
        with self._lock:
            cum_wait = self.cum_wait_s
            cum_dispatch = self.cum_dispatch_s
            cum_wall = self.cum_wall_s
            last = dict(self.last)
        out = {"rank": self.rank,
               "cum_wait_s": round(cum_wait, 6),
               "cum_dispatch_s": round(cum_dispatch, 6),
               "cum_wall_s": round(cum_wall, 6),
               "totals": self.totals(),
               "last": last}
        if "overlap_pct" in last:
            out["overlap_pct"] = last["overlap_pct"]
        if cum_wall > 0:
            # run-aggregate view: one number for the whole run, not
            # the latest window — what bench/history should trend (a
            # single iteration's overlap is per-iteration noise)
            out["run_overlap_pct"] = round(
                overlap_pct(cum_wait, cum_wall), 2)
        deltas = self.straggler_deltas(service)
        if deltas is not None:
            out["straggler_s"] = deltas
        return out
