"""Roofline attribution: live per-kernel bandwidth vs a measured peak.

GPU boosting systems treat per-kernel achieved bandwidth as the
primary tuning instrument (arXiv:1706.08359 §5, arXiv:2005.09148); the
bench already computes a one-shot `hist_bytes_per_s` microprobe. This
module makes the number LIVE: the histogram host-callback kernels
(ops/histogram.py bincount mode — the CPU default and where the
engine's 9.7x lives) time themselves and record (seconds, bytes
streamed, rows scanned) per call into a process-wide table; /trainz
serves per-kernel achieved bytes/s and rows/s against a once-measured
STREAM-style copy peak, and `roofline_warn_fraction > 0` flags kernels
running below that fraction of peak at end of run.

Scope is honest by construction: only kernels whose execution the host
can actually observe record live (the bincount callbacks run ON the
host; fully in-graph kernels — Pallas, einsum, segment — are invisible
to host timers inside one XLA program and stay covered by the bench's
single-op microprobes, tools/microbench.py). The table is process-wide
(the callbacks have no booster handle), same singleton shape as
journal.current().

The peak is measured lazily once per process (a ~64 MB numpy copy
triad — memcpy streams 2x the buffer, the classic STREAM COPY
accounting) and can be pinned via LIGHTGBM_TPU_STREAM_PEAK (bytes/s)
when a machine's number is already known (tools/microbench.py prints
it as `stream_host`).
"""

import os
import threading
import time

PEAK_ENV = "LIGHTGBM_TPU_STREAM_PEAK"

_PEAK_LOCK = threading.Lock()
_PEAK = None


def measure_stream_peak(size_mb=64, reps=3):
    """STREAM-style COPY bandwidth of this host (bytes/s): best of
    `reps` timed copies of a `size_mb` buffer, counting read+write
    bytes. ~50 ms once per process at the default size."""
    import numpy as np
    n = int(size_mb) * (1 << 20) // 8
    src = np.ones(n, dtype=np.float64)
    dst = np.empty_like(src)
    best = 0.0
    for _ in range(int(reps)):
        t0 = time.perf_counter()
        np.copyto(dst, src)
        dt = time.perf_counter() - t0
        best = max(best, 2.0 * src.nbytes / max(dt, 1e-9))
    return best


def stream_peak_bytes_per_s():
    """The cached process-wide peak (env override wins; measured once
    otherwise)."""
    global _PEAK
    with _PEAK_LOCK:
        if _PEAK is None:
            env = os.environ.get(PEAK_ENV)
            if env:
                try:
                    _PEAK = float(env)
                except ValueError:
                    _PEAK = measure_stream_peak()
            else:
                _PEAK = measure_stream_peak()
        return _PEAK


class RooflineTable:
    """Per-kernel (calls, seconds, bytes, rows) accumulator with
    peak-relative snapshots."""

    def __init__(self):
        self._lock = threading.Lock()
        self._kernels = {}

    def record(self, kernel, seconds, nbytes, rows):
        """One kernel execution: `nbytes` streamed, `rows` scanned, in
        `seconds` of host wall time. O(1), one short lock hold — cheap
        enough for once-per-histogram-build call sites."""
        with self._lock:
            k = self._kernels.get(kernel)
            if k is None:
                k = self._kernels[kernel] = {"calls": 0, "seconds": 0.0,
                                             "bytes": 0, "rows": 0}
            k["calls"] += 1
            k["seconds"] += float(seconds)
            k["bytes"] += int(nbytes)
            k["rows"] += int(rows)

    def reset(self):
        with self._lock:
            self._kernels.clear()

    def snapshot(self, warn_fraction=0.0, peak=None):
        """JSON-ready per-kernel roofline view. `peak` defaults to the
        lazily-measured host STREAM peak; kernels whose achieved
        bytes/s fall below `warn_fraction * peak` carry
        `below_peak_fraction: true` (the end-of-run warning's input,
        models/gbdt.py)."""
        with self._lock:
            kernels = {name: dict(k) for name, k in self._kernels.items()}
        if not kernels:
            return {"peak_bytes_per_s": None, "kernels": {}}
        if peak is None:
            peak = stream_peak_bytes_per_s()
        out = {}
        for name, k in kernels.items():
            secs = k["seconds"]
            entry = {"calls": k["calls"], "seconds": round(secs, 6),
                     "bytes": k["bytes"], "rows": k["rows"]}
            if secs > 0:
                bps = k["bytes"] / secs
                entry["bytes_per_s"] = round(bps, 1)
                entry["rows_per_s"] = round(k["rows"] / secs, 1)
                if peak:
                    entry["pct_of_peak"] = round(100.0 * bps / peak, 2)
                    if warn_fraction > 0:
                        entry["below_peak_fraction"] = \
                            bool(bps < warn_fraction * peak)
            out[name] = entry
        return {"peak_bytes_per_s": round(peak, 1) if peak else None,
                "kernels": out}


TABLE = RooflineTable()
