"""Structured run journal: append-only JSONL training timeline.

No reference equivalent — the reference's training record is log text.
The journal gives every run a machine-readable timeline: one record per
completed boosting iteration (or per fused device block — the block is
ONE XLA program, so per-iteration host phases do not exist inside it)
plus run-start / config / checkpoint / resume / abort / restart /
run-end events, all in the same file, so a supervisor restart or a
watchdog abort (exit 117/118, parallel/heartbeat.py) lands in the same
timeline as training progress.

Write discipline (the whole point):

- one file per rank (`journal.rank0000.jsonl`) in a shared directory —
  multi-host ranks never contend on a writer;
- every record is ONE `os.write` of a complete line to an O_APPEND fd:
  appends from concurrent processes (the training child and its
  supervisor share rank files) interleave at line granularity, and a
  `os._exit`-style kill (utils/faults.py hard_crash) can lose at most
  the record being written, never tear an earlier one;
- readers (`read_journal`) skip unparseable lines, so a resumed run
  appends past a torn tail and the timeline stays loadable;
- rank 0 merges all rank files into `journal.jsonl` sorted by wall
  time (`merge_journals`), called at end of training.

The schema (`SCHEMA` below) is the contract `tools/check_journal.py`
lints against and docs/Observability.md documents. This module is
jax-free so the supervisor and CPU test harness import it without
touching the accelerator runtime.
"""

import glob
import json
import os
import threading
import time

from ..utils.log import Log

SCHEMA_VERSION = 1
MERGED_NAME = "journal.jsonl"

# --------------------------------------------------------------- schema
#
# Per-event REQUIRED fields (name -> type). Every record also carries
# the COMMON fields. OPTIONAL fields are type-checked when present;
# unknown extra fields are allowed (forward compatibility), unknown
# event names are not.

COMMON_FIELDS = {"ts": float, "event": str, "rank": int}

# present on every record written by this version, but OPTIONAL in the
# schema so journals from older runs stay valid: `mono` is the writing
# process's time.monotonic() — within one rank it orders records even
# when the wall clock steps (NTP slew, a skewed host), which is what
# `merge_journals` sorts each rank file by before interleaving ranks
OPTIONAL_COMMON_FIELDS = {"mono": float, "source": str}

SCHEMA = {
    "run_start": {"required": {"schema": int, "pid": int},
                  "optional": {"run_id": str, "argv": list,
                               "num_ranks": int, "source": str}},
    "config": {"required": {"params": dict}, "optional": {}},
    "iteration": {"required": {"iteration": int},
                  "optional": {"phases": dict, "block": int,
                               "grad_norm": float, "hess_norm": float,
                               "leaf_count": int,
                               "compile_cache_hit": bool,
                               "fused": bool,
                               # out-of-core streaming (data/ooc_learner)
                               "prefetch_wait_s": float,
                               "prefetch_bytes": int,
                               "prefetch_overlap_pct": float,
                               # per-kind collective wire bytes this
                               # iteration (parallel/mesh.py CommPlan)
                               "collective_bytes": dict}},
    "metrics": {"required": {"iteration": int, "values": dict},
                "optional": {}},
    # model-quality deltas per iteration/block (`quality_telemetry`
    # knob; telemetry/quality.py QualityTracker): split ledger deltas,
    # top features by gain, leaf-value distribution of the new trees,
    # normalized-gain-importance L1 shift, latest eval values; the
    # serving-side drift e2e also journals psi_max/skew_count here
    "quality": {"required": {"iteration": int},
                "optional": {"trees": int, "splits": int,
                             "gain_total": float, "top_gain": dict,
                             "leaf_values": dict,
                             "importance_shift": float, "values": dict,
                             "psi_max": float, "skew_count": int,
                             "source": str}},
    "checkpoint": {"required": {"iteration": int, "path": str},
                   "optional": {"write_s": float}},
    "resume": {"required": {"iteration": int},
               "optional": {"path": str, "source": str}},
    "truncate": {"required": {"iteration": int, "dropped_iters": int},
                 "optional": {"reason": str}},
    "abort": {"required": {"exit_code": int, "reason": str},
              "optional": {"collective": str, "iteration": int,
                           "dead_ranks": list, "source": str}},
    "restart": {"required": {"attempt": int, "exit_code": int},
                "optional": {"reason": str, "survivors": list,
                             "new_rank": int, "source": str,
                             # world shrank: the relaunch re-derives
                             # the mesh and feature ownership
                             "mesh_reshard": bool}},
    # one record per meshed-learner incarnation (parallel/learners.py):
    # shard count + feature ownership — across an elastic shrink the
    # journal shows the mesh re-sharding, not just the machine list
    "mesh": {"required": {"shards": int},
             "optional": {"processes": int, "precision": str,
                          "exchange": str, "f_pad": int, "f_loc": int,
                          "learner": str, "source": str}},
    # one record per out-of-core learner incarnation: this rank's owned
    # block range over the shared store (data/ooc_learner.py). Across
    # an elastic shrink/grow the journal shows block ownership
    # re-sharding (shards/block_lo/block_hi change, attempt advances)
    # with ZERO `binning` events between — the proof that survivors
    # adopted blocks instead of re-binning (docs/Out-of-Core.md)
    "block_reshard": {"required": {"blocks": int, "shards": int},
                      "optional": {"rank": int, "block_lo": int,
                                   "block_hi": int, "rows": int,
                                   "attempt": int, "learner": str,
                                   "source": str}},
    # one record per block-store BUILD (the two-round streaming binning
    # pass, data/block_store.py) — elastic restarts assert none of
    # these appear after the first incarnation
    "binning": {"required": {"rows": int, "blocks": int},
                "optional": {"directory": str, "features": int,
                             "build_count": int, "source": str}},
    "run_end": {"required": {"iterations": int},
                "optional": {"train_s": float, "source": str}},
    # per-iteration/block collective latency attribution (`comm_telemetry`
    # knob; telemetry/comm_profile.py): host-visible seconds blocked in
    # each armed collective section since the last record (`waits`),
    # split into pure sync waits (`wait_s` — leaf_count_sync,
    # row_leaf_gather, ...) vs dispatch windows that contain compute
    # (`dispatch_s` — tree_build, fused_block), plus the wall seconds
    # the record covers and the derived comm_overlap_pct
    "comm": {"required": {"iteration": int},
             "optional": {"waits": dict, "wait_s": float,
                          "dispatch_s": float, "wall_s": float,
                          "overlap_pct": float, "source": str}},
    # one compact per-run summary appended to RUN_HISTORY.jsonl
    # (telemetry/history.py; tools/sentinel.py trends over the last K
    # of these) — NOT part of the per-run journal timeline, but the
    # same schema machinery lints it
    "run_summary": {"required": {"kind": str},
                    "optional": {"run_id": str, "label": str,
                                 "platform": str, "rows": int,
                                 "iterations": int, "train_s": float,
                                 "auc": float, "metrics": dict,
                                 "peak_memory_bytes": int,
                                 "collective_bytes": int,
                                 "collective_bytes_per_tree": float,
                                 "comm_overlap_pct": float,
                                 "prefetch_overlap_pct": float,
                                 "serving_p99_ms": float,
                                 "telemetry_overhead_pct": float,
                                 "source": str}},
    # fleet registry transitions (fleet/registry.py): one record per
    # pointer move / quarantine, with the validation metrics that drove
    # the decision — the Perfetto export renders them as instant
    # markers on the fleet timeline (docs/Fleet.md)
    "promote": {"required": {"version": int},
                "optional": {"from_version": int, "generation": int,
                             "reason": str, "metric": float,
                             "metric_name": str,
                             "incumbent_metric": float, "source": str}},
    "reject": {"required": {"version": int},
               "optional": {"reason": str, "metric": float,
                            "metric_name": str,
                            "incumbent_metric": float, "source": str}},
    "rollback": {"required": {"version": int},
                 "optional": {"from_version": int, "generation": int,
                              "reason": str, "source": str}},
    # device-memory watermarks sampled at iteration/block boundaries
    # (telemetry/ledger.py sample_memory; device_* absent on backends
    # without allocator stats — this image's CPU jax returns None)
    "memory": {"required": {"iteration": int},
               "optional": {"device_bytes_in_use": int,
                            "device_peak_bytes": int,
                            "host_rss_bytes": int,
                            "host_peak_rss_bytes": int}},
    # one jit lowering (telemetry/ledger.py CompileLedger): label names
    # the shape bucket ("fused_scan_10it", "serving_bucket_256"),
    # seconds is backend-compile wall time (0.0 on a persistent-cache
    # hit), cache_hit whether the persistent compile cache served it
    "compile": {"required": {"label": str},
                "optional": {"seconds": float, "cache_hit": bool,
                             "count": int, "source": str}},
    # dump of the tracer's recent-span ring at close (telemetry_trace
    # knob): epoch_ts maps span start offsets to wall time, spans is
    # the Span.as_dict() list the trace exporter turns into slices
    "spans": {"required": {"epoch_ts": float, "spans": list},
              "optional": {"source": str}},
    # one completed distributed-trace span (telemetry/disttrace.py):
    # start is wall epoch seconds (cross-process comparable), links
    # lists other trace_ids a batch span coalesced (the collector
    # follows them when stitching), flags carries the propagated
    # head-sampling bit. The aggregator's TraceCollector stitches
    # these per-process fragments into /tracez trees
    "trace": {"required": {"trace_id": str, "span_id": str,
                           "name": str, "start": float,
                           "duration_s": float},
              "optional": {"parent_span_id": str, "kind": str,
                           "status": str, "flags": int, "tags": dict,
                           "links": list, "service": str,
                           "source": str}},
    "note": {"required": {}, "optional": {"msg": str, "source": str}},
}

# json types are exact; bool is an int subclass in Python, so int
# checks must reject bools while float checks accept ints
_NUMERIC = (int, float)


def _type_ok(value, expected):
    if expected is float:
        return isinstance(value, _NUMERIC) and not isinstance(value, bool)
    if expected is int:
        return isinstance(value, int) and not isinstance(value, bool)
    return isinstance(value, expected)


def validate_record(rec):
    """Validate one parsed record against SCHEMA. Returns a list of
    error strings (empty = valid)."""
    errors = []
    if not isinstance(rec, dict):
        return [f"record is not an object: {type(rec).__name__}"]
    for name, typ in COMMON_FIELDS.items():
        if name not in rec:
            errors.append(f"missing common field {name!r}")
        elif not _type_ok(rec[name], typ):
            errors.append(f"field {name!r} has type "
                          f"{type(rec[name]).__name__}, want {typ.__name__}")
    for name, typ in OPTIONAL_COMMON_FIELDS.items():
        if name in rec and rec[name] is not None \
                and not _type_ok(rec[name], typ):
            errors.append(f"common field {name!r} has type "
                          f"{type(rec[name]).__name__}, want {typ.__name__}")
    event = rec.get("event")
    if not isinstance(event, str):
        return errors
    spec = SCHEMA.get(event)
    if spec is None:
        errors.append(f"unknown event {event!r}")
        return errors
    for name, typ in spec["required"].items():
        if name not in rec:
            errors.append(f"{event}: missing required field {name!r}")
        elif not _type_ok(rec[name], typ):
            errors.append(f"{event}: field {name!r} has type "
                          f"{type(rec[name]).__name__}, want {typ.__name__}")
    for name, typ in spec["optional"].items():
        # None is legal anywhere optional: the writer null-sanitizes
        # non-finite floats (JSON has no NaN/Inf literal)
        if name in rec and rec[name] is not None \
                and not _type_ok(rec[name], typ):
            errors.append(f"{event}: optional field {name!r} has type "
                          f"{type(rec[name]).__name__}, want {typ.__name__}")
    if event == "iteration":
        for k, v in (rec.get("phases") or {}).items():
            if v is not None and not _type_ok(v, float):
                errors.append(f"iteration: phases[{k!r}] is not a number")
    return errors


# -------------------------------------------------------------- writing

def _sanitize(value):
    """Deep-replace non-finite floats with None so the record stays
    strict JSON."""
    if isinstance(value, float):
        return value if value == value and abs(value) != float("inf") \
            else None
    if isinstance(value, dict):
        return {k: _sanitize(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_sanitize(v) for v in value]
    return value


def journal_path(directory, rank):
    return os.path.join(os.fspath(directory),
                        f"journal.rank{int(rank):04d}.jsonl")


class RunJournal:
    """One rank's append-only journal (see module docstring).

    `emit_run_start=False` attaches to an EXISTING rank file without
    opening a new run (the supervisor appending restart events, a
    resumed child continuing the timeline). `source` tags every record
    from this writer (e.g. "supervisor")."""

    def __init__(self, directory, rank=0, emit_run_start=True, meta=None,
                 source=None):
        self.directory = os.fspath(directory)
        self.rank = int(rank)
        self.source = source
        self.path = journal_path(self.directory, self.rank)
        self._lock = threading.Lock()
        self._fd = None
        try:
            os.makedirs(self.directory, exist_ok=True)
            self._fd = os.open(self.path,
                               os.O_APPEND | os.O_CREAT | os.O_WRONLY,
                               0o644)
        except OSError as e:
            Log.warning("run journal disabled (cannot open %s: %s)",
                        self.path, e)
        if emit_run_start:
            self.event("run_start", schema=SCHEMA_VERSION, pid=os.getpid(),
                       **(meta or {}))

    @property
    def enabled(self):
        return self._fd is not None

    def event(self, event, **fields):
        """Append one record: a single O_APPEND write of a complete
        line. Never raises — a full disk must not kill training."""
        if self._fd is None:
            return
        rec = {"ts": time.time(), "mono": round(time.monotonic(), 6),
               "event": event, "rank": self.rank}
        if self.source is not None:
            rec["source"] = self.source
        rec.update(fields)
        try:
            line = json.dumps(rec, separators=(",", ":"),
                              allow_nan=False) + "\n"
        except (TypeError, ValueError):
            # NaN/Inf (JSON has no literal for them) or a non-JSON
            # value: sanitize rather than drop the record — readers
            # need every line to parse
            line = json.dumps(_sanitize(rec), separators=(",", ":"),
                              allow_nan=False, default=str) + "\n"
        try:
            os.write(self._fd, line.encode("utf-8"))
        except OSError as e:
            Log.warning("journal write failed (%s): %s", self.path, e)

    def iteration(self, iteration, phases=None, **fields):
        if phases:
            fields["phases"] = phases
        self.event("iteration", iteration=int(iteration), **fields)

    def close(self):
        with self._lock:
            if self._fd is not None:
                try:
                    os.close(self._fd)
                except OSError:
                    pass
                self._fd = None

    def __del__(self):
        # Python-API runs may drop a booster without an explicit
        # close_telemetry(); the raw fd must not outlive the journal
        try:
            self.close()
        except Exception:
            pass


# -------------------------------------------------------------- reading

def read_journal(path, strict=False):
    """Parse one JSONL journal file. Torn/garbled lines are skipped
    (and counted) unless `strict`; returns (records, n_bad)."""
    records, bad = [], 0
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except ValueError:
                    bad += 1
                    if strict:
                        raise
    except OSError:
        return [], 0
    return records, bad


def rank_files(directory):
    return sorted(glob.glob(os.path.join(os.fspath(directory),
                                         "journal.rank*.jsonl")))


def tail(path, n=20):
    """Last `n` parsed records of a journal file (newest last)."""
    records, _ = read_journal(path)
    return records[-int(n):]


def detect_clock_skew(per_rank_records):
    """Cross-rank wall-clock skew estimate from a merged run's records:
    the same completed iteration N is a near-synchronization point
    across ranks (a data-parallel iteration cannot finish on one rank
    while peers are still many seconds inside it — the collectives
    serialize them), so the spread of `iteration`-record wall
    timestamps at the same iteration index, minimized over iterations,
    bounds the wall-clock disagreement. Straggling inflates individual
    spreads, which is why the MINIMUM over iterations is the estimate.
    Returns (skew_s, iteration) or (0.0, None) with fewer than two
    ranks' worth of matching records."""
    by_iter = {}
    for rank, records in per_rank_records.items():
        for rec in records:
            if rec.get("event") != "iteration":
                continue
            it = rec.get("iteration")
            ts = rec.get("ts")
            if isinstance(it, int) and isinstance(ts, (int, float)):
                # last record per (rank, iteration): restarts replay
                by_iter.setdefault(it, {})[rank] = float(ts)
    best = None
    for it, ranks in by_iter.items():
        if len(ranks) < 2:
            continue
        spread = max(ranks.values()) - min(ranks.values())
        if best is None or spread < best[0]:
            best = (spread, it)
    return best if best is not None else (0.0, None)


def merge_journals(directory, out_path=None, skew_threshold_s=2.0):
    """Merge every rank's journal into one timeline (rank 0 calls this
    at end of training; `tools/check_journal.py` lints the result).

    Each rank file is first ordered by its own `mono` timestamps (wall
    clocks can step mid-run; monotonic time cannot), then ranks are
    interleaved by wall time — the only cross-host ordering available.
    When the cross-rank wall-clock skew estimate (`detect_clock_skew`)
    exceeds `skew_threshold_s`, the merge does not silently interleave
    a lie: it logs a warning and appends a `note` record naming the
    measured skew so readers of the merged timeline know cross-rank
    order is unreliable at that scale. Returns the merged path or None
    when there was nothing to merge."""
    files = rank_files(directory)
    if not files:
        return None
    per_rank = {}
    for path in files:
        records, bad = read_journal(path)
        if bad:
            Log.warning("journal merge: skipped %d torn line(s) in %s",
                        bad, path)
        # within-rank order IS file order: O_APPEND writes land in real
        # time order even when the supervisor and child co-write one
        # rank file, and a stepped wall clock cannot reorder them. Do
        # NOT sort by `mono` here — CLOCK_MONOTONIC resets on reboot,
        # so a crash -> reboot -> resume run's resumed records would
        # sort before its pre-crash ones. `mono` exists for readers
        # comparing two records of one incarnation.
        per_rank[path] = records
    # k-way interleave by wall time that NEVER reorders within a rank:
    # wall clocks only decide which rank's next record comes first —
    # each rank's own append-ordered stream is consumed in order even
    # when its wall clock stepped backwards mid-run
    import heapq
    streams = [recs for recs in per_rank.values() if recs]
    heap = [(recs[0].get("ts", 0.0), i, 0)
            for i, recs in enumerate(streams)]
    heapq.heapify(heap)
    merged = []
    while heap:
        _, i, pos = heapq.heappop(heap)
        merged.append(streams[i][pos])
        if pos + 1 < len(streams[i]):
            heapq.heappush(heap, (streams[i][pos + 1].get("ts", 0.0),
                                  i, pos + 1))
    skew_s, skew_iter = detect_clock_skew(per_rank)
    if skew_s > skew_threshold_s:
        Log.warning(
            "journal merge: cross-rank wall-clock skew ~%.2fs "
            "(iteration %s timestamps disagree by that much; threshold "
            "%.1fs) — cross-rank ordering in the merged timeline is "
            "unreliable, trust within-rank order only", skew_s,
            skew_iter, skew_threshold_s)
        merged.append({"ts": time.time(),
                       "mono": round(time.monotonic(), 6),
                       "event": "note", "rank": 0,
                       "msg": (f"clock_skew: cross-rank wall-clock skew "
                               f"~{skew_s:.2f}s measured at iteration "
                               f"{skew_iter} (threshold "
                               f"{skew_threshold_s:.1f}s); merged "
                               "cross-rank order is unreliable")})
    out_path = out_path or os.path.join(os.fspath(directory), MERGED_NAME)
    tmp = f"{out_path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            for rec in merged:
                f.write(json.dumps(rec, separators=(",", ":"),
                                   default=str) + "\n")
        os.replace(tmp, out_path)
    except OSError as e:
        Log.warning("journal merge failed (%s): %s", out_path, e)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
    return out_path


# --------------------------------------------------- process-wide handle
#
# Cross-cutting emitters (the collective watchdog's abort path, the
# heartbeat monitor's peer-loss path) need the active journal without a
# booster reference — one training run per process, same singleton
# shape as parallel/heartbeat.py.

_CURRENT = None


def set_current(journal):
    global _CURRENT
    _CURRENT = journal


def current():
    return _CURRENT
