"""Unified training telemetry: span tracing, metrics, run journal,
live /trainz endpoint.

The training-side observability stack (docs/Observability.md):

- `trace.SpanTracer` — per-Booster nested span timing (replaces the
  global `utils/timers.py` singleton), with optional
  `jax.profiler.TraceAnnotation` passthrough.
- `registry.MetricsRegistry` — thread-safe counters/gauges/histograms;
  the serving layer's `/metricz` accounting (serving/metrics.py) is
  built on the same primitives.
- `journal.RunJournal` — append-only JSONL run timeline (atomic line
  writes, rank-suffixed files, rank-0 merge); schema in
  `journal.SCHEMA`, linted by `tools/check_journal.py`.
- `trainz.start_trainz` — opt-in stdlib HTTP thread serving the live
  training state (`telemetry_port` knob).
- `ledger.CompileLedger` / `ledger.sample_memory` — jit-lowering
  ledger (shape-bucket labels, persistent-cache hit/miss) and device/
  host memory watermarks.
- `roofline.TABLE` — live per-kernel achieved bytes/s vs a measured
  STREAM-style peak.
- `prometheus.render` — the registry in Prometheus text exposition
  (`?format=prometheus` on /metricz and /trainz), with the canonical
  naming contract (`canonical_name`/`lint_names`) and the labeled
  multi-source page (`render_multi`).
- `export.export_trace` — the journal (+ span-ring dump) as Chrome
  trace-event JSON for Perfetto (`tools/export_trace.py`), with
  cross-rank collective flow events.
- `comm_profile.CommProfiler` — per-collective latency attribution,
  `comm_overlap_pct` and straggler deltas (`comm_telemetry` knob).
- `aggregate.FleetAggregator` — one poller merging every rank's
  /trainz + every replica's /metricz
  (`python -m lightgbm_tpu.telemetry.aggregate`).
- `history.append_run_summary` — the append-only RUN_HISTORY.jsonl
  store `tools/sentinel.py` trends over.

Everything here is jax-free unless the jax-annotation passthrough is
explicitly enabled (the compile ledger's `install()` touches jax's
monitoring API only when jax is importable), so the supervisor and CPU
test harness can import it without touching the accelerator runtime.
"""

from . import aggregate, comm_profile, export, history  # noqa: F401
from . import journal, ledger, prometheus  # noqa: F401
from . import registry, roofline, trace, trainz  # noqa: F401
from .aggregate import FleetAggregator  # noqa: F401
from .comm_profile import CommProfiler  # noqa: F401
from .export import build_trace, export_trace, validate_trace  # noqa: F401
from .history import append_run_summary, read_history  # noqa: F401
from .journal import RunJournal, merge_journals, read_journal  # noqa: F401
from .ledger import LEDGER, CompileLedger, sample_memory  # noqa: F401
from .registry import MetricsRegistry  # noqa: F401
from .trace import SpanTracer  # noqa: F401
from .trainz import start_trainz, stop_trainz  # noqa: F401
