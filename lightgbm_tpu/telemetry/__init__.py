"""Unified training telemetry: span tracing, metrics, run journal,
live /trainz endpoint.

The training-side observability stack (docs/Observability.md):

- `trace.SpanTracer` — per-Booster nested span timing (replaces the
  global `utils/timers.py` singleton), with optional
  `jax.profiler.TraceAnnotation` passthrough.
- `registry.MetricsRegistry` — thread-safe counters/gauges/histograms;
  the serving layer's `/metricz` accounting (serving/metrics.py) is
  built on the same primitives.
- `journal.RunJournal` — append-only JSONL run timeline (atomic line
  writes, rank-suffixed files, rank-0 merge); schema in
  `journal.SCHEMA`, linted by `tools/check_journal.py`.
- `trainz.start_trainz` — opt-in stdlib HTTP thread serving the live
  training state (`telemetry_port` knob).

Everything here is jax-free unless the jax-annotation passthrough is
explicitly enabled, so the supervisor and CPU test harness can import
it without touching the accelerator runtime.
"""

from . import journal, registry, trace, trainz  # noqa: F401
from .journal import RunJournal, merge_journals, read_journal  # noqa: F401
from .registry import MetricsRegistry  # noqa: F401
from .trace import SpanTracer  # noqa: F401
from .trainz import start_trainz, stop_trainz  # noqa: F401
