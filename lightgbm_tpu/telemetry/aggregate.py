"""Fleet-wide telemetry aggregator: one scrape answers "where is the
pod slow".

Every rank of a training run serves its own /trainz and every serving
replica its own /metricz (telemetry/trainz.py, serving/server.py) —
deep per-process views that force an operator to chase N endpoints to
answer fleet questions: which rank is the straggler, is any replica's
p99 blown, did prefetch overlap collapse somewhere. This module is the
missing cross-process layer: ONE stdlib poller scrapes every target
into a single merged snapshot served as

- `/fleetz` — the full merged JSON: per-target documents plus the
  computed `fleet` view (max-over-ranks sync wait, per-rank straggler
  deltas, min comm/prefetch overlap, iteration lag, worst replica
  p99, summed request/error counts);
- `/metricz` — the same content as one Prometheus exposition page:
  each target's registry rendered with `rank`/`replica` + `role`
  labels (prometheus.render_multi keeps every family's TYPE line
  unique), fleet-level values as `fleet_*` gauges;
- `/healthz` — aggregator liveness + per-target reachability;
- `/tracez` — with `--trace-dir`, the distributed-trace collector's
  view: per-process `trace` journal records (telemetry/disttrace.py)
  stitched into cross-process trees, error traces first then slowest,
  each with a per-hop breakdown (router root -> attempt -> replica
  parse/admission/queue -> batch dispatch -> kernel).

Targets are `[role=]host:port` specs; `role` is `train`, `serve`,
`router`, or `auto` (default — probe /trainz first, fall back to
/metricz; a front-door router self-identifies via the `"router": true`
marker in its /metricz, fleet/router.py). A dead target stays in the
snapshot with `ok: false` and its last error so a vanished rank is a
visible fact, not a silent gap. Router targets contribute the
resilience rollup (`router_retry_count`, `router_breaker_open_count`,
`router_min_healthy_replicas`, ...) to the `fleet` view.

CLI (the ops entry point; `aggregate_port` in docs/Parameters.md):

    python -m lightgbm_tpu.telemetry.aggregate \
        --port 9280 --poll-s 2 127.0.0.1:9100 127.0.0.1:9101
    python -m lightgbm_tpu.telemetry.aggregate --once TARGET...

`--once` polls every target one time and prints the merged JSON to
stdout (scripting / debugging). stdlib-only and jax-free, like the
rest of the telemetry package.
"""

import argparse
import json
import os
import sys
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..utils.log import Log
from . import prometheus

ROLES = ("auto", "train", "serve", "router")

# flat serving-/metricz fields that are counters in the replica's own
# registry (serving/metrics.py) — the aggregator must render them with
# the same kind + canonical name the replica's exposition uses
# (swap_count/failed_swaps are NOT here: they are plain server fields
# the replica itself renders as gauges)
SERVING_COUNTER_FIELDS = frozenset((
    "request_count", "rows_served", "error_count", "batch_count",
    "batched_rows", "batched_requests", "shed_count",
    "deadline_expired_count"))

# the front-door router's /metricz counters (fleet/router.py); same
# render-as-counter rule as the serving fields above
ROUTER_COUNTER_FIELDS = frozenset((
    "request_count", "upstream_attempt_count", "retry_count",
    "hedge_count", "hedge_cancelled_count", "no_replica_count",
    "breaker_open_count", "breaker_close_count", "eject_count",
    "error_count", "deadline_expired_count"))


class Target:
    """One scrape target: `[role=]host:port`."""

    def __init__(self, spec):
        spec = str(spec).strip()
        role = "auto"
        if "=" in spec:
            role, spec = spec.split("=", 1)
            role = role.strip().lower()
        if role not in ROLES:
            raise ValueError(f"target role must be one of {ROLES}, "
                             f"got {role!r}")
        if ":" not in spec:
            raise ValueError(f"target must be [role=]host:port, got "
                             f"{spec!r}")
        self.role = role
        self.host_port = spec

    def url(self, path):
        return f"http://{self.host_port}{path}"


def _get_json(url, timeout_s):
    with urllib.request.urlopen(url, timeout=timeout_s) as r:
        return json.loads(r.read())


def _num(v, default=None):
    return float(v) if isinstance(v, (int, float)) \
        and not isinstance(v, bool) else default


# ---------------------------------------------------------------- tracing
def read_trace_records(directory):
    """Every `trace` record from every rank journal under `directory`
    (router, replicas and training ranks write to the SAME trace dir
    with distinct ranks, so one read sees the whole fleet's spans)."""
    from . import journal as journal_mod
    records = []
    for path in journal_mod.rank_files(directory):
        recs, _bad = journal_mod.read_journal(path)
        records.extend(r for r in recs if r.get("event") == "trace")
    return records


def _span_error(rec):
    if rec.get("status") == "error":
        return True
    code = (rec.get("tags") or {}).get("http.status")
    return isinstance(code, int) and code >= 400


def stitch_traces(records):
    """Group per-process `trace` records into cross-process trees.

    Spans keyed by trace_id form the tree; a span carrying `links`
    (the coalesced-batch spans from serving/batcher.py list every
    OTHER member request's trace_id) is grafted into each linked tree
    too, marked `shared` — so a member request's trace still shows the
    batch-dispatch/kernel hop it rode even though the span was
    journaled under the head request's trace_id. Returns trace
    documents sorted error-first then slowest-first, each with a
    per-hop breakdown ordered by wall start."""
    by_trace = {}
    for rec in records:
        tid = rec.get("trace_id")
        if tid and isinstance(rec.get("start"), (int, float)):
            by_trace.setdefault(tid, []).append(rec)
    for rec in records:
        for linked in (rec.get("links") or ()):
            if linked in by_trace and linked != rec.get("trace_id"):
                by_trace[linked].append(dict(rec, shared=True))
    traces = []
    for tid, spans in by_trace.items():
        spans.sort(key=lambda r: (r.get("start", 0.0),
                                  r.get("span_id", "")))
        t0 = min(s["start"] for s in spans)
        t1 = max(s["start"] + float(s.get("duration_s") or 0.0)
                 for s in spans)
        ids = {s.get("span_id") for s in spans}
        root = next((s for s in spans
                     if not s.get("parent_span_id")
                     or s.get("parent_span_id") not in ids), spans[0])
        traces.append({
            "trace_id": tid,
            "start": round(t0, 6),
            "duration_ms": round((t1 - t0) * 1e3, 3),
            "status": ("error" if any(_span_error(s) for s in spans)
                       else "ok"),
            "root": root.get("name"),
            "services": sorted({s.get("service") or "?"
                                for s in spans}),
            "span_count": len(spans),
            "spans": [{
                "name": s.get("name"),
                "service": s.get("service") or "?",
                "span_id": s.get("span_id"),
                "parent_span_id": s.get("parent_span_id"),
                "kind": s.get("kind", "internal"),
                "offset_ms": round((s["start"] - t0) * 1e3, 3),
                "duration_ms": round(
                    float(s.get("duration_s") or 0.0) * 1e3, 3),
                "status": s.get("status", "ok"),
                **({"shared": True} if s.get("shared") else {}),
                **({"tags": s["tags"]} if s.get("tags") else {}),
            } for s in spans],
        })
    traces.sort(key=lambda t: (t["status"] != "error",
                               -t["duration_ms"]))
    return traces


class TraceCollector:
    """The /tracez backend: re-stitches the trace dir on demand (rank
    journals are append-only JSONL; a full re-read per request is
    cheap at journal scale and needs no offset bookkeeping), keeping
    the `max_traces` most interesting trees (errors, then slowest)."""

    def __init__(self, directory, max_traces=100):
        self.directory = os.fspath(directory)
        self.max_traces = int(max_traces)

    def refresh(self):
        return stitch_traces(read_trace_records(self.directory))

    def tracez(self, n=None):
        traces = self.refresh()
        keep = self.max_traces if n is None else min(int(n),
                                                     self.max_traces)
        return {"trace_dir": self.directory,
                "trace_count": len(traces),
                "error_count": sum(1 for t in traces
                                   if t["status"] == "error"),
                "traces": traces[:keep]}


class FleetAggregator:
    """Poll + merge (see module docstring). `poll_once` is synchronous
    (tests and --once call it directly); `start` runs it on a daemon
    thread every `poll_s` seconds."""

    def __init__(self, targets, poll_s=2.0, timeout_s=5.0,
                 trace_dir=None):
        self.targets = [t if isinstance(t, Target) else Target(t)
                        for t in targets]
        if not self.targets:
            raise ValueError("aggregator needs at least one target")
        self.poll_s = float(poll_s)
        self.timeout_s = float(timeout_s)
        self.trace_collector = (TraceCollector(trace_dir)
                                if trace_dir else None)
        self._lock = threading.Lock()
        self._state = {}          # host_port -> scrape doc
        self._polls = 0
        self._stop = threading.Event()
        self._thread = None
        self._server = None

    # ------------------------------------------------------------ scraping
    def _scrape(self, target):
        doc = {"role": target.role, "ok": False, "ts": time.time()}
        try:
            if target.role in ("train", "auto"):
                try:
                    data = _get_json(target.url("/trainz"),
                                     self.timeout_s)
                    doc.update(ok=True, role="train", data=data,
                               label=self._train_label(target, data))
                    return doc
                except Exception:
                    if target.role == "train":
                        raise
            data = _get_json(target.url("/metricz"), self.timeout_s)
            # the router's /metricz self-identifies (`"router": true`,
            # fleet/router.py) so `auto` targets resolve without a
            # dedicated probe path
            role = ("router" if data.get("router") is True
                    or target.role == "router" else "serve")
            doc.update(ok=True, role=role, data=data,
                       label=str(self.targets.index(target)))
            return doc
        except Exception as e:
            doc["error"] = f"{type(e).__name__}: {e}"
            return doc

    def _train_label(self, target, data):
        """Rank label for a /trainz document: the comm profiler and
        the heartbeat view both carry the rank; fall back to the
        target's position."""
        for path in (("comm", "rank"), ("heartbeats", "rank")):
            node = data
            for key in path:
                node = node.get(key) if isinstance(node, dict) else None
            if isinstance(node, int):
                return str(node)
        return str(self.targets.index(target))

    def poll_once(self):
        """Scrape every target once; returns the merged snapshot."""
        state = {t.host_port: self._scrape(t) for t in self.targets}
        with self._lock:
            self._state = state
            self._polls += 1
        return self.snapshot()

    # ------------------------------------------------------------- merging
    def snapshot(self):
        with self._lock:
            state = dict(self._state)
            polls = self._polls
        return {"ts": time.time(), "polls": polls,
                "poll_s": self.poll_s,
                "targets": state,
                "fleet": fleet_view(state)}

    def prometheus(self):
        """Every reachable target's registry on one labeled page, plus
        the fleet view as `fleet_*` gauges."""
        with self._lock:
            state = dict(self._state)
        parts = []
        for host_port, doc in sorted(state.items()):
            if not doc.get("ok"):
                continue
            data = doc.get("data") or {}
            if doc["role"] == "train":
                labels = {"rank": doc.get("label", "?"), "role": "train"}
                snap = data.get("metrics") or {}
                extra = {}
                it = _num(data.get("iteration"))
                if it is not None:
                    extra["iteration"] = it
                comm = data.get("comm") or {}
                ov = _num(comm.get("overlap_pct"))
                if ov is not None:
                    extra["comm_overlap_pct"] = ov
                parts.append((labels, snap, extra))
            else:
                # serving and router /metricz are flat scalar
                # documents; their counter fields must render as
                # COUNTERS so the aggregator page carries the same
                # canonical names (lightgbm_tpu_request_total, ...) as
                # the process's own exposition — a dashboard built
                # against one page must match the other
                role = doc["role"]
                counter_fields = (ROUTER_COUNTER_FIELDS
                                  if role == "router"
                                  else SERVING_COUNTER_FIELDS)
                labels = {("router" if role == "router"
                           else "replica"): doc.get("label", "?"),
                          "role": role}
                counters = {k: v for k, v in data.items()
                            if k in counter_fields
                            and _num(v) is not None}
                extra = {k: v for k, v in data.items()
                         if k not in counter_fields
                         and _num(v) is not None}
                parts.append((labels, {"counters": counters}, extra))
        fleet = fleet_view(state)
        fleet_flat = {}
        for key, value in fleet.items():
            if _num(value) is not None:
                fleet_flat[f"fleet_{key}"] = value
            elif isinstance(value, dict):
                # per-rank maps flatten with the unit suffix kept LAST
                # so the canonical-name mapping still applies
                # (straggler_s -> fleet_straggler_rank_0_s -> _seconds)
                base, unit = key, ""
                for suffix in ("_s", "_pct", "_ms", "_bytes"):
                    if key.endswith(suffix):
                        base, unit = key[: -len(suffix)], suffix
                        break
                for sub, v in value.items():
                    if _num(v) is not None:
                        fleet_flat[f"fleet_{base}_rank_{sub}{unit}"] = v
        parts.append(({}, {}, fleet_flat))
        return prometheus.render_multi(parts)

    # ------------------------------------------------------------- serving
    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(target=self._run,
                                            daemon=True,
                                            name="lgbm-tpu-aggregate")
            self._thread.start()
        return self

    def _run(self):
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as e:   # the poller must never die
                Log.warning("aggregator poll failed: %s", e)
            self._stop.wait(self.poll_s)

    def serve(self, port, host="127.0.0.1"):
        """Bind the HTTP view (trainz.py's daemon-thread pattern);
        returns the server or None on bind failure."""
        agg = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                Log.debug("aggregate: " + fmt, *args)

            def _send(self, code, data, content_type):
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                fmt = ("prometheus" if "format=prometheus" in self.path
                       else "json")
                try:
                    if path.startswith("/healthz"):
                        snap = agg.snapshot()
                        ok = {hp: d.get("ok", False)
                              for hp, d in snap["targets"].items()}
                        self._send(200, json.dumps(
                            {"status": "ok", "polls": snap["polls"],
                             "targets": ok}).encode(),
                            "application/json")
                    elif path.startswith("/metricz"):
                        if fmt == "prometheus":
                            self._send(200, agg.prometheus().encode(),
                                       prometheus.CONTENT_TYPE)
                        else:
                            self._send(200, json.dumps(
                                agg.snapshot(), default=str).encode(),
                                "application/json")
                    elif path.startswith("/fleetz"):
                        self._send(200, json.dumps(
                            agg.snapshot(), default=str).encode(),
                            "application/json")
                    elif path.startswith("/tracez"):
                        if agg.trace_collector is None:
                            self._send(404, json.dumps(
                                {"error": "tracing not configured "
                                          "(start with --trace-dir)"}
                            ).encode(), "application/json")
                        else:
                            self._send(200, json.dumps(
                                agg.trace_collector.tracez(),
                                default=str).encode(),
                                "application/json")
                    else:
                        self._send(404, json.dumps(
                            {"error": f"unknown path {self.path}"}
                        ).encode(), "application/json")
                except Exception as e:   # a scrape race must not 500-loop
                    self._send(500, json.dumps(
                        {"error": str(e)}).encode(), "application/json")

        try:
            srv = ThreadingHTTPServer((host, int(port)), Handler)
        except OSError as e:
            Log.warning("aggregator bind failed (%s:%s): %s",
                        host, port, e)
            return None
        srv.daemon_threads = True
        threading.Thread(target=srv.serve_forever, daemon=True,
                         name="lgbm-tpu-aggregate-http").start()
        self._server = srv
        Log.info("fleet aggregator on http://%s:%d/fleetz (%d targets)",
                 host, srv.server_address[1], len(self.targets))
        return srv

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(2 * self.poll_s, 1.0))
            self._thread = None
        if self._server is not None:
            try:
                self._server.shutdown()
                self._server.server_close()
            except Exception:
                pass
            self._server = None


def fleet_view(state):
    """Cross-target rollup of one poll's scrape docs. Training ranks:
    max/sum sync wait with per-rank straggler deltas (cumulative wait
    minus the fleet's fastest — delta ~0 marks the straggler itself),
    min comm/prefetch overlap, iteration lag (max - min completed
    iteration: a lagging rank is mid-collective while peers wait).
    Serving replicas: worst p99 (max is the honest cross-replica p99
    merge — the true fleet p99 lies at or below it), summed
    request/error counts."""
    fleet = {"train_ranks": 0, "serve_replicas": 0, "routers": 0,
             "unreachable": 0}
    sync_waits, overlaps, prefetch, iters = {}, {}, {}, {}
    p99s, req_total, err_total = [], 0, 0
    rt_retries = rt_hedges = rt_breaker_opens = rt_shed = 0
    rt_healthy = []
    for host_port, doc in sorted(state.items()):
        if not doc.get("ok"):
            fleet["unreachable"] += 1
            continue
        data = doc.get("data") or {}
        if doc["role"] == "router":
            # the front door's own rollup: how hard is the resilience
            # layer working (retries/hedges/breaker flips) and how much
            # of the fleet it still considers routable
            fleet["routers"] += 1
            rt_retries += int(_num(data.get("retry_count"), 0) or 0)
            rt_hedges += int(_num(data.get("hedge_count"), 0) or 0)
            rt_breaker_opens += int(
                _num(data.get("breaker_open_count"), 0) or 0)
            rt_shed += int(_num(data.get("no_replica_count"), 0) or 0)
            healthy = _num(data.get("healthy_replica_count"))
            if healthy is not None:
                rt_healthy.append(int(healthy))
        elif doc["role"] == "train":
            fleet["train_ranks"] += 1
            label = doc.get("label", host_port)
            comm = data.get("comm") or {}
            wait = _num(comm.get("cum_wait_s"))
            if wait is None:
                hist = ((data.get("metrics") or {}).get("histograms")
                        or {}).get("sync_wait_s") or {}
                wait = _num(hist.get("total"))
            if wait is not None:
                sync_waits[label] = wait
            ov = _num(comm.get("overlap_pct"))
            if ov is not None:
                overlaps[label] = ov
            pf = _num(((data.get("metrics") or {}).get("gauges")
                       or {}).get("prefetch_overlap_pct"))
            if pf is not None:
                prefetch[label] = pf
            it = _num(data.get("iteration"))
            if it is not None:
                iters[label] = it
        else:
            fleet["serve_replicas"] += 1
            p99 = _num(data.get("latency_p99_ms"))
            if p99 is not None:
                p99s.append(p99)
            req_total += int(_num(data.get("request_count"), 0) or 0)
            err_total += int(_num(data.get("error_count"), 0) or 0)
    if sync_waits:
        fleet["max_sync_wait_s"] = round(max(sync_waits.values()), 6)
        fastest = min(sync_waits.values())
        fleet["straggler_s"] = {r: round(w - fastest, 6)
                                for r, w in sorted(sync_waits.items())}
    if overlaps:
        fleet["min_comm_overlap_pct"] = round(min(overlaps.values()), 2)
    if prefetch:
        fleet["min_prefetch_overlap_pct"] = round(
            min(prefetch.values()), 2)
    if len(iters) >= 2:
        fleet["iteration_lag"] = int(max(iters.values())
                                     - min(iters.values()))
    if p99s:
        fleet["worst_latency_p99_ms"] = round(max(p99s), 4)
    if fleet["serve_replicas"]:
        fleet["request_count"] = req_total
        fleet["error_count"] = err_total
    if fleet["routers"]:
        fleet["router_retry_count"] = rt_retries
        fleet["router_hedge_count"] = rt_hedges
        fleet["router_breaker_open_count"] = rt_breaker_opens
        fleet["router_no_replica_count"] = rt_shed
        if rt_healthy:
            fleet["router_min_healthy_replicas"] = min(rt_healthy)
    return fleet


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.telemetry.aggregate",
        description="Fleet telemetry aggregator: scrape every rank's "
                    "/trainz and every replica's /metricz into one "
                    "merged snapshot (JSON + labeled Prometheus).")
    ap.add_argument("targets", nargs="+",
                    help="scrape targets, [role=]host:port "
                         "(role: train|serve|auto)")
    ap.add_argument("--port", type=int, default=None,
                    help="bind port for /fleetz + /metricz (default: "
                         "the `aggregate_port` config knob, 0 = "
                         "ephemeral)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--poll-s", type=float, default=2.0)
    ap.add_argument("--timeout-s", type=float, default=5.0)
    ap.add_argument("--once", action="store_true",
                    help="poll once, print the merged JSON, exit")
    ap.add_argument("--trace-dir", default="",
                    help="telemetry dir the fleet's trace journals "
                         "land in; enables /tracez (stitched "
                         "cross-process request traces)")
    args = ap.parse_args(argv)
    if args.port is None:
        # the `aggregate_port` knob is the documented default for this
        # CLI (config.py); imported lazily — Config is jax-free but
        # pulls numpy, which --help shouldn't need
        from ..config import Config
        args.port = int(Config().aggregate_port)
    try:
        agg = FleetAggregator(args.targets, poll_s=args.poll_s,
                              timeout_s=args.timeout_s,
                              trace_dir=args.trace_dir or None)
    except ValueError as e:
        print(f"aggregate: {e}", file=sys.stderr)
        return 2
    if args.once:
        print(json.dumps(agg.poll_once(), indent=2, default=str))
        return 0
    srv = agg.serve(args.port, host=args.host)
    if srv is None:
        return 1
    # the parseable readiness line tests and wrappers key off
    print(f"AGGREGATE listening on http://{args.host}:"
          f"{srv.server_address[1]}/fleetz", flush=True)
    agg.poll_once()
    agg.start()
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        agg.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
