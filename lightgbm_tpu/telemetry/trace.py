"""Span tracer: nested, tagged wall-clock spans for the training loop.

Replaces the `utils/timers.py` global `PhaseTimers` singleton (whose
accumulator two Boosters trained in one process silently shared) with a
per-Booster instance. The reference's observability surface is the
cumulative network-time counters in include/LightGBM/network.h /
src/network/linkers.h:195-212 plus ad-hoc timers in application.cpp;
GPU tree-boosting systems report per-kernel phase breakdowns as the
primary tuning instrument (arXiv:1706.08359, arXiv:2005.09148) — the
tracer is that instrument for the host-visible side of training.

Three views of the same spans:

- **Accumulator** (`acc`/`cnt`/`snapshot`/`report`): per-phase total
  seconds and call counts, drop-in compatible with the old PhaseTimers
  API so existing call sites and the bench keep working.
- **Deltas** (`delta_snapshot`): per-phase seconds since the previous
  call — what the run journal attaches to each iteration record.
- **Recent spans** (`recent`): a bounded ring of completed spans with
  nesting path, start offset and tags — the `/trainz` endpoint's live
  breakdown.

Spans nest via a thread-local stack ("train/build" style paths), are
exception-safe (the `finally` always closes the span), and optionally
pass through to `jax.profiler.TraceAnnotation` so host spans line up
with XLA device traces (`telemetry_jax_annotations` knob; the import
is lazy so this module stays jax-free unless the passthrough is on).
"""

import threading
import time
from collections import defaultdict, deque

from . import disttrace

RECENT_SPANS = 256


class Span:
    """One completed (or open) span. `path` includes parents:
    "train/build". `tid` is the recording thread's ident, so concurrent
    threads (batcher worker, heartbeat monitor, the training loop) land
    on separate tracks in an exported trace (telemetry/export.py)."""

    __slots__ = ("name", "path", "start", "duration", "tags", "tid")

    def __init__(self, name, path, start, duration=None, tags=None,
                 tid=0):
        self.name = name
        self.path = path
        self.start = start
        self.duration = duration
        self.tags = tags or {}
        self.tid = tid

    def as_dict(self):
        return {"name": self.name, "path": self.path,
                "start_s": round(self.start, 6),
                "duration_s": (round(self.duration, 6)
                               if self.duration is not None else None),
                "tid": self.tid,
                **({"tags": self.tags} if self.tags else {})}


class _SpanContext:
    """Context manager for one span; created by SpanTracer.span()."""

    __slots__ = ("_tracer", "_name", "_tags", "_t0", "_path", "_ann")

    def __init__(self, tracer, name, tags):
        self._tracer = tracer
        self._name = name
        self._tags = tags
        self._t0 = None
        self._path = None
        self._ann = None

    def __enter__(self):
        tr = self._tracer
        stack = tr._stack()
        self._path = ("/".join(s for s in stack) + "/" + self._name
                      if stack else self._name)
        stack.append(self._name)
        if tr.jax_annotations:
            self._ann = tr._annotation(self._name)
            if self._ann is not None:
                self._ann.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        elapsed = time.perf_counter() - self._t0
        tr = self._tracer
        if self._ann is not None:
            self._ann.__exit__(exc_type, exc, tb)
        stack = tr._stack()
        if stack and stack[-1] == self._name:
            stack.pop()
        tr._record(self._name, self._path, elapsed, self._t0, self._tags)
        return False


class SpanTracer:
    """Per-Booster span registry (see module docstring).

    The accumulator keys on the LEAF name (not the path) so nested and
    flat call sites aggregate the same way the old PhaseTimers did.
    Thread-safe: concurrent threads keep independent nesting stacks and
    the shared accumulator mutates under one lock.
    """

    def __init__(self, rank=0, jax_annotations=False):
        self.rank = int(rank)
        self.jax_annotations = bool(jax_annotations)
        self.acc = defaultdict(float)
        self.cnt = defaultdict(int)
        self._lock = threading.Lock()
        self._last = {}            # delta_snapshot baseline
        self._recent = deque(maxlen=RECENT_SPANS)
        self._local = threading.local()
        self._epoch = time.perf_counter()
        # wall-clock time of the perf_counter epoch: span start offsets
        # + epoch_wall = journal-comparable epoch seconds, the mapping
        # the trace exporter uses to line spans up with journal records
        self.epoch_wall = time.time()

    # ------------------------------------------------------------- spans
    def _stack(self):
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    @staticmethod
    def _annotation(name):
        try:
            import jax
            return jax.profiler.TraceAnnotation(name)
        except Exception:   # jax absent / profiler API drift: span still times
            return None

    def span(self, name, **tags):
        """Context manager timing one (possibly nested) span."""
        return _SpanContext(self, name, tags)

    # PhaseTimers-compatible alias: `with tracer.phase("build"): ...`
    phase = span

    def _record(self, name, path, elapsed, t0, tags):
        with self._lock:
            self.acc[name] += elapsed
            self.cnt[name] += 1
            self._recent.append(Span(name, path, t0 - self._epoch,
                                     elapsed, tags,
                                     tid=threading.get_ident()))
        # distributed-trace mirror: when this thread runs under an
        # active X-Trace-Ctx (a traced canary retrain, a request that
        # reached training code), the span ALSO lands on that trace so
        # /tracez shows training phases inside the cross-process tree.
        # One thread-local read when no context is active
        ctx = disttrace.current()
        if ctx is not None:
            rec = disttrace.get_recorder()
            if rec.enabled:
                rec.observe("train." + name, ctx,
                            time.time() - elapsed, elapsed,
                            tags=dict(tags) if tags else None)

    def add(self, name, seconds):
        """Accumulate an externally-timed phase (e.g. the bench's
        compile window). Also lands a synthetic span in the recent ring
        — ending NOW, `seconds` long — so externally-timed phases show
        up on /trainz and in exported traces instead of vanishing from
        every per-span view."""
        seconds = float(seconds)
        with self._lock:
            self.acc[name] += seconds
            self.cnt[name] += 1
            start = time.perf_counter() - seconds - self._epoch
            self._recent.append(Span(name, name, start, seconds,
                                     {"synthetic": True},
                                     tid=threading.get_ident()))

    # ----------------------------------------------------------- readers
    def reset(self):
        with self._lock:
            self.acc.clear()
            self.cnt.clear()
            self._last.clear()
            self._recent.clear()
            self._epoch = time.perf_counter()
            self.epoch_wall = time.time()

    def snapshot(self):
        """{phase: total_seconds}, machine-readable (bench JSON)."""
        with self._lock:
            return {k: round(v, 6) for k, v in self.acc.items()}

    def delta_snapshot(self):
        """{phase: seconds since the previous delta_snapshot call} —
        only phases that moved. The run journal attaches this to each
        iteration record so per-record phase seconds sum back to the
        run totals."""
        out = {}
        with self._lock:
            for name, total in self.acc.items():
                d = total - self._last.get(name, 0.0)
                if d > 0:
                    out[name] = round(d, 6)
                self._last[name] = total
        return out

    def recent(self, n=32):
        """Last `n` completed spans, oldest first (`/trainz`); `n=None`
        dumps the whole ring (the journal `spans` record at close)."""
        with self._lock:
            spans = list(self._recent)
        if n is not None:
            spans = spans[-int(n):]
        return [s.as_dict() for s in spans]

    def report(self):
        """One line per phase, largest first (the old PhaseTimers
        debug report)."""
        with self._lock:
            items = sorted(self.acc.items(), key=lambda kv: -kv[1])
            lines = ["%-12s %8.3fs total, %7.2fms/call x%d"
                     % (name, total, 1e3 * total / max(self.cnt[name], 1),
                        self.cnt[name])
                     for name, total in items]
        return "\n".join(lines)
