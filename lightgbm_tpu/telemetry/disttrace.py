"""Distributed request tracing + the crash flight recorder.

No reference equivalent — the reference is a library; a FLEET (router
-> replicas -> batcher -> kernel, plus gang training behind it) needs
one request followable across process boundaries. This is a W3C
trace-context-style propagation layer built on the same no-new-deps
rule as the rest of the serving stack (stdlib only, jax-free):

- **Context**: every hop carries ``X-Trace-Ctx: trace_id/span_id/flags``
  (hex ids, int flags). `parse_header` accepts it, `inject_headers`
  stamps it onto outbound calls (the `trace-context-propagation` lint
  rule checks that every header-setting HTTP call in fleet|serving
  goes through it), and a thread-local stack keeps the active context
  so nested spans parent correctly without plumbing arguments.

- **Spans**: `TraceRecorder.span(...)` times one hop (router root,
  per-attempt child, parse/admission/queue/batch/kernel stages);
  `observe(...)` lands externally-timed spans (the batcher worker's
  stamps). Completed spans buffer per trace until the process-local
  root closes, then the whole fragment is journaled as `trace`
  records (telemetry/journal.py SCHEMA) — or dropped.

- **Tail-based sampling**: the keep decision runs at fragment close,
  when the outcome is known. 100% of error traces (any span status
  "error", any http.status >= 400 — shed 429s and deadline 504s
  included) and of slow traces (fragment wall span over `slow_ms`,
  the serving `slow_request_ms` bar) are kept; the rest keep a
  deterministic hash(trace_id) fraction (`sample_rate`), identical on
  every process so a kept trace is kept at EVERY hop and the
  collector (telemetry/aggregate.py TraceCollector) can stitch
  complete trees. The head also sets FLAG_SAMPLED in the propagated
  flags so downstream processes need not recompute.

- **Flight recorder**: `FLIGHT` dumps the registered evidence sources
  (span rings, registry snapshots, journal tails) atomically to
  `<dir>/blackbox-<rank>.json` on watchdog abort (exit 117/118,
  parallel/heartbeat.py — BEFORE the os._exit), on SIGQUIT, and on
  unhandled serving exceptions — every post-mortem starts with the
  final seconds instead of nothing (docs/Observability.md).
"""

import json
import os
import random
import signal
import threading
import time
import zlib
from collections import deque

from ..utils.log import Log

TRACE_HEADER = "X-Trace-Ctx"
# env fallback: a child process (canary retrain, spawned rank) joins
# its parent's trace without an HTTP hop to carry the header
ENV_CONTEXT = "LGBM_TPU_TRACE_CTX"

# flags bits (propagated verbatim)
FLAG_SAMPLED = 1   # head's hash decision said keep; downstream honors it

DEFAULT_SAMPLE_RATE = 0.01
# tail-sampling buffers at most this many distinct in-flight traces
# per recorder; beyond it the oldest fragment is dropped (bounded
# memory beats complete evidence under a trace-id flood)
MAX_PENDING_TRACES = 512
# backstop on the recorder's event queue: if the drain thread ever
# wedges, producers drop new spans rather than grow without bound
MAX_QUEUED_EVENTS = 65536
# how long a completed span may sit in the queue before the drain
# thread folds it into its fragment (teardown/stats drain on demand)
DRAIN_INTERVAL_S = 0.02

_HEX = set("0123456789abcdef")

# span/trace ids come off a thread-local PRNG, not uuid4: ids are
# correlation keys, not secrets, and getrandbits is ~10x cheaper than
# the uuid machinery on the per-request path
_RNG = threading.local()


def _rand_hex16():
    r = getattr(_RNG, "r", None)
    if r is None:
        r = _RNG.r = random.Random(
            int.from_bytes(os.urandom(8), "big") ^ threading.get_ident())
    return f"{r.getrandbits(64):016x}"


def new_trace_id():
    return _rand_hex16()


def new_span_id():
    return _rand_hex16()


def hash_fraction(trace_id):
    """Deterministic [0, 1) hash of a trace id — the SAME value on
    every process, so independent tail samplers agree on keep/drop."""
    return (zlib.crc32(trace_id.encode("ascii", "replace"))
            & 0xFFFFFFFF) / 2.0 ** 32


class TraceContext:
    """One hop's identity: which trace, which span is the parent of
    anything started under this context, and the propagated flags."""

    __slots__ = ("trace_id", "span_id", "flags")

    def __init__(self, trace_id, span_id, flags=0):
        self.trace_id = trace_id
        self.span_id = span_id
        self.flags = int(flags)

    def header_value(self):
        return f"{self.trace_id}/{self.span_id}/{self.flags:d}"

    def __repr__(self):
        return f"TraceContext({self.header_value()})"


def _hex_ok(s, lo=8, hi=32):
    return lo <= len(s) <= hi and all(c in _HEX for c in s)


def parse_header(value):
    """``trace_id/span_id/flags`` -> TraceContext, or None for
    anything malformed (a garbled header must degrade to a fresh
    trace, never to a 4xx)."""
    if not value or not isinstance(value, str):
        return None
    parts = value.strip().split("/")
    if len(parts) != 3:
        return None
    trace_id, span_id, flags = (p.strip().lower() for p in parts)
    if not _hex_ok(trace_id) or not _hex_ok(span_id):
        return None
    try:
        flags_i = int(flags)
    except ValueError:
        return None
    return TraceContext(trace_id, span_id, flags_i)


# ------------------------------------------------------ thread context

_LOCAL = threading.local()


def current():
    """The active TraceContext on THIS thread, or None."""
    return getattr(_LOCAL, "ctx", None)


class _Activation:
    __slots__ = ("_ctx", "_prev")

    def __init__(self, ctx):
        self._ctx = ctx
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_LOCAL, "ctx", None)
        _LOCAL.ctx = self._ctx
        return self._ctx

    def __exit__(self, exc_type, exc, tb):
        _LOCAL.ctx = self._prev
        return False


def activate(ctx):
    """Context manager installing `ctx` as this thread's current
    context (None deactivates for the scope)."""
    return _Activation(ctx)


def from_env(environ=None):
    """TraceContext from the LGBM_TPU_TRACE_CTX env var, or None —
    how a spawned training child joins the spawning request's trace."""
    return parse_header((environ or os.environ).get(ENV_CONTEXT, ""))


def inject_headers(headers=None, ctx=None):
    """Return `headers` (a new dict) carrying the trace context header
    — THE helper every outbound HTTP call in fleet|serving must route
    header dicts through (lint rule `trace-context-propagation`). With
    no explicit ctx and no current() context the headers pass through
    unstamped: probes and untraced traffic stay headerless."""
    out = dict(headers or {})
    ctx = ctx or current()
    if ctx is not None:
        out[TRACE_HEADER] = ctx.header_value()
    return out


# -------------------------------------------------------------- spans

class DistSpan:
    """One completed (or open) cross-process span. `start` is wall
    epoch seconds (time.time(): journal-comparable across processes;
    per-rank NTP skew is visible, not corrected)."""

    __slots__ = ("trace_id", "span_id", "parent_span_id", "name",
                 "kind", "start", "duration", "status", "flags",
                 "tags", "links")

    def __init__(self, trace_id, span_id, parent_span_id, name,
                 kind="internal", start=None, flags=0, tags=None,
                 links=None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_span_id = parent_span_id
        self.name = name
        self.kind = kind
        self.start = time.time() if start is None else float(start)
        self.duration = None
        self.status = "ok"
        self.flags = int(flags)
        self.tags = dict(tags) if tags else {}
        self.links = list(links) if links else None

    def context(self):
        """The context a child hop (or downstream process) continues."""
        return TraceContext(self.trace_id, self.span_id, self.flags)

    def set_tag(self, key, value):
        self.tags[key] = value

    def as_record(self):
        rec = {"trace_id": self.trace_id, "span_id": self.span_id,
               "name": self.name, "start": round(self.start, 6),
               "duration_s": round(self.duration or 0.0, 6),
               "kind": self.kind, "status": self.status,
               "flags": self.flags}
        if self.parent_span_id:
            rec["parent_span_id"] = self.parent_span_id
        if self.tags:
            rec["tags"] = self.tags
        if self.links:
            rec["links"] = self.links
        return rec


class _SpanHandle:
    """Context manager for one recorder-owned span: activates the
    span's context for the scope (children/downstream parent to it),
    closes the span exception-safely."""

    __slots__ = ("recorder", "span", "_activation", "_t0")

    def __init__(self, recorder, span):
        self.recorder = recorder
        self.span = span
        self._activation = None
        self._t0 = None

    @property
    def ctx(self):
        return self.span.context()

    def set_tag(self, key, value):
        self.span.set_tag(key, value)

    def __enter__(self):
        self._t0 = time.monotonic()
        self._activation = _Activation(self.span.context())
        self._activation.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb):
        self._activation.__exit__(exc_type, exc, tb)
        status = None
        if exc_type is not None and self.span.status == "ok":
            status = "error"
            self.span.set_tag("exception", repr(exc)[:200])
        self.recorder.finish(self.span, status=status,
                             elapsed=time.monotonic() - self._t0)
        return False


class _NoopHandle:
    """Shared do-nothing span handle: the disabled-recorder fast path
    costs one attribute read and no allocation per request."""

    __slots__ = ()
    ctx = None
    span = None

    def set_tag(self, key, value):
        pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NOOP = _NoopHandle()


class TraceRecorder:
    """Per-process (or per-server) span sink with tail-based sampling.

    Completed spans buffer per trace until no span of that trace is
    still open HERE; then the whole local fragment is either appended
    to the journal as `trace` records or dropped (policy in the
    module docstring).

    The REQUEST PATH only allocates the span and appends one event to
    a deque (GIL-atomic, no lock): fragment bookkeeping, the tail
    decision and the journal writes all run on a background drain
    thread, so the serving p99 never pays for a kept trace's I/O (the
    <1% overhead bar, tools/verify_perf.py --trace). `flush_pending`
    / `stats` / `close` drain synchronously first, so teardown-then-
    read sees every span. `enabled=False` turns every call into a
    near-free no-op."""

    def __init__(self, directory=None, rank=0, journal=None, service="",
                 sample_rate=DEFAULT_SAMPLE_RATE, slow_ms=0.0,
                 slow_only=False, enabled=True):
        self.service = service or ""
        self.sample_rate = float(sample_rate)
        self.slow_ms = float(slow_ms or 0.0)
        self.slow_only = bool(slow_only)
        self.rank = int(rank)
        self._own_journal = False
        self.journal = journal
        if journal is None and directory:
            from . import journal as journal_mod
            self.journal = journal_mod.RunJournal(
                directory, rank=self.rank,
                source=self.service or "trace")
            self._own_journal = True
        self.enabled = bool(enabled) and self.journal is not None
        # producers append ("+", trace_id) / ("-", span) / ("o", span)
        # events; ONLY the drain passes (serialized by _lock) touch
        # _pending and the counters
        self._events = deque()
        self._lock = threading.Lock()
        self._pending = {}   # trace_id -> {"open": int, "spans": [...]}
        self._stop = threading.Event()
        self._thread = None
        self.spans_recorded = 0
        self.traces_kept = 0
        self.traces_dropped = 0
        if self.enabled:
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name=f"lgbm-tpu-trace-drain-{self.rank}")
            self._thread.start()

    # ------------------------------------------------------------ create
    def _head_flags(self, trace_id):
        return FLAG_SAMPLED \
            if hash_fraction(trace_id) < self.sample_rate else 0

    def _enqueue(self, op, payload):
        if len(self._events) < MAX_QUEUED_EVENTS:
            self._events.append((op, payload))
        else:   # wedged drain: drop rather than grow without bound
            self.traces_dropped += 1   # racy counter; evidence only

    def start(self, name, ctx=None, kind="internal", links=None,
              tags=None):
        """Open a span. `ctx` (or the thread's current context) makes
        it a child; without either it roots a NEW trace, deciding the
        head sampling flag. Returns the open DistSpan (pair with
        `finish`) — use `span()` for the with-statement form."""
        ctx = ctx or current()
        if ctx is None:
            trace_id = new_trace_id()
            parent = None
            flags = self._head_flags(trace_id)
        else:
            trace_id, parent, flags = ctx.trace_id, ctx.span_id, ctx.flags
        span = DistSpan(trace_id, new_span_id(), parent, name,
                        kind=kind, flags=flags, tags=tags, links=links)
        if self.enabled:
            self._enqueue("+", trace_id)
        return span

    def finish(self, span, status=None, elapsed=None, **tags):
        """Close a span opened with `start`. `elapsed` (monotonic
        seconds) beats wall-clock subtraction when the caller timed
        the hop itself; without it the wall delta is used."""
        if status is not None:
            span.status = status
        if tags:
            span.tags.update(tags)
        if span.duration is None:
            span.duration = (float(elapsed) if elapsed is not None
                             else max(0.0, time.time() - span.start))
        if self.enabled:
            self._enqueue("-", span)

    def span(self, name, ctx=None, kind="internal", **tags):
        """`with recorder.span("router.request") as sp:` — times the
        body, activates the span's context for it, journals through
        the tail sampler. The disabled path returns a shared no-op."""
        if not self.enabled:
            return _NOOP
        return _SpanHandle(self, self.start(name, ctx=ctx, kind=kind,
                                            tags=tags or None))

    def observe(self, name, ctx, start, duration_s, kind="internal",
                status="ok", tags=None, links=None, parent=None):
        """Land an externally-timed span (batcher worker stamps,
        mirrored SpanTracer phases). `start` is wall epoch seconds.
        Joins the trace's pending fragment when one is open here,
        otherwise flushes as its own single-span fragment."""
        if not self.enabled or ctx is None:
            return None
        span = DistSpan(ctx.trace_id, new_span_id(),
                        parent if parent is not None else ctx.span_id,
                        name, kind=kind, start=start, flags=ctx.flags,
                        tags=tags, links=links)
        span.duration = float(duration_s)
        span.status = status
        self._enqueue("o", span)
        return span

    # ------------------------------------------------------------- drain
    def _run(self):
        while not self._stop.wait(DRAIN_INTERVAL_S):
            try:
                self.drain()
            except Exception as e:   # the drain must never die
                Log.warning("trace drain failed: %s", e)

    def drain(self, burst=16):
        """Fold every queued event into its fragment, flushing closed
        fragments through the tail sampler. Runs on the background
        thread every DRAIN_INTERVAL_S; `flush_pending`/`stats`/`close`
        call it inline (the lock serializes passes, the deque keeps
        producers wait-free). Events are processed in bursts of
        `burst` with a GIL yield between bursts, so a request thread
        colliding with a big backlog never waits out the whole pass."""
        while True:
            with self._lock:
                flushes = []
                n = 0
                while n < burst:
                    try:
                        op, payload = self._events.popleft()
                    except IndexError:
                        break
                    n += 1
                    if op == "+":
                        frag = self._pending.get(payload)
                        if frag is None:
                            self._evict_locked()
                            frag = self._pending[payload] = \
                                {"open": 0, "spans": []}
                        frag["open"] += 1
                    elif op == "-":
                        frag = self._pending.get(payload.trace_id)
                        if frag is None:
                            frag = {"open": 1, "spans": []}
                            self._pending[payload.trace_id] = frag
                        frag["spans"].append(payload)
                        frag["open"] -= 1
                        if frag["open"] <= 0:
                            self._pending.pop(payload.trace_id, None)
                            flushes.append((payload.trace_id,
                                            frag["spans"]))
                    else:   # "o": externally-timed span
                        frag = self._pending.get(payload.trace_id)
                        if frag is not None:
                            frag["spans"].append(payload)
                        else:
                            flushes.append((payload.trace_id,
                                            [payload]))
                for trace_id, spans in flushes:
                    self._flush_locked(trace_id, spans)
            if n < burst:
                return
            time.sleep(0)   # yield the GIL between bursts

    # ----------------------------------------------------------- sampling
    def _evict_locked(self):
        while len(self._pending) >= MAX_PENDING_TRACES:
            oldest = next(iter(self._pending))
            self._pending.pop(oldest)
            self.traces_dropped += 1

    def _keep(self, trace_id, spans):
        """The tail decision (module docstring): errors and slowness
        always keep; otherwise the deterministic head fraction."""
        slow_s = self.slow_ms / 1e3 if self.slow_ms > 0 else None
        t_lo = t_hi = None
        for s in spans:
            if s.status == "error":
                return True
            code = s.tags.get("http.status")
            if isinstance(code, int) and code >= 400:
                return True
            end = s.start + (s.duration or 0.0)
            t_lo = s.start if t_lo is None else min(t_lo, s.start)
            t_hi = end if t_hi is None else max(t_hi, end)
        if slow_s is not None and t_lo is not None \
                and (t_hi - t_lo) >= slow_s:
            return True
        if self.slow_only:
            return False
        if any(s.flags & FLAG_SAMPLED for s in spans):
            return True
        return hash_fraction(trace_id) < self.sample_rate

    def _flush_locked(self, trace_id, spans):
        if not spans:
            return
        if not self._keep(trace_id, spans):
            self.traces_dropped += 1
            return
        self.traces_kept += 1
        self.spans_recorded += len(spans)
        j = self.journal
        if j is None:
            return
        for s in spans:
            rec = s.as_record()
            if self.service and "service" not in rec:
                rec["service"] = self.service
            j.event("trace", **rec)

    def flush_pending(self):
        """Drain the queue, then force the tail decision on every
        still-buffered fragment (server teardown; tests). Open counts
        are ignored — anything still nominally open is journaled with
        its current duration."""
        self.drain()
        with self._lock:
            pending, self._pending = self._pending, {}
            for trace_id, frag in pending.items():
                spans = [s for s in frag["spans"]
                         if s.duration is not None]
                self._flush_locked(trace_id, spans)

    def stats(self):
        self.drain()
        with self._lock:
            return {"trace_spans_recorded": self.spans_recorded,
                    "traces_kept": self.traces_kept,
                    "traces_dropped": self.traces_dropped,
                    "trace_sample_rate": self.sample_rate}

    def close(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * DRAIN_INTERVAL_S + 1.0)
            self._thread = None
        self.flush_pending()
        if self._own_journal and self.journal is not None:
            self.journal.close()
        self.enabled = False


# a permanently-disabled recorder: call sites can hold it instead of
# None and skip every `if recorder is not None` branch
NOOP_RECORDER = TraceRecorder(enabled=False)

_DEFAULT = NOOP_RECORDER
_DEFAULT_LOCK = threading.Lock()


def get_recorder():
    """The process-default recorder (training-side spans mirror into
    it; servers usually hold their own instance)."""
    return _DEFAULT


def set_recorder(recorder):
    global _DEFAULT
    with _DEFAULT_LOCK:
        prev, _DEFAULT = _DEFAULT, (recorder or NOOP_RECORDER)
    return prev


def configure(**kwargs):
    """Build + install the process-default TraceRecorder (the training
    CLI path; models/gbdt.py wires it from the trace_* knobs)."""
    rec = TraceRecorder(**kwargs)
    set_recorder(rec)
    return rec


# ------------------------------------------------------ flight recorder

BLACKBOX_PREFIX = "blackbox"


def blackbox_path(directory, rank):
    return os.path.join(os.fspath(directory),
                        f"{BLACKBOX_PREFIX}-{int(rank)}.json")


class FlightRecorder:
    """Last-seconds evidence dump for post-mortems (`blackbox` knob).

    Sources register lazily (`add_source`) — each is a zero-argument
    callable returning JSON-serializable evidence (span ring, registry
    snapshot, journal tail). `dump(reason)` collects every source
    (per-source failures are recorded, never raised), then writes
    `blackbox-<rank>.json` atomically (tmp + os.replace). It is called
    from abort paths microseconds before os._exit, so it must never
    raise and never block on a lock the dying thread might hold."""

    def __init__(self):
        self.directory = None
        self.rank = 0
        self._sources = {}
        self._lock = threading.Lock()
        self.last_path = None

    @property
    def enabled(self):
        return self.directory is not None

    def configure(self, directory, rank=0):
        """Arm the recorder (idempotent). Returns self."""
        self.directory = os.fspath(directory) if directory else None
        self.rank = int(rank)
        if self.directory:
            try:
                os.makedirs(self.directory, exist_ok=True)
            except OSError as e:
                Log.warning("flight recorder disabled (%s): %s",
                            self.directory, e)
                self.directory = None
        return self

    def disarm(self):
        self.directory = None
        with self._lock:
            self._sources.clear()

    def add_source(self, name, fn):
        with self._lock:
            self._sources[str(name)] = fn

    def dump(self, reason, **extra):
        """Write the blackbox; returns its path or None. Never raises."""
        try:
            if not self.enabled:
                return None
            with self._lock:
                sources = dict(self._sources)
            payload = {"ts": time.time(), "reason": str(reason),
                       "rank": self.rank, "pid": os.getpid()}
            payload.update(extra)
            evidence = {}
            for name, fn in sources.items():
                try:
                    evidence[name] = fn()
                except Exception as e:   # one bad source must not void
                    evidence[name] = {"error": repr(e)[:200]}  # the rest
            payload["sources"] = evidence
            path = blackbox_path(self.directory, self.rank)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f, separators=(",", ":"), default=str)
            os.replace(tmp, path)
            self.last_path = path
            Log.warning("flight recorder: %s -> %s", reason, path)
            return path
        except Exception as e:
            # the dump is best-effort evidence; the abort it rides on
            # must proceed regardless
            try:
                Log.warning("flight recorder dump failed: %s", e)
            except Exception:
                pass
            return None

    def install_sigquit(self):
        """SIGQUIT -> dump (live process inspection: `kill -QUIT <pid>`
        leaves a blackbox without killing the process). Main-thread
        only; elsewhere it is a recorded no-op."""
        try:
            signal.signal(signal.SIGQUIT,
                          lambda signum, frame: self.dump("sigquit"))
            return True
        except (ValueError, OSError, AttributeError):
            # not the main thread / platform without SIGQUIT
            return False


FLIGHT = FlightRecorder()
