"""Public Dataset / Booster API.

Reference: python-package/lightgbm/basic.py. The reference wraps the C
API through ctypes (`_InnerDataset`/`Booster` over `LGBM_*` handles,
basic.py:29-52); here the same public surface delegates directly to the
JAX core (io.dataset.CoreDataset, models.gbdt.GBDT) — no FFI boundary,
the "handle" is the Python object itself.

Mirrored semantics:
- lazy `Dataset` that constructs on first use, aligns bin mappers via
  `reference=`, supports `subset()` and `free_raw_data` (basic.py:413-1183);
- `_InnerPredictor` chaining for continued training: a predictor attached
  to a Dataset seeds init scores, and the new Booster merges the
  predictor's trees (basic.py:182-390, 1227-1231);
- `Booster.update()` with optional custom objective `fobj(preds, dataset)`
  (basic.py:1304-1372), eval/eval_train/eval_valid with `feval`,
  save/dump, split-count feature importance, attr dict (basic.py:1184-1677).
"""

import numpy as np

from .config import Config, key_alias_transform
from .io.dataset import DatasetLoader
from .io.parser import parse_text_file
from .metrics import create_metric
from .models.gbdt import create_boosting
from .objectives import create_objective
from .utils.log import LightGBMError, Log


def is_str(s):
    return isinstance(s, str)


def _coerce_2d(data):
    """numpy 2-D / pandas / scipy-sparse / list-of-rows -> float32 ndarray."""
    if hasattr(data, "toarray"):          # scipy sparse
        data = data.toarray()
    if hasattr(data, "values") and not isinstance(data, np.ndarray):  # pandas
        data = data.values
    arr = np.asarray(data, dtype=np.float32)
    if arr.ndim == 1:
        arr = arr.reshape(1, -1)
    return np.ascontiguousarray(arr)


def _coerce_label(label):
    if label is None:
        return None
    if hasattr(label, "values") and not isinstance(label, np.ndarray):
        label = label.values
    return np.asarray(label, dtype=np.float32).reshape(-1)


class _InnerPredictor:
    """Raw-score predictor used for prediction and init-score chaining
    (basic.py:182-390)."""

    def __init__(self, model_file=None, booster=None):
        if model_file is not None:
            self.gbdt = create_boosting("gbdt", model_file)
            with open(model_file) as f:
                self.gbdt.load_model_from_string(f.read())
        elif booster is not None:
            self.gbdt = booster
        else:
            raise TypeError("Need Model file or Booster to create a predictor")
        self.num_class = self.gbdt.num_class

    @property
    def num_total_iteration(self):
        return len(self.gbdt.models) // max(self.gbdt.num_class, 1)

    def predict(self, data, num_iteration=-1, raw_score=False,
                pred_leaf=False, data_has_header=False, is_reshape=True):
        if is_str(data):
            _, feats, _, _, _ = parse_text_file(
                data, has_header=data_has_header, label_column="")
            data = feats
        data = _coerce_2d(data)
        if pred_leaf:
            return self.gbdt.predict_leaf_index(data, num_iteration)
        if raw_score:
            out = self.gbdt.predict_raw(data, num_iteration)
        else:
            out = self.gbdt.predict(data, num_iteration)
        if is_reshape and self.num_class == 1:
            return out.reshape(-1)
        return out if is_reshape else out.reshape(-1, order="F")


class Dataset:
    """Lazy dataset (basic.py:893-1183): stores raw inputs, constructs the
    binned CoreDataset on first use (so `reference=` alignment and the
    predictor for continued training can be attached before binning)."""

    def __init__(self, data, label=None, max_bin=255, reference=None,
                 weight=None, group=None, silent=False, feature_name=None,
                 categorical_feature=None, params=None, free_raw_data=True):
        self.data = data
        self.label = _coerce_label(label)
        self.max_bin = max_bin
        self.reference = reference
        self.weight = weight
        self.group = group
        self.silent = silent
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.params = dict(params) if params else {}
        self.free_raw_data = free_raw_data
        self.init_score = None
        self._predictor = None
        self._core = None              # CoreDataset once constructed
        self._used_indices = None      # set by subset()
        self._parent = None

    # ------------------------------------------------------------- laziness
    def __is_constructed(self):
        return self._core is not None

    def construct(self) -> "Dataset":
        if self._core is not None:
            return self
        if self._parent is not None:   # subset path (basic.py:1012-1035)
            parent_core = self._parent.construct()._core
            self._core = parent_core.subset(self._used_indices)
            self._apply_fields()
            return self
        params = key_alias_transform(dict(self.params))
        params.setdefault("max_bin", self.max_bin)
        if self.silent:
            params.setdefault("verbose", 0)
        cfg = Config.from_params(params)
        loader = DatasetLoader(cfg)
        ref_core = None
        if self.reference is not None:
            if not isinstance(self.reference, Dataset):
                raise TypeError("Reference dataset should be None or dataset")
            ref_core = self.reference.construct()._core
            self._set_predictor(self.reference._predictor)
        categorical = self._resolve_categorical()
        if is_str(self.data):
            if ref_core is not None:
                self._core = loader.load_from_file_align_with_other_dataset(
                    self.data, ref_core)
            else:
                self._core = loader.load_from_file(self.data)
        else:
            # column sources (CscColumns from the C API's sparse inputs)
            # pass through untouched: one column densifies at a time.
            # NOT a bare hasattr(.col) test — scipy COO matrices have a
            # `.col` ndarray and must keep densifying via _coerce_2d.
            from .io.dataset import is_column_source
            mat = (self.data if is_column_source(self.data)
                   else _coerce_2d(self.data))
            self._core = loader.construct_from_matrix(
                mat, label=self.label, reference=ref_core,
                categorical_features=categorical)
        if self.feature_name is not None:
            self._core.feature_names = list(self.feature_name)
        self._apply_fields()
        self._apply_predictor_init_score()
        if self.free_raw_data and not is_str(self.data):
            self.data = None
        return self

    def _resolve_categorical(self):
        cats = []
        if self.categorical_feature:
            for c in self.categorical_feature:
                if is_str(c):
                    if not self.feature_name:
                        raise LightGBMError(
                            "categorical_feature by name needs feature_name")
                    cats.append(self.feature_name.index(c))
                else:
                    cats.append(int(c))
        return cats

    def _apply_fields(self):
        meta = self._core.metadata
        if self.weight is not None:
            meta.set_weights(np.asarray(self.weight, dtype=np.float32).reshape(-1))
        if self.group is not None:
            meta.set_query(np.asarray(self.group, dtype=np.int64).reshape(-1))
        if self.init_score is not None:
            meta.set_init_score(
                np.asarray(self.init_score, dtype=np.float64).reshape(-1))

    def _apply_predictor_init_score(self):
        """Seed init scores from the chained predictor (basic.py:523-536)."""
        if self._predictor is None:
            return
        if self._core.metadata.init_score is not None:
            return
        if self.data is None and self._core.raw_data is None:
            raise LightGBMError(
                "Cannot set predictor after freed raw data, "
                "Set free_raw_data=False when construct Dataset to avoid this.")
        data = self.data if self.data is not None else self._core.raw_data
        raw = self._predictor.predict(data, raw_score=True, is_reshape=True,
                                      data_has_header=False)
        raw = np.asarray(raw, dtype=np.float64)
        if raw.ndim == 2:              # (N, K) row-major -> class-major flat
            init = raw.T.reshape(-1)
        else:
            init = raw.reshape(-1)
        self._core.metadata.set_init_score(init)

    # ----------------------------------------------------------- public API
    def create_valid(self, data, label=None, weight=None, group=None,
                     silent=False, params=None):
        """New Dataset aligned with self (basic.py:947-971)."""
        return Dataset(data, label=label, max_bin=self.max_bin, reference=self,
                       weight=weight, group=group, silent=silent, params=params)

    def subset(self, used_indices, params=None):
        """Row subset sharing this dataset's bin mappers (basic.py:1012-1035)."""
        ret = Dataset(None, max_bin=self.max_bin, params=params or self.params)
        ret._parent = self
        ret._used_indices = np.asarray(used_indices, dtype=np.int64)
        ret._predictor = self._predictor
        return ret

    def set_reference(self, reference):
        self.reference = reference
        self._set_predictor(reference._predictor)

    def _set_predictor(self, predictor):
        if predictor is self._predictor:
            return
        self._predictor = predictor
        if self._core is not None and predictor is not None:
            self._apply_predictor_init_score()

    def set_feature_name(self, feature_name):
        if feature_name is not None:
            self.feature_name = list(feature_name)
            if self._core is not None:
                self._core.feature_names = list(feature_name)

    def set_categorical_feature(self, categorical_feature):
        if categorical_feature is None:
            return
        if self.__is_constructed():
            Log.warning("categorical_feature set after Dataset was "
                        "constructed; it will not take effect")
        self.categorical_feature = categorical_feature

    def set_label(self, label):
        self.label = _coerce_label(label)
        if self._core is not None and self.label is not None:
            self._core.metadata.set_label(self.label)

    def set_weight(self, weight):
        self.weight = weight
        if self._core is not None and weight is not None:
            self._core.metadata.set_weights(
                np.asarray(weight, dtype=np.float32).reshape(-1))

    def set_init_score(self, init_score):
        self.init_score = init_score
        if self._core is not None and init_score is not None:
            self._core.metadata.set_init_score(
                np.asarray(init_score, dtype=np.float64).reshape(-1))

    def set_group(self, group):
        self.group = group
        if self._core is not None and group is not None:
            self._core.metadata.set_query(
                np.asarray(group, dtype=np.int64).reshape(-1))

    def get_label(self):
        if self._core is not None:
            return self._core.metadata.label
        return self.label

    def get_weight(self):
        if self._core is not None:
            return self._core.metadata.weights
        return self.weight

    def get_init_score(self):
        if self._core is not None:
            return self._core.metadata.init_score
        return self.init_score

    def get_group(self):
        if self._core is not None and self._core.metadata.query_boundaries is not None:
            return np.diff(self._core.metadata.query_boundaries)
        return self.group

    def num_data(self):
        return self.construct()._core.num_data

    def num_feature(self):
        return self.construct()._core.num_features

    def save_binary(self, filename):
        self.construct()._core.save_binary(filename)


class Booster:
    """Training/prediction handle (basic.py:1184-1677)."""

    def __init__(self, params=None, train_set=None, model_file=None,
                 silent=False):
        self.best_iteration = -1
        self._attr = {}
        self.__train_data_name = "training"
        self.__train_dataset = None
        self.__valid_datasets = []
        self.__name_valid_sets = []
        self.gbdt = None
        self.config = None
        self.objective = None
        self.__init_predictor = None
        if train_set is not None:
            if not isinstance(train_set, Dataset):
                raise TypeError("Training data should be Dataset instance")
            params = dict(params) if params else {}
            if silent:
                params.setdefault("verbose", 0)
            self.config = Config.from_params(params)
            train_set.construct()
            core = train_set._core
            self.objective = create_objective(self.config.objective, self.config)
            if self.objective is None:
                Log.warning("Using self-defined objective function")
            else:
                self.objective.init(core.metadata, core.num_data)
            train_metrics = self._create_metrics(core)
            self.gbdt = create_boosting(self.config.boosting_type)
            self.gbdt.init(self.config, core, self.objective, train_metrics)
            self.__train_dataset = train_set
            self.__init_predictor = train_set._predictor
            if self.__init_predictor is not None:
                self.gbdt.merge_from(self.__init_predictor.gbdt)
        elif model_file is not None:
            self.gbdt = _InnerPredictor(model_file=model_file).gbdt
        else:
            raise TypeError("At least need training dataset or model file "
                            "to create booster instance")

    # ------------------------------------------------------------- plumbing
    def _create_metrics(self, core):
        metrics = []
        for name in (self.config.metric or ()):
            m = create_metric(name, self.config)
            if m is None:
                continue
            m.init(core.metadata, core.num_data)
            metrics.append(m)
        return metrics

    def set_train_data_name(self, name):
        self.__train_data_name = name

    def add_valid(self, data, name):
        """basic.py:1252-1280."""
        if data._predictor is not self.__init_predictor:
            raise LightGBMError("Add validation data failed, you should use "
                                "same predictor for these data")
        data.construct()
        metrics = self._create_metrics(data._core)
        self.gbdt.add_valid_dataset(data._core, metrics)
        self.__valid_datasets.append(data)
        self.__name_valid_sets.append(name)

    def reset_parameter(self, params):
        """basic.py:1282-1302. Fast path: only the shrinkage rate changes
        (learning-rate schedules call this every iteration)."""
        params = key_alias_transform(dict(params))
        if set(params.keys()) <= {"learning_rate"}:
            if "learning_rate" in params:
                lr = float(params["learning_rate"])
                self.config.learning_rate = lr
                self.gbdt.shrinkage_rate = lr
            return
        merged = {**self._config_as_params(), **params}
        self.config = Config.from_params(merged)
        core = self.gbdt.train_data
        self._reset_objective(core)
        self.gbdt.reset_training_data(
            self.config, core, self.objective,
            self.gbdt.training_metrics)

    def _reset_objective(self, core):
        """Recreate + re-init the objective against `core`, as the
        reference's Booster::ResetTrainingData does (c_api.cpp:63-75) —
        the objective caches label/weight views of the old dataset."""
        if self.objective is None:
            return  # custom-objective mode stays custom
        self.objective = create_objective(self.config.objective, self.config)
        if self.objective is None:
            Log.warning("Using self-defined objective function")
        else:
            self.objective.init(core.metadata, core.num_data)

    def _config_as_params(self):
        from dataclasses import fields as dc_fields
        return {f.name: getattr(self.config, f.name)
                for f in dc_fields(type(self.config))
                if f.name not in ("is_parallel", "is_parallel_find_bin", "seed")}

    # ------------------------------------------------------------- training
    def update(self, train_set=None, fobj=None):
        """One boosting iteration (basic.py:1304-1341). Returns True when
        no further splits are possible (is_finished)."""
        if train_set is not None and train_set is not self.__train_dataset:
            if train_set._predictor is not self.__init_predictor:
                raise LightGBMError("Replace training data failed, you should "
                                    "use same predictor for these data")
            train_set.construct()
            self.__train_dataset = train_set
            self._reset_objective(train_set._core)
            self.gbdt.reset_training_data(
                self.config, train_set._core, self.objective,
                self._create_metrics(train_set._core))
        if fobj is None:
            return self.gbdt.train_one_iter(is_eval=False)
        grad, hess = fobj(self.__inner_predict(0), self.__train_dataset)
        return self.__boost(grad, hess)

    def __boost(self, grad, hess):
        grad = np.asarray(grad, dtype=np.float32).reshape(-1)
        hess = np.asarray(hess, dtype=np.float32).reshape(-1)
        n = self.gbdt.num_data * self.gbdt.num_class
        if len(grad) != n or len(hess) != n:
            raise ValueError("Length of grad and hess should be equal with "
                             "num_data * num_class")
        return self.gbdt.train_one_iter(grad, hess, is_eval=False)

    def rollback_one_iter(self):
        self.gbdt.rollback_one_iter()

    def current_iteration(self):
        return len(self.gbdt.models) // max(self.gbdt.num_class, 1)

    # ----------------------------------------------------------- evaluation
    def __inner_predict(self, data_idx):
        """Transformed predictions of a bound dataset, class-major flat
        (basic.py:1646-1677)."""
        return self.gbdt.get_predict_at(data_idx)

    def __inner_eval(self, data_name, data_idx, feval=None):
        ret = []
        names = self.gbdt.get_eval_names(data_idx)
        values = self.gbdt.get_eval_at(data_idx)
        metrics = (self.gbdt.training_metrics if data_idx == 0
                   else self.gbdt.valid_metrics[data_idx - 1])
        factors = []
        for m in metrics:
            factors.extend([m.factor_to_bigger_better] * len(m.names))
        for name, value, fac in zip(names, values, factors):
            ret.append((data_name, name, value, fac > 0))
        if feval is not None:
            dataset = (self.__train_dataset if data_idx == 0
                       else self.__valid_datasets[data_idx - 1])
            feval_ret = feval(self.__inner_predict(data_idx), dataset)
            if isinstance(feval_ret, list):
                for name, value, bigger in feval_ret:
                    ret.append((data_name, name, value, bigger))
            else:
                name, value, bigger = feval_ret
                ret.append((data_name, name, value, bigger))
        return ret

    def eval(self, data, name, feval=None):
        if data is self.__train_dataset:
            return self.eval_train(feval)
        for i, vd in enumerate(self.__valid_datasets):
            if data is vd:
                return self.__inner_eval(name, i + 1, feval)
        raise LightGBMError("Cannot evaluate Dataset that was not used "
                            "during training")

    def eval_train(self, feval=None):
        return self.__inner_eval(self.__train_data_name, 0, feval)

    def eval_valid(self, feval=None):
        out = []
        for i, name in enumerate(self.__name_valid_sets):
            out.extend(self.__inner_eval(name, i + 1, feval))
        return out

    # ----------------------------------------------------------- prediction
    def predict(self, data, num_iteration=-1, raw_score=False,
                pred_leaf=False, data_has_header=False, is_reshape=True):
        predictor = _InnerPredictor(booster=self.gbdt)
        return predictor.predict(data, num_iteration, raw_score, pred_leaf,
                                 data_has_header, is_reshape)

    def _to_predictor(self):
        return _InnerPredictor(booster=self.gbdt)

    # -------------------------------------------------------- serialization
    def save_model(self, filename, num_iteration=-1):
        self.gbdt.save_model_to_file(num_iteration, filename)

    def dump_model(self):
        return self.gbdt.dump_model()

    def feature_importance(self, importance_type="split"):
        """Per-feature importance ndarray from the split ledger
        (telemetry/quality.py), reference semantics: `split` = int64
        count of splits using the feature (basic.py:1587-1601),
        `gain` = float64 sum of split gain over those splits (the
        C API's LGBM_BoosterFeatureImportance gain variant), `coeff` =
        float64 gain-weighted |coefficient| sums over linear leaves
        (linear_tree=true models; all-zero otherwise — see
        docs/Linear-Trees.md)."""
        from .telemetry.quality import IMPORTANCE_TYPES
        if importance_type not in IMPORTANCE_TYPES:
            raise LightGBMError(
                f"Unknown importance type {importance_type!r}: expected "
                f"one of {IMPORTANCE_TYPES}")
        return self.gbdt.feature_importance_values(importance_type)

    # ---------------------------------------------------------------- attrs
    def attr(self, key):
        return self._attr.get(key)

    def set_attr(self, **kwargs):
        for key, value in kwargs.items():
            if value is None:
                self._attr.pop(key, None)
            else:
                self._attr[key] = str(value)
