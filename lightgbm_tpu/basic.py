# placeholder - full implementation follows
class Dataset: pass
class Booster: pass
from .utils.log import LightGBMError
