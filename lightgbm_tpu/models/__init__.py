from .tree import Tree
from .gbdt import GBDT, create_boosting
from .dart import DART

__all__ = ["Tree", "GBDT", "DART", "create_boosting"]
