"""GOSS (Gradient-based One-Side Sampling) boosting.

NOT part of the v0 reference snapshot (SURVEY.md: GOSS/EFB arrived with
the NeurIPS-2017 LightGBM paper) — an additive extension following the
paper's algorithm: keep the top_rate fraction of rows by gradient
magnitude, sample other_rate of the rest uniformly, and amplify the
sampled small-gradient rows by (1 - top_rate) / other_rate so split
gains stay unbiased. Fits this framework as a fractional in-bag weight
vector: the builders already multiply gradient/hessian/count columns by
`inbag` (models/tree_learner.py), so amplified rows contribute weighted
statistics — including weighted counts, so min_data_in_leaf acts on
effective (weighted) rows under GOSS; out-of-bag rows still receive
score updates through the full-row partition.

Row score = sum over classes of |g * h| with a plain-boosting warm-up
of ceil(1 / learning_rate) iterations, both per the paper's reference
implementation.

The sampling runs entirely in-graph (sort threshold + jax PRNG keyed on
(bagging_seed, iteration)), so GOSS keeps the fused multi-iteration
trainer (models/gbdt.py train_many) — the per-iteration loop calls the
SAME device function, making the two paths produce identical samples.
"""

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.log import Log
from .gbdt import GBDT


class GOSS(GBDT):
    name = "goss"

    def init(self, config, train_data, objective, training_metrics=()):
        super().init(config, train_data, objective, training_metrics)
        if not (0.0 <= config.top_rate <= 1.0
                and 0.0 <= config.other_rate <= 1.0
                and config.top_rate + config.other_rate <= 1.0):
            Log.fatal("GOSS needs top_rate >= 0, other_rate >= 0 and "
                      "top_rate + other_rate <= 1.0 (got %g, %g)",
                      config.top_rate, config.other_rate)
        if config.bagging_fraction < 1.0 and config.bagging_freq > 0:
            Log.fatal("Cannot use bagging in GOSS (bagging_fraction/"
                      "bagging_freq conflict with gradient-based sampling)")
        self._warmup = int(np.ceil(1.0 / max(config.learning_rate, 1e-6)))
        self._goss_key = jax.random.PRNGKey(config.bagging_seed)

    def _goss_weights(self, it, gradients, hessians):
        """(K, M) device grads -> (M,) in-bag weights, in-graph.

        M may include zero-gradient padding rows: they sort to the
        bottom, and the caller masks any sampled pad rows away (the
        fused path multiplies by the pad mask; the per-iteration path
        slices to N).
        """
        cfg = self.config
        n = self.num_data
        m = gradients.shape[-1]
        score = jnp.sum(jnp.abs(gradients * hessians), axis=0)
        top_n = max(1, int(cfg.top_rate * n))
        rand_n = int(cfg.other_rate * n)
        thr = jnp.sort(score)[m - top_n]  # ties land in the top set
        top = score >= thr
        weights = top.astype(jnp.float32)
        if rand_n > 0:
            # realized rest size over REAL rows (ties at the threshold
            # inflate the top set); p and amp both use it so that
            # E[#sampled] = rand_n AND p * amp = 1 (each rest row keeps
            # expected weight 1 — the paper's unbiased-gain invariant).
            # Without ties n_rest = n - top_n and amp reduces to the
            # paper's (1 - top_rate) / other_rate.
            n_rest = jnp.maximum(jnp.sum((~top[:n]).astype(jnp.int32)), 1)
            p = rand_n / n_rest
            amp = (n_rest / rand_n).astype(jnp.float32)
            # draw at the UNPADDED size: jax.random.uniform values depend
            # on the array size, and the fused path passes padded rows —
            # a (m,) draw would make fused and sequential samples diverge
            u = jax.random.uniform(
                jax.random.fold_in(self._goss_key, it), (n,))
            if m > n:  # pad rows: u=1 >= p, never sampled
                u = jnp.pad(u, (0, m - n), constant_values=1.0)
            weights = jnp.where(~top & (u < p), jnp.float32(amp), weights)
        # warm-up iterations train on all rows
        return jnp.where(it < self._warmup, jnp.ones(m, jnp.float32),
                         weights)

    def _fused_boosting_ok(self):
        return True  # sampling is in-graph; the fused scan stays valid

    def _fused_inbag_fn(self):
        return self._goss_weights

    def _bagging(self, it, gradients=None, hessians=None):
        if gradients is None:
            return None
        if it < self._warmup:
            return None
        w = self._goss_weights(
            jnp.int32(it),
            jnp.asarray(gradients, jnp.float32).reshape(self.num_class, -1),
            jnp.asarray(hessians, jnp.float32).reshape(self.num_class, -1))
        Log.debug("GOSS: re-sampled at iteration %d", it)
        return np.asarray(w)[:self.num_data]
