"""GOSS (Gradient-based One-Side Sampling) boosting.

NOT part of the v0 reference snapshot (SURVEY.md: GOSS/EFB arrived with
the NeurIPS-2017 LightGBM paper) — an additive extension following the
paper's algorithm: keep the top_rate fraction of rows by gradient
magnitude, sample other_rate of the rest uniformly, and amplify the
sampled small-gradient rows by (1 - top_rate) / other_rate so split
gains stay unbiased. Fits this framework as a fractional in-bag weight
vector: the builders already multiply gradient/hessian/count columns by
`inbag` (models/tree_learner.py), so amplified rows contribute weighted
statistics — including weighted counts, so min_data_in_leaf acts on
effective (weighted) rows under GOSS; out-of-bag rows still receive
score updates through the full-row partition.

Row score = sum over classes of |g * h| with a plain-boosting warm-up
of ceil(1 / learning_rate) iterations, both per the paper's reference
implementation.
"""

import numpy as np

from ..utils.log import Log
from .gbdt import GBDT


class GOSS(GBDT):
    name = "goss"

    def init(self, config, train_data, objective, training_metrics=()):
        super().init(config, train_data, objective, training_metrics)
        if not (0.0 <= config.top_rate <= 1.0
                and 0.0 <= config.other_rate <= 1.0
                and config.top_rate + config.other_rate <= 1.0):
            Log.fatal("GOSS needs top_rate >= 0, other_rate >= 0 and "
                      "top_rate + other_rate <= 1.0 (got %g, %g)",
                      config.top_rate, config.other_rate)
        if config.bagging_fraction < 1.0 and config.bagging_freq > 0:
            Log.fatal("Cannot use bagging in GOSS (bagging_fraction/"
                      "bagging_freq conflict with gradient-based sampling)")
        self._warmup = int(np.ceil(1.0 / max(config.learning_rate, 1e-6)))

    def _bagging(self, it, gradients=None, hessians=None):
        cfg = self.config
        if it < self._warmup or gradients is None:
            return None
        n = self.num_data
        g = np.abs(np.asarray(gradients, dtype=np.float64)
                   * np.asarray(hessians, dtype=np.float64))
        score = g.reshape(self.num_class, n).sum(axis=0)
        top_n = max(1, int(cfg.top_rate * n))
        rand_n = int(cfg.other_rate * n)
        # threshold of the top_n-th largest score (ties land in the top set)
        thr = np.partition(score, n - top_n)[n - top_n]
        top = score >= thr
        rest = ~top
        n_rest = int(rest.sum())
        mask = np.zeros(n, dtype=np.float32)
        mask[top] = 1.0
        if rand_n > 0 and n_rest > 0:
            amp = (1.0 - cfg.top_rate) / cfg.other_rate
            u = self.random._rng.random_sample(n)
            mask[rest & (u < rand_n / n_rest)] = amp
        Log.debug("GOSS: %d top + ~%d sampled rows of %d",
                  int(top.sum()), rand_n, n)
        return mask
