"""ScoreUpdater: per-dataset model scores.

Reference: src/boosting/score_updater.hpp:15-85. Scores live on device as
a (num_class, N) float32 array. Train-set updates use the tree builder's
final row->leaf partition (a pure gather — the analog of the reference's
via-partition fast path Tree::AddPredictionToScore(tree_learner)).

Valid sets are scored per iteration ON DEVICE by a vectorized bin-space
tree traversal over the dataset's device bin matrix (the analog of
Tree::AddPredictionToScore(data), tree.h:211-224, which the reference
runs OpenMP-parallel inside the hot loop): every row walks the tree in
lockstep inside a `lax.while_loop` bounded by the realized depth, so a
training iteration never leaves the device. The host numpy traversal
remains for re-scoring materialized (loaded) models.
"""

import jax
import jax.numpy as jnp
import numpy as np


def _traverse_add(score_row, bins_dev, is_cat, split_feature, threshold_bin,
                  left_child, right_child, leaf_value, n_splits, scale,
                  feat_slot, feat_off, feat_nb):
    """score_row + scale * leaf_value[leaf(bins)] for one tree, on device.

    bins_dev: (S, N) STORED bins (S == F when unbundled); virtual feature
    f lives in slot feat_slot[f] at bin offset feat_off[f] with
    feat_nb[f] bins (identity maps when no bundling — the decode below
    reduces to the raw bin value). Tree arrays as produced by
    build_tree_device (leaves encoded as ~leaf_index in child arrays).
    A 0-split tree contributes leaf_value[0] == 0, so it is a no-op.
    """
    n = bins_dev.shape[1]
    node0 = jnp.where(n_splits > 0, 0, -1)
    node = jnp.full((n,), node0, dtype=jnp.int32)

    def cond(state):
        i, node = state
        return jnp.logical_and(i < leaf_value.shape[0] - 1,
                               jnp.any(node >= 0))

    def body(state):
        i, node = state
        nd = jnp.maximum(node, 0)
        feat = split_feature[nd]
        sc = jnp.take_along_axis(bins_dev, feat_slot[feat][None, :],
                                 axis=0)[0].astype(jnp.int32)
        off = feat_off[feat]
        nb = feat_nb[feat]
        fv = jnp.where((sc > off) & (sc <= off + nb - 1), sc - off, 0)
        thr = threshold_bin[nd]
        go_left = jnp.where(is_cat[feat], fv == thr, fv <= thr)
        nxt = jnp.where(go_left, left_child[nd], right_child[nd])
        node = jnp.where(node < 0, node, nxt)
        return i + 1, node

    _, node = jax.lax.while_loop(cond, body, (jnp.asarray(0, jnp.int32), node))
    leaf = jnp.where(node < 0, ~node, 0)
    return score_row + scale * jnp.take(leaf_value, leaf)


_traverse_add_jit = jax.jit(_traverse_add)


@jax.jit
def _stacked_deltas(bins_dev, is_cat, sf, thr, lc, rc, lv, nsp, scale,
                    feat_slot, feat_off, feat_nb):
    """(M, ...) stacked tree arrays -> (M, N) scaled score deltas.

    One vmapped bin-space traversal over the tree axis: the whole
    block's valid/train scoring is a single device program (the
    reference re-walks the dataset per tree inside the training loop,
    gbdt.cpp:210-245 + tree.h:211-224)."""
    zero = jnp.zeros((bins_dev.shape[1],), jnp.float32)

    def one(sfi, thri, lci, rci, lvi, nspi):
        return _traverse_add(zero, bins_dev, is_cat, sfi, thri, lci, rci,
                             lvi.astype(jnp.float32), nspi, scale,
                             feat_slot, feat_off, feat_nb)

    return jax.vmap(one)(sf, thr, lc, rc, lv, nsp)


class ScoreUpdater:
    def __init__(self, dataset, num_class):
        self.dataset = dataset
        self.num_class = int(num_class)
        n = dataset.num_data
        self.num_data = n
        self._is_cat_dev = None
        self._decode_dev = None
        init = dataset.metadata.init_score
        if init is not None:
            if len(init) != n * self.num_class:
                from ..utils.log import Log
                Log.fatal("Number of class for initial score error")
            self.score = jnp.asarray(
                np.asarray(init, dtype=np.float32).reshape(self.num_class, n))
        else:
            self.score = jnp.zeros((self.num_class, n), dtype=jnp.float32)

    def add_score_by_partition(self, leaf_values, row_leaf, curr_class):
        """score += leaf_values[row_leaf] (device gather)."""
        upd = jnp.take(jnp.asarray(leaf_values, dtype=jnp.float32), row_leaf)
        self.score = self.score.at[curr_class].add(upd)

    def add_score_by_values(self, values, curr_class):
        """score += values: one (N,) per-row delta computed on host —
        the linear-leaf training path (models/linear_leaves.py), where
        a leaf's contribution varies per row so a leaf-value gather
        cannot express it."""
        self.score = self.score.at[curr_class].add(
            jnp.asarray(np.asarray(values, dtype=np.float32)))

    def _tree_bin_values(self, tree):
        """Bin representative table when `tree` needs one (linear
        leaves), else None — keeps the constant-leaf path allocation-
        free and works on datasets with no resident table."""
        if getattr(tree, "is_linear", False):
            return self.dataset.bin_value_table()
        return None

    def _decode_maps(self):
        """(feat_slot, feat_off, feat_nb) device arrays: bundle decode
        when the dataset is bundled, identity maps otherwise."""
        if self._decode_dev is None:
            ds = self.dataset
            nb = np.asarray(ds.num_bin_array(), dtype=np.int32)
            if ds.bundle_plan is None:
                slot = np.arange(ds.num_features, dtype=np.int32)
                off = np.zeros(ds.num_features, dtype=np.int32)
            else:
                slot = ds.bundle_plan.feat_slot
                off = ds.bundle_plan.feat_offset
            self._decode_dev = (jnp.asarray(slot), jnp.asarray(off),
                                jnp.asarray(nb))
        return self._decode_dev

    def add_score_by_device_tree(self, out, scale, curr_class):
        """Per-iteration valid-set scoring: device bin-space traversal of
        the builder's raw output dict. No host synchronization."""
        if self._is_cat_dev is None:
            self._is_cat_dev = jnp.asarray(self.dataset.feature_is_categorical())
        feat_slot, feat_off, feat_nb = self._decode_maps()
        new_row = _traverse_add_jit(
            self.score[curr_class], self.dataset.device_bins(),
            self._is_cat_dev, out["split_feature"],
            out["split_threshold_bin"], out["left_child"],
            out["right_child"],
            jnp.asarray(out["leaf_value"], dtype=jnp.float32),
            out["n_splits"], jnp.float32(scale),
            feat_slot, feat_off, feat_nb)
        self.score = self.score.at[curr_class].set(new_row)

    def deltas_by_stacked_device_trees(self, stk, scale):
        """(M, N) scaled deltas for M stacked builder-output trees (the
        dict's arrays carry a flattened leading tree axis). Device-only;
        no host sync. Used by GBDT.train_many_eval's per-iteration
        score snapshots."""
        if self._is_cat_dev is None:
            self._is_cat_dev = jnp.asarray(
                self.dataset.feature_is_categorical())
        feat_slot, feat_off, feat_nb = self._decode_maps()
        return _stacked_deltas(
            self.dataset.device_bins(), self._is_cat_dev,
            stk["split_feature"], stk["split_threshold_bin"],
            stk["left_child"], stk["right_child"], stk["leaf_value"],
            stk["n_splits"], jnp.float32(scale),
            feat_slot, feat_off, feat_nb)

    def add_score_by_tree(self, tree, curr_class):
        """Host bin-space traversal (re-scoring loaded/materialized models)."""
        vals = tree.predict_by_bins(
            self.dataset.traversal_bins(),
            self._tree_bin_values(tree)).astype(np.float32)
        self.score = self.score.at[curr_class].add(jnp.asarray(vals))

    def sub_score_by_tree(self, tree, curr_class):
        vals = tree.predict_by_bins(
            self.dataset.traversal_bins(),
            self._tree_bin_values(tree)).astype(np.float32)
        self.score = self.score.at[curr_class].add(jnp.asarray(-vals))

    def add_score_by_trees(self, trees, num_class, sign=1.0):
        """Batched update from many class-major trees: one host pass and
        ONE device update total. sign=+1: valid-score catch-up after a
        fused block (gbdt.train_many); sign=-1: early-stopping
        truncation."""
        delta = np.zeros((self.num_class, self.num_data), dtype=np.float32)
        for i, tree in enumerate(trees):
            delta[i % num_class] += sign * tree.predict_by_bins(
                self.dataset.traversal_bins(), self._tree_bin_values(tree))
        self.score = self.score + jnp.asarray(delta)

    def sub_score_by_trees(self, trees, num_class):
        self.add_score_by_trees(trees, num_class, sign=-1.0)

    def host_score(self):
        """Flat class-major (K*N,) float64 host array (the reference's
        score layout, score[k*N + i])."""
        return np.asarray(self.score, dtype=np.float64).reshape(-1)
