"""ScoreUpdater: per-dataset model scores.

Reference: src/boosting/score_updater.hpp:15-85. Scores live on device as
a (num_class, N) float32 array. Train-set updates use the tree builder's
final row->leaf partition (a pure gather — the analog of the reference's
via-partition fast path Tree::AddPredictionToScore(tree_learner)); valid
sets are traversed in bin space on host.
"""

import jax.numpy as jnp
import numpy as np


class ScoreUpdater:
    def __init__(self, dataset, num_class):
        self.dataset = dataset
        self.num_class = int(num_class)
        n = dataset.num_data
        self.num_data = n
        init = dataset.metadata.init_score
        if init is not None:
            if len(init) != n * self.num_class:
                from ..utils.log import Log
                Log.fatal("Number of class for initial score error")
            self.score = jnp.asarray(
                np.asarray(init, dtype=np.float32).reshape(self.num_class, n))
        else:
            self.score = jnp.zeros((self.num_class, n), dtype=jnp.float32)

    def add_score_by_partition(self, leaf_values, row_leaf, curr_class):
        """score += leaf_values[row_leaf] (device gather)."""
        upd = jnp.take(jnp.asarray(leaf_values, dtype=jnp.float32), row_leaf)
        self.score = self.score.at[curr_class].add(upd)

    def add_score_by_tree(self, tree, curr_class):
        """Host bin-space traversal (valid sets / re-scoring loaded models)."""
        vals = tree.predict_by_bins(self.dataset.bins).astype(np.float32)
        self.score = self.score.at[curr_class].add(jnp.asarray(vals))

    def sub_score_by_tree(self, tree, curr_class):
        vals = tree.predict_by_bins(self.dataset.bins).astype(np.float32)
        self.score = self.score.at[curr_class].add(jnp.asarray(-vals))

    def sub_score_by_trees(self, trees, num_class):
        """Batched subtraction of many class-major trees: one host pass and
        ONE device update total (used by early-stopping truncation)."""
        delta = np.zeros((self.num_class, self.num_data), dtype=np.float32)
        for i, tree in enumerate(trees):
            delta[i % num_class] -= tree.predict_by_bins(self.dataset.bins)
        self.score = self.score + jnp.asarray(delta)

    def host_score(self):
        """Flat class-major (K*N,) float64 host array (the reference's
        score layout, score[k*N + i])."""
        return np.asarray(self.score, dtype=np.float64).reshape(-1)
