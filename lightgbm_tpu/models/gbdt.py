"""GBDT: the boosting loop.

Reference: src/boosting/gbdt.h:17-310, src/boosting/gbdt.cpp. Covers:
gradient boosting with bagging (record- and query-unit), per-class tree
training, shrinkage, out-of-bag score updates, metric output with early
stopping + model truncation, rollback, model text/JSON serialization,
load-from-string, split-count feature importance, raw/sigmoid/softmax
prediction paths, and booster merging for continued training.

Bagging note: the reference draws a sequential selection sample
(gbdt.cpp:161-169), uniform over fixed-size subsets. We draw the same
distribution IN-GRAPH with jax.random.permutation keyed on
(bagging_seed, iter // bagging_freq): bags are stateless per re-bag
window, identical between the fused scan and the per-iteration loop,
and exact-count like the reference's.
"""

import contextlib
import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel import heartbeat
from ..telemetry import disttrace
from ..telemetry import journal as run_journal
from ..telemetry.registry import MetricsRegistry
from ..telemetry.trace import SpanTracer
from ..utils import common, faults, guardrails
from ..utils.log import Log
from .score_updater import ScoreUpdater
from .tree import Tree
from .tree_learner import create_tree_learner

K_MIN_SCORE = -np.inf

# Model text-format version this reader/writer speaks. v1: constant
# leaves (implicit — no format_version line, byte-identical to every
# pre-linear release). v2: per-leaf linear coefficient blocks
# (models/linear_leaves.py, docs/Linear-Trees.md). Loading a HIGHER
# version is a hard error, never a silent partial parse.
MODEL_FORMAT_VERSION = 2


def f32_safe_thresholds(thr, dt):
    """f32 cast of f64 numeric thresholds rounded toward -inf so
    `x <= thr32` equals the f64 `x <= thr` for every f32-representable
    x (round-to-nearest could lift thr32 ABOVE thr and flip rows
    landing in between). Categorical thresholds are exact category
    ids: f32 holds ints < 2^24 exactly, and the id-vs-id equality is
    unaffected by the adjustment only applied to numeric nodes.
    Shared by the training-side device predictor and the serving-side
    CompiledPredictor (serving/compiled_model.py)."""
    thr32 = thr.astype(np.float32)
    numeric = dt != Tree.CATEGORICAL
    lifted = numeric & (thr32.astype(np.float64) > thr)
    return np.where(lifted,
                    np.nextafter(thr32, np.float32(-np.inf),
                                 dtype=np.float32),
                    thr32)


def device_traverse(xb, sf, thr, cat, lc, rc, node0, depth):
    """Lockstep device traversal of a (B, F) f32 row block through all
    stacked trees: every (row, tree) pair walks `depth` steps (leaves
    freeze as ~leaf in the child arrays) and the final (B, T) node
    states (~leaf encoded) come back. NaN: numeric compares send NaN
    right (fval <= thr is False) and categorical compares send NaN
    right too (a missing value is not a category id — reference
    default-direction semantics). Traced inside jitted callers
    (GBDT._predict_block_device, serving kernels)."""
    b = xb.shape[0]
    t_cnt = sf.shape[0]
    t_idx = jnp.arange(t_cnt)
    node_init = jnp.broadcast_to(node0[None, :], (b, t_cnt))
    xs = jnp.nan_to_num(xb)  # the int cast below needs a finite input

    def step(_, node):
        nd = jnp.maximum(node, 0)
        feat = sf[t_idx[None, :], nd]                       # (B, T)
        th = thr[t_idx[None, :], nd]
        is_c = cat[t_idx[None, :], nd]
        rows = jnp.arange(b)[:, None]
        fval = xb[rows, feat]
        fcat = xs[rows, feat]
        go_left = jnp.where(
            is_c,
            (fcat.astype(jnp.int32) == th.astype(jnp.int32))
            & ~jnp.isnan(fval),
            fval <= th)
        nxt = jnp.where(go_left, lc[t_idx[None, :], nd],
                        rc[t_idx[None, :], nd])
        return jnp.where(node < 0, node, nxt)

    return jax.lax.fori_loop(0, depth, step, node_init)


class LazyTree:
    """A Tree whose arrays still live on device.

    The training loop appends these WITHOUT pulling anything to host —
    the only per-iteration synchronization is the scalar n_splits stop
    check. Any host-side access (serialization, prediction, rollback,
    DART normalization) materializes a real Tree on first touch via the
    learner's batched single-transfer conversion.
    """

    # builder output is always constant-leaf; linear-leaf trees are
    # materialized eagerly (GBDT._fit_linear_tree), never lazy. A class
    # attribute keeps `getattr(m, "is_linear", ...)` probes from
    # forcing a materializing __getattr__ round-trip.
    is_linear = False

    def __init__(self, out, learner, shrink=1.0):
        # row_leaf is (N_pad,) and already consumed by the score updater;
        # holding it for every tree would pin O(iter * N) HBM.
        self._out = {k: v for k, v in out.items() if k != "row_leaf"}
        self._learner = learner
        self._shrink = float(shrink)
        self._tree = None

    @property
    def num_leaves(self):
        if self._tree is not None:
            return self._tree.num_leaves
        return int(self._out["n_splits"]) + 1

    def shrinkage(self, rate):
        if self._tree is not None:
            self._tree.shrinkage(rate)
        else:
            self._shrink = self._shrink * float(rate)

    def materialize(self) -> Tree:
        if self._tree is None:
            self._tree = self._learner._to_host_tree(self._out, shrink=self._shrink)
            self._out = None
        return self._tree

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self.materialize(), name)


class _VersionedList(list):
    """Model list with a mutation counter: the stacked-prediction caches
    key on (slice, length, version) so length-preserving mutations
    (rollback + retrain) can never serve stale trees."""

    def __init__(self, *args):
        super().__init__(*args)
        self.version = 0

    def _bump(self):
        self.version = getattr(self, "version", 0) + 1

    def append(self, item):
        self._bump()
        super().append(item)

    def extend(self, items):
        self._bump()
        super().extend(items)

    def __delitem__(self, key):
        self._bump()
        super().__delitem__(key)

    def __setitem__(self, key, value):
        self._bump()
        super().__setitem__(key, value)

    def insert(self, index, item):
        self._bump()
        super().insert(index, item)

    def pop(self, index=-1):
        self._bump()
        return super().pop(index)

    def remove(self, item):
        self._bump()
        super().remove(item)

    def clear(self):
        self._bump()
        super().clear()

    def __iadd__(self, items):
        self._bump()
        return super().__iadd__(items)

    def sort(self, **kwargs):
        self._bump()
        super().sort(**kwargs)

    def reverse(self):
        self._bump()
        super().reverse()


class _BlockSnapshots:
    """Per-iteration score snapshots over one fused training block.

    After GBDT._run_fused_block, the block's tree arrays are still
    stacked on device. For every bound dataset, the score after
    in-block iteration t is base + cumsum(deltas)[t], where the deltas
    come from ONE vmapped bin-space traversal per chunk
    (score_updater._stacked_deltas) — so the engine can replay the
    reference's per-iteration eval/early-stop callback protocol
    (gbdt.cpp:210-349) without a single training-loop host sync.
    Chunking bounds device memory to ~CHUNK_BYTES per dataset; the
    caller walks t forward, so chunks stream.
    """

    CHUNK_BYTES = 64 << 20

    def __init__(self, gbdt, stacked, base_train, base_valids, t_eff,
                 n_before, k_stop, natural_stop):
        self._gbdt = gbdt
        self._stacked = stacked
        self._t_eff = t_eff
        self._n_before = n_before
        self._k_stop = k_stop
        self._natural_stop = natural_stop
        self._scan_final_train = gbdt.train_score_updater.score
        self._states = [self._new_state(gbdt.train_score_updater,
                                        base_train)]
        for u, b in zip(gbdt.valid_score_updaters, base_valids):
            self._states.append(self._new_state(u, b))

    @staticmethod
    def _new_state(updater, base):
        return {"updater": updater, "base": base, "next": 0,
                "c0": 0, "chunk": None, "carry": None}

    def _flat_slice(self, t0, t1):
        """Stacked arrays sliced to [t0, t1) with the (iter, class) axes
        flattened to one leading tree axis."""
        k = self._gbdt.num_class
        out = {}
        for key, v in self._stacked.items():
            s = v[t0:t1]
            if k > 1:
                s = s.reshape(((t1 - t0) * k,) + tuple(s.shape[2:]))
            out[key] = s
        return out

    def _row_at(self, st, t):
        gb = self._gbdt
        k = gb.num_class
        u = st["updater"]
        if st["chunk"] is not None and t < st["c0"]:
            raise ValueError("snapshots must be walked forward")
        while st["chunk"] is None or t >= st["c0"] + st["chunk"].shape[0]:
            c0 = st["next"]
            v = u.num_data
            csz = max(1, min(self._t_eff - c0,
                             self.CHUNK_BYTES // max(1, k * v * 4)))
            deltas = u.deltas_by_stacked_device_trees(
                self._flat_slice(c0, c0 + csz), gb.shrinkage_rate)
            deltas = deltas.reshape(csz, k, v)
            carry = st["carry"] if st["carry"] is not None else st["base"]
            cum = carry[None] + jnp.cumsum(deltas, axis=0)
            st["carry"] = cum[-1]
            st["c0"], st["chunk"], st["next"] = c0, cum, c0 + csz
        return st["chunk"][t - st["c0"]]

    def drop_tail_to(self, t):
        """Early-stop break at in-block iteration t: drop every tree
        past iteration t WITHOUT score adjustment (the caller has set
        all scores to the t snapshot). Accounts for the k_stop
        partial-class trees a natural-stop block appends beyond its
        t_eff full iterations — a plain per-iteration count would leave
        them behind and break the class-major model layout."""
        gb = self._gbdt
        n_drop = (self._t_eff - (t + 1)) * gb.num_class
        if self._natural_stop:
            n_drop += self._k_stop
        if n_drop > 0:
            del gb.models[-n_drop:]
        dropped = self._t_eff - (t + 1)
        gb.iter -= dropped
        if gb.journal is not None and dropped > 0:
            gb.journal.event("truncate", iteration=int(gb.iter),
                             dropped_iters=int(dropped),
                             reason="early_stop_block")
        gb._journal_quality()  # snap the split ledger to the kept trees

    def set_scores_at(self, t, with_train=False):
        """Point every bound updater's score at the post-iteration-t
        state (t 0-based within the block). The train updater only
        moves when with_train (train-set metrics requested, or fixing
        state on an early-stop break) — its canonical final value comes
        from the scan itself."""
        for st in self._states[1:]:
            st["updater"].score = self._row_at(st, t)
        if with_train:
            st = self._states[0]
            st["updater"].score = self._row_at(st, t)

    def finalize(self):
        """After a COMPLETED walk (no early-stop break): restore the
        train score to the scan's final value, or — after a natural
        stop (an empty tree mid-block) — rebuild exact state for the
        kept trees, including partial-class trees the walk never saw."""
        gb = self._gbdt
        if not self._natural_stop:
            gb.train_score_updater.score = self._scan_final_train
            return False
        Log.info("Stopped training because there are no more leafs "
                 "that meet the split requirements.")
        if gb._natural_stop_score_exact():
            gb.train_score_updater.score = self._scan_final_train
        else:
            gb._rebuild_train_score_from_models()
        if self._k_stop > 0:
            # the stop iteration kept classes [0, k_stop) whose deltas
            # the per-full-iteration walk never applied
            new_trees = gb.models[self._n_before:]
            for st in self._states[1:]:
                st["updater"].score = st["base"]
                if new_trees:
                    st["updater"].add_score_by_trees(new_trees,
                                                     gb.num_class)
        return True


class GBDT:
    name = "gbdt"

    def __init__(self):
        self.models = _VersionedList()  # Tree list, class-major per iteration
        self.iter = 0
        self.num_init_iteration = 0
        self.num_iteration_for_pred = 0
        self.num_class = 1
        self.sigmoid = -1.0
        self.label_idx = 0
        self.max_feature_idx = 0
        self.feature_names = []
        self.train_data = None
        self.config = None
        self.objective = None
        self.tree_learner = None
        self.train_score_updater = None
        self.valid_score_updaters = []
        self.valid_metrics = []
        self.training_metrics = []
        self.early_stopping_round = 0
        self.shrinkage_rate = 0.1
        self.best_iter = []
        self.best_score = []
        self.best_msg = []
        self._bag_rows = None       # in-bag float mask or None
        self._bag_window = None     # it // bagging_freq of the cached bag
        self.last_compile_cache_hit = False  # persistent-cache hit on
        #                             the latest fused-program lowering
        # per-Booster telemetry (telemetry/): the tracer replaces the
        # old utils/timers.py process-global singleton, whose
        # accumulator two Boosters in one process silently shared
        self.tracer = SpanTracer()
        self.metrics = MetricsRegistry()
        self.journal = None         # RunJournal when `telemetry` is on
        self._trainz_server = None
        # model-quality observability (telemetry/quality.py): the split
        # ledger tracker (`quality_telemetry` knob) and the training
        # dataset's baseline distribution (io/profile.py), persisted
        # next to every saved model file for the serving drift monitor
        self.quality = None
        self.dataset_profile = None
        self._last_metric_values = {}
        # collective latency/overlap attribution (`comm_telemetry`
        # knob; telemetry/comm_profile.py): fed by the heartbeat
        # timing sink, flushed into one `comm` journal record per
        # iteration/block
        self.comm_profile = None

    # ------------------------------------------------------------------ init
    def init(self, config, train_data, objective, training_metrics=()):
        self.iter = 0
        self.num_class = config.num_class
        self.config = None
        self.train_data = None
        self.reset_training_data(config, train_data, objective, training_metrics)

    def reset_training_data(self, config, train_data, objective, training_metrics=()):
        """gbdt.cpp:42-115."""
        if self.train_data is not None and not self.train_data.check_align(train_data):
            Log.fatal("cannot reset training data, since new training data has "
                      "different bin mappers")
        self.early_stopping_round = config.early_stopping_round
        self.shrinkage_rate = config.learning_rate
        self.objective = objective
        self.apply_predict_config(config)
        self._bag_fn = None   # bakes in config/metadata; rebuild lazily
        self._bag_rows = None
        self._bag_window = None
        self.sigmoid = -1.0
        if objective is not None and objective.name == "binary":
            self.sigmoid = config.sigmoid

        # compiled fused programs bake in the old learner's bins and the
        # old objective's labels; never reuse them across a reset
        self._fused_cache = {}
        data_changed = train_data is not None and train_data is not self.train_data
        if data_changed:
            if self.tree_learner is None:
                self.tree_learner = create_tree_learner(config.tree_learner, config)
            else:
                self.tree_learner.config = config
            self.tree_learner.init(train_data)
            self.training_metrics = list(training_metrics)
            self.train_score_updater = ScoreUpdater(train_data, self.num_class)
            # replay THIS booster's trees onto the new data; merged init
            # trees are covered by the dataset's init score (gbdt.cpp:77-79)
            for i in range(self.iter):
                for k in range(self.num_class):
                    t = self.models[(i + self.num_init_iteration) * self.num_class + k]
                    self.train_score_updater.add_score_by_tree(t, k)
            self.num_data = train_data.num_data
            self.max_feature_idx = train_data.num_total_features - 1
            self.label_idx = train_data.label_idx
            self.feature_names = list(train_data.feature_names)
            # the dataset's training-time baseline distribution rides
            # with the booster so save_model_to_file can persist it
            # next to the model text (docs/Observability.md)
            self.dataset_profile = getattr(train_data, "profile", None)
        self.train_data = train_data
        self.config = config
        # data_changed already init'ed the learner with this config
        if self.tree_learner is not None and not data_changed:
            self.tree_learner.reset_config(config)
        if self.tree_learner is not None:
            # learners account host<->device transfer bytes into the
            # booster's registry (parallel/learners.py)
            self.tree_learner.metrics = self.metrics
        self._setup_telemetry(config)

    def add_valid_dataset(self, valid_data, valid_metrics):
        """gbdt.cpp:117-147."""
        if not self.train_data.check_align(valid_data):
            Log.fatal("cannot add validation data, since it has different bin "
                      "mappers with training data")
        updater = ScoreUpdater(valid_data, self.num_class)
        # only this booster's own trees: merged init trees are covered by
        # the valid set's init score (gbdt.cpp:125-129)
        for i in range(self.iter):
            for k in range(self.num_class):
                idx = (i + self.num_init_iteration) * self.num_class + k
                updater.add_score_by_tree(self.models[idx], k)
        self.valid_score_updaters.append(updater)
        self.valid_metrics.append(list(valid_metrics))
        if self.early_stopping_round > 0:
            self.best_iter.append([0] * len(valid_metrics))
            self.best_score.append([K_MIN_SCORE] * len(valid_metrics))
            self.best_msg.append([""] * len(valid_metrics))

    # ------------------------------------------------------------- telemetry
    def _setup_telemetry(self, config):
        """Wire the `telemetry_*` knobs (docs/Observability.md): span ->
        jax.profiler annotation passthrough, the structured run journal
        (rank-suffixed JSONL in `telemetry_dir`), the collective
        sync-wait timing sink, and the opt-in /trainz endpoint.
        Idempotent per booster — a reset_parameter() config rebuild must
        not open a second journal."""
        self.tracer.jax_annotations = bool(
            getattr(config, "telemetry_jax_annotations", False))
        # performance-introspection knobs (read again at close_telemetry;
        # stored so a reset_parameter() rebuild keeps the latest values)
        self._telemetry_trace = bool(getattr(config, "telemetry_trace",
                                             False))
        self._roofline_warn_fraction = float(
            getattr(config, "roofline_warn_fraction", 0.0) or 0.0)
        # quality telemetry works with or without the journal: the
        # split-ledger tracker always feeds the registry gauges
        # (/trainz + Prometheus); `quality` journal records need
        # `telemetry` on too
        if (getattr(config, "quality_telemetry", False)
                and self.quality is None and self.train_data is not None):
            from ..telemetry.quality import QualityTracker
            self.quality = QualityTracker(self.max_feature_idx + 1,
                                          self.feature_names)
        if not getattr(config, "telemetry", False):
            return
        import weakref
        ref = weakref.ref(self)  # process-global sinks and the /trainz
        #                          thread must not pin a dropped booster
        if (self.comm_profile is None
                and getattr(config, "comm_telemetry", True)):
            from ..telemetry.comm_profile import CommProfiler
            self.comm_profile = CommProfiler(rank=faults.current_rank())

        def timing_sink(name, seconds):
            gbdt = ref()
            if gbdt is None:
                # the booster died without close_telemetry (Python-API
                # drop): self-unbind so guarded sections elsewhere in
                # the process go back to the zero-overhead path — if
                # this sink is still being called, it IS the bound one
                heartbeat.bind_timing_sink(None)
                return
            gbdt.metrics.observe("sync_wait_s", seconds)
            if gbdt.comm_profile is not None:
                gbdt.comm_profile.record(name, seconds)

        # collective sync-wait seconds land in the registry + the comm
        # profiler: binding the sink is what makes every guarded
        # section measure, armed watchdog or not
        # (parallel/heartbeat.py)
        heartbeat.bind_timing_sink(timing_sink)
        self._timing_sink_fn = timing_sink
        if self.comm_profile is not None:
            prof = self.comm_profile
            # publish this rank's cumulative collective wait in the
            # heartbeat beats so peers/aggregators compute straggler
            # deltas (comm_profile.straggler_deltas); holds the
            # profiler, not the booster — cleared by close_telemetry
            # and heartbeat.shutdown

            def beat_extra():
                return {"comm_wait_s": round(prof.cum_wait_s, 6)}

            heartbeat.bind_beat_extra(beat_extra)
            self._beat_extra_fn = beat_extra
        if self.journal is None:
            directory = (getattr(config, "telemetry_dir", "")
                         or getattr(config, "snapshot_dir", ""))
            if not directory:
                Log.warning("telemetry=true but neither telemetry_dir "
                            "nor snapshot_dir is set; run journal "
                            "disabled")
            else:
                rank = faults.current_rank()
                self.journal = run_journal.RunJournal(
                    directory, rank=rank,
                    meta={"num_ranks": int(getattr(config, "num_machines",
                                                   1) or 1)})
                run_journal.set_current(self.journal)
                self.tracer.rank = rank
                # distributed tracing (telemetry/disttrace.py): the
                # process-default recorder shares the run journal, so
                # traced canary retrains (LGBM_TPU_TRACE_CTX from a
                # /fleetz-driven comparison) land `trace` records in
                # the same timeline; SpanTracer mirrors its spans into
                # any active context via this recorder
                self._trace_recorder = disttrace.configure(
                    journal=self.journal, rank=rank, service="train",
                    sample_rate=float(getattr(config,
                                              "trace_sample_rate",
                                              0.01) or 0.0),
                    slow_ms=float(getattr(config, "slow_request_ms",
                                          0.0) or 0.0),
                    slow_only=bool(getattr(config, "trace_slow_only",
                                           False)))
                # crash flight recorder (`blackbox` knob): ring +
                # registry + journal tail dumped on watchdog abort
                # (exit 117/118, parallel/heartbeat.py), SIGQUIT, and
                # unhandled serving exceptions
                if getattr(config, "blackbox", True):
                    flight = disttrace.FLIGHT.configure(directory,
                                                        rank=rank)
                    self._flight_armed = flight.enabled
                    tracer, metrics = self.tracer, self.metrics
                    jpath = self.journal.path
                    flight.add_source("spans",
                                      lambda: tracer.recent(None))
                    flight.add_source("metrics", metrics.snapshot)
                    flight.add_source(
                        "journal_tail",
                        lambda: run_journal.tail(jpath, n=20))
                    flight.install_sigquit()
        port = int(getattr(config, "telemetry_port", 0) or 0)
        if port > 0 and self._trainz_server is None:
            from ..telemetry import trainz

            def iteration_fn():
                gbdt = ref()
                return gbdt.iter if gbdt is not None else -1

            def quality_fn():
                gbdt = ref()
                if gbdt is None or gbdt.quality is None:
                    return None
                return gbdt.quality.snapshot()

            def comm_fn():
                gbdt = ref()
                if gbdt is None or gbdt.comm_profile is None:
                    return None
                return gbdt.comm_profile.snapshot()

            self._trainz_server = trainz.start_trainz(
                trainz.build_sources(
                    iteration_fn=iteration_fn,
                    tracer=self.tracer,
                    registry=self.metrics,
                    journal=self.journal,
                    roofline_warn_fraction=self._roofline_warn_fraction,
                    quality_fn=(quality_fn if self.quality is not None
                                else None),
                    comm_fn=(comm_fn if self.comm_profile is not None
                             else None)),
                port=port)

    def _journal_iteration(self, **fields):
        """One journal record per completed iteration (or fused block —
        `block` carries the iteration count it covers); phase seconds
        ride as deltas so summing records reconstructs the run totals
        (bench.py)."""
        if self.journal is None:
            return
        self.journal.iteration(self.iter,
                               phases=self.tracer.delta_snapshot(),
                               **fields)
        self._journal_comm()
        self._journal_introspection()

    def _journal_comm(self):
        """One `comm` record per iteration/block (`comm_telemetry`
        knob): per-collective host-visible waits since the last record,
        the derived comm_overlap_pct, and registry gauges so /trainz +
        Prometheus carry the live values (telemetry/comm_profile.py)."""
        if self.comm_profile is None:
            return
        rec = self.comm_profile.flush(self.iter)
        if rec is None:
            return
        self.metrics.set("comm_overlap_pct", rec["overlap_pct"])
        self.metrics.set("comm_wait_s", rec["wait_s"])
        if self.journal is not None:
            self.journal.event("comm", **rec)

    def _journal_introspection(self):
        """Memory watermarks + newly-recorded jit lowerings, appended at
        every iteration/block boundary (the cadence docs/Observability.md
        documents). The sample is one /proc read + allocator-stats call
        (~microseconds) and the ledger drain hands each compile to the
        journal exactly once, so the boundary cost stays inside the <1%
        telemetry overhead bar (bench telemetry_probe)."""
        from ..telemetry import ledger
        mem = ledger.sample_memory()
        if mem:
            self.journal.event("memory", iteration=int(self.iter), **mem)
            for key, val in mem.items():
                self.metrics.set(key, val)
        for entry in ledger.LEDGER.drain():
            self.journal.event("compile", label=entry["label"] or "jit",
                               seconds=round(entry["seconds"], 6),
                               cache_hit=bool(entry["cache_hit"]))

    def _journal_quality(self):
        """One `quality` record per completed iteration/block
        (`quality_telemetry` knob): the split ledger's deltas
        (splits/gain, top features by gain), the new trees' leaf-value
        distribution, the normalized-gain-importance L1 shift, and the
        latest eval metric values — the model-health timeline the
        serving drift monitor's data-health timeline pairs with.
        Registry gauges (quality_*) update even without a journal so
        /trainz + Prometheus always carry the totals."""
        if self.quality is None:
            return
        delta = self.quality.sync(self.models)
        ledger = self.quality.ledger
        self.metrics.set("quality_trees_total", int(ledger.n_trees))
        self.metrics.set("quality_splits_total", int(ledger.n_splits))
        self.metrics.set("quality_gain_total",
                         float(ledger.gain_sums.sum()))
        top = self.quality.snapshot()["top_features"]
        if top:
            self.metrics.set("quality_top_feature_gain",
                             float(top[0]["gain"]))
        if delta is not None and self.journal is not None:
            if self._last_metric_values:
                delta["values"] = dict(self._last_metric_values)
            self.journal.event("quality", iteration=int(self.iter),
                               **delta)

    @staticmethod
    def _rms(arr):
        a = np.asarray(arr, dtype=np.float64)
        return float(np.sqrt(np.mean(a * a))) if a.size else 0.0

    def finalize_introspection(self):
        """Final introspection drain: last memory/compile records, the
        `telemetry_trace` span-ring dump, the roofline warning. The CLI
        calls it BEFORE writing `run_end` so that record stays the
        timeline's last event; close_telemetry runs it as a fallback
        for the Python-API path (engine/bench write no run_end).
        Once-only."""
        if self.journal is None or getattr(self, "_introspection_done",
                                           False):
            return
        self._introspection_done = True
        self._journal_introspection()
        if getattr(self, "_telemetry_trace", False):
            # the recent-span ring as ONE journal record: the trace
            # exporter (telemetry/export.py) renders it as
            # fine-grained per-thread slices next to the timeline
            self.journal.event("spans",
                               epoch_ts=self.tracer.epoch_wall,
                               spans=self.tracer.recent(n=None))
        self._warn_roofline()

    def close_telemetry(self, merge=False):
        """End-of-run hook: drain the introspection layer (see
        finalize_introspection), close the journal (after an optional
        rank-0 merge) and stop the /trainz thread. Safe to call twice."""
        if self.journal is not None:
            self.finalize_introspection()
            # retire OUR trace recorder first: it shares the journal,
            # so its pending fragments must flush before close. A
            # newer booster's recorder stays installed
            rec = getattr(self, "_trace_recorder", None)
            if rec is not None:
                rec.flush_pending()
                if disttrace.get_recorder() is rec:
                    disttrace.set_recorder(None)
                self._trace_recorder = None
            if getattr(self, "_flight_armed", False):
                disttrace.FLIGHT.disarm()
                self._flight_armed = False
            if merge:
                run_journal.merge_journals(self.journal.directory)
            self.journal.close()
            if run_journal.current() is self.journal:
                run_journal.set_current(None)
            self.journal = None
        if self._trainz_server is not None:
            from ..telemetry import trainz
            trainz.stop_trainz(self._trainz_server)
            self._trainz_server = None
        # drop OUR process-global hooks (a newer booster's stay): an
        # unbound sink returns guarded sections to zero-overhead, and
        # beats must stop publishing a closed booster's frozen
        # comm_wait_s (wrong straggler attribution for peers)
        if (getattr(self, "_timing_sink_fn", None) is not None
                and heartbeat._TIMING_SINK is self._timing_sink_fn):
            heartbeat.bind_timing_sink(None)
        self._timing_sink_fn = None
        if (getattr(self, "_beat_extra_fn", None) is not None
                and heartbeat._BEAT_EXTRA is self._beat_extra_fn):
            heartbeat.bind_beat_extra(None)
        self._beat_extra_fn = None

    def _warn_roofline(self):
        """End-of-run roofline check (`roofline_warn_fraction` knob):
        name every histogram kernel whose live achieved bytes/s fell
        below the configured fraction of the measured STREAM peak."""
        frac = getattr(self, "_roofline_warn_fraction", 0.0)
        if frac <= 0:
            return
        from ..telemetry import roofline
        snap = roofline.TABLE.snapshot(warn_fraction=frac)
        for name, k in (snap.get("kernels") or {}).items():
            if k.get("below_peak_fraction"):
                Log.warning(
                    "roofline: kernel [%s] achieved %.2f GB/s = %.1f%% "
                    "of the %.2f GB/s STREAM peak (< %.0f%% warn "
                    "fraction; %d calls, %.3fs)", name,
                    k["bytes_per_s"] / 1e9, k.get("pct_of_peak", 0.0),
                    snap["peak_bytes_per_s"] / 1e9, 100.0 * frac,
                    k["calls"], k["seconds"])

    # --------------------------------------------------------------- bagging
    def _bagging_device_fn(self):
        """(iter, grad, hess) -> (M,) in-bag mask, fully in-graph —
        record- or query-unit bagging (gbdt.cpp:150-201) with an exact
        bag count via jax.random.permutation, keyed on
        (bagging_seed, iter // bagging_freq) so re-bagging happens at
        the reference's cadence and the fused scan and per-iteration
        loop draw identical bags. Returns None when bagging is off."""
        cfg = self.config
        if not (cfg.bagging_fraction < 1.0 and cfg.bagging_freq > 0):
            return None
        if getattr(self, "_bag_fn", None) is not None:
            return self._bag_fn
        n = self.num_data
        meta = self.train_data.metadata
        key = jax.random.PRNGKey(cfg.bagging_seed)
        freq = int(cfg.bagging_freq)
        qb = meta.query_boundaries
        if qb is None:
            bag_cnt = int(cfg.bagging_fraction * n)

            def fn(it, gradients=None, hessians=None):
                k = jax.random.fold_in(key, it // freq)
                mask = (jax.random.permutation(k, n) < bag_cnt)
                mask = mask.astype(jnp.float32)
                m = None if gradients is None else gradients.shape[-1]
                if m is not None and m > n:
                    mask = jnp.pad(mask, (0, m - n))
                return mask
        else:
            nq = len(qb) - 1
            bag_q = int(nq * cfg.bagging_fraction)
            row_q = np.searchsorted(np.asarray(qb), np.arange(n),
                                    side="right") - 1
            row_q_dev = jnp.asarray(row_q, jnp.int32)

            def fn(it, gradients=None, hessians=None):
                k = jax.random.fold_in(key, it // freq)
                qmask = (jax.random.permutation(k, nq) < bag_q)
                mask = jnp.take(qmask.astype(jnp.float32), row_q_dev)
                m = None if gradients is None else gradients.shape[-1]
                if m is not None and m > n:
                    mask = jnp.pad(mask, (0, m - n))
                return mask

        self._bag_fn = fn
        return fn

    def _bagging(self, it, gradients=None, hessians=None):
        """gbdt.cpp:150-201; returns in-bag float mask or None.
        gradients/hessians are provided for gradient-based sampling
        strategies (models/goss.py); plain bagging ignores them."""
        fn = self._bagging_device_fn()
        if fn is None:
            return None
        # cache keyed by the re-bag window (fused blocks and rollbacks
        # can move self.iter across windows between sequential calls)
        window = it // self.config.bagging_freq
        if window == self._bag_window and self._bag_rows is not None:
            return self._bag_rows
        mask = np.asarray(fn(jnp.int32(it)))[:self.num_data]
        Log.debug("Re-bagging, using %d data to train", int(mask.sum()))
        self._bag_rows = mask
        self._bag_window = window
        return mask

    # -------------------------------------------------------------- training
    def train_one_iter(self, gradients=None, hessians=None, is_eval=True):
        """gbdt.cpp:210-245. Returns True if training should stop."""
        faults.crash_if_reached(self.iter)
        faults.rank_crash_if_reached(self.iter)
        faults.rank_hang_if_reached(self.iter)
        heartbeat.WATCHDOG.set_iteration(self.iter)
        if gradients is None or hessians is None:
            if self.objective is None:
                Log.fatal("No object function provided")
            with self.tracer.phase("gradients"):
                gradients, hessians = self.objective.get_gradients(
                    self._score_for_boosting())
        else:
            gradients = np.asarray(gradients, dtype=np.float32).reshape(
                self.num_class, self.num_data)
            hessians = np.asarray(hessians, dtype=np.float32).reshape(
                self.num_class, self.num_data)
        gradients, hessians = faults.poison_gradients_if_armed(
            self.iter, gradients, hessians)
        policy = getattr(self.config, "nonfinite_guard", "raise")
        if policy != "off":
            gradients, hessians, skip = guardrails.guard_gradients(
                gradients, hessians, self.iter, policy)
            if skip:
                # round skipped: no tree appended and self.iter does NOT
                # advance (the model list must stay iter*num_class long).
                # Callers loop over a bounded round count, so a
                # persistently-poisoned objective stalls progress but
                # cannot loop forever.
                return False
        with self.tracer.phase("bagging"):
            inbag = self._bagging(self.iter, gradients, hessians)
        n = self.num_data
        multi_host = getattr(self.tree_learner, "n_proc", 1) > 1
        linear = bool(getattr(self.config, "linear_tree", False))
        new_leaves = 0
        for k in range(self.num_class):
            with self.tracer.phase("build"):
                out = self.tree_learner.train_device(
                    gradients[k], hessians[k], inbag)
            self.metrics.inc("tree_build_dispatches")
            if linear:
                # the split search fixed the STRUCTURE; now refit every
                # eligible leaf as a ridge model over its path features
                # (models/linear_leaves.py). This path is host-synced by
                # construction — the fit needs the partition and the
                # gradients on host — so laziness buys nothing here.
                with self.tracer.phase("host_sync"), \
                        heartbeat.collective_guard("leaf_count_sync"):
                    tree, lin_values = self._fit_linear_tree(
                        out, gradients[k], hessians[k], inbag)
                with self.tracer.phase("score_upd"):
                    self.train_score_updater.add_score_by_values(
                        lin_values * self.shrinkage_rate, k)
                    for updater in self.valid_score_updaters:
                        updater.add_score_by_tree(tree, k)
                stopped = tree.num_leaves <= 1
            else:
                # enqueue ALL device work for this class before the scalar
                # stop check: train scores via partition gather (covers
                # in-bag AND out-of-bag rows: the partition is computed
                # over all rows, the bag mask only gates the histogram
                # statistics), then valid scores via device bin-space
                # traversal. A 0-split tree makes every update a no-op
                # (leaf values are all zero), so checking afterwards is
                # safe.
                tree = LazyTree(out, self.tree_learner,
                                shrink=self.shrinkage_rate)
                with self.tracer.phase("score_upd"):
                    self.train_score_updater.add_score_by_partition(
                        self.tree_learner.local_leaf_values(out)
                        * self.shrinkage_rate,
                        self.tree_learner.local_row_leaf(out, n), k)
                    for updater in self.valid_score_updaters:
                        if multi_host:
                            # device-tree traversal would mix global and
                            # local arrays; materialize once and score on
                            # host
                            updater.add_score_by_tree(tree, k)
                        else:
                            updater.add_score_by_device_tree(
                                out, self.shrinkage_rate, k)
                with self.tracer.phase("host_sync"), \
                        heartbeat.collective_guard("leaf_count_sync"):
                    stopped = tree.num_leaves <= 1  # scalar sync: only wait
            # collective-byte ledger: the meshed learners' wire plan is
            # root + per-split x n_splits (parallel/mesh.py CommPlan);
            # n_splits is on host from the sync above, so the counters
            # advance exactly once per tree — including 0-split trees,
            # whose root exchange still moved bytes
            account = getattr(self.tree_learner,
                              "account_tree_collectives", None)
            if account is not None:
                account(tree.num_leaves - 1)
            if stopped:
                Log.info("Stopped training because there are no more leafs "
                         "that meet the split requirements.")
                return True
            new_leaves += tree.num_leaves
            self.models.append(tree)
        self.iter += 1
        self.metrics.inc("leaves_total", new_leaves)
        self.metrics.set("iteration", self.iter)
        if self.journal is not None:
            # norms are the per-iteration training-health proxy (a NaN
            # storm or divergence is visible before the guardrails
            # fire); np transfer is (K, N) f32, telemetry-gated.
            # Learners with per-iteration IO telemetry (the out-of-core
            # streaming learner's prefetch deltas) ride along through
            # the journal_fields hook.
            fields_fn = getattr(self.tree_learner, "journal_fields", None)
            extra = fields_fn() if callable(fields_fn) else {}
            self._journal_iteration(grad_norm=self._rms(gradients),
                                    hess_norm=self._rms(hessians),
                                    leaf_count=int(new_leaves),
                                    **(extra or {}))
        self._journal_quality()
        if is_eval:
            with self.tracer.phase("eval"):
                return self.eval_and_check_early_stopping()
        return False

    def _score_for_boosting(self):
        """Hook for DART's tree-dropping (dart.hpp GetTrainingScore)."""
        return self.train_score_updater.score

    def _fit_linear_tree(self, out, grad, hess, inbag):
        """Materialize the builder's tree and refit its leaves as ridge
        models (models/linear_leaves.py, docs/Linear-Trees.md).

        Returns (tree, values): the SHRUNK materialized tree and the
        UNSHRUNK per-row (N,) f64 outputs (the caller applies the
        learning rate to the score delta, mirroring the constant path's
        `leaf_values * shrinkage_rate`). The fit runs in unshrunk value
        space and the whole model block scales multiplicatively, so
        shrinkage/DART semantics match constant leaves exactly."""
        from .linear_leaves import fit_linear_leaves, leaf_path_features
        learner = self.tree_learner
        n = self.num_data
        tree = learner._to_host_tree(out, shrink=1.0)
        if tree.num_leaves <= 1:
            tree.shrinkage(self.shrinkage_rate)
            return tree, np.zeros(n, np.float64)
        row_leaf = np.asarray(learner.local_row_leaf(out, n))
        feats = leaf_path_features(
            tree.split_feature, tree.left_child, tree.right_child,
            tree.leaf_parent, tree.num_leaves,
            self.config.linear_max_features)
        chunks, bin_values, fit_chunk = learner.linear_fit_context()
        const, coeffs, is_lin, values = fit_linear_leaves(
            feats, tree.leaf_value, tree.leaf_count, bin_values,
            row_leaf, np.asarray(grad)[:n], np.asarray(hess)[:n],
            None if inbag is None else np.asarray(inbag)[:n],
            chunks, fit_chunk, self.config.linear_lambda)
        if is_lin.any():
            tree.set_linear(const, coeffs, is_lin, feats,
                            learner.train_set.real_feature_idx)
        tree.shrinkage(self.shrinkage_rate)
        return tree, values

    # ------------------------------------------------- fused multi-iteration
    # TPU-first: when nothing in an iteration needs the host (no bagging,
    # no per-iteration metric output, binary/regression with a jitted
    # gradient), the ENTIRE boosting block — gradients, tree build, score
    # update — runs as ONE XLA program: a lax.scan over iterations. The
    # host's only job is to feed the per-iteration feature-fraction masks
    # (same RNG stream as the sequential path) and pull the stacked tree
    # arrays once at the end. The reference's C++ hot loop
    # (gbdt.cpp:210-245) keeps everything in-process; this keeps
    # everything in-graph.

    def _fused_boosting_ok(self):
        """Whether this boosting type's per-iteration logic is pure
        in-graph work. DART's tree dropping mutates the model list on
        host; GOSS overrides this (its sampling runs in-graph via
        _fused_inbag_fn)."""
        return type(self).__name__ == "GBDT"

    def _fused_inbag_fn(self):
        """Optional (iter, grad, hess) -> (N_pad,) in-bag weights hook
        for the fused scan (grad/hess are (K, N_pad) padded); None =
        constant all-ones. The caller masks padding rows afterwards.
        Plain bagging fuses via its in-graph mask; GOSS overrides."""
        return self._bagging_device_fn()

    def _fused_eligible(self, ignore_train_metrics=False):
        """ignore_train_metrics=True answers "could this train fused in
        metric_freq-sized blocks, with metric output (and valid-set
        score catch-up from the block's materialized trees) between
        blocks?" (the CLI uses it, application.py train)."""
        cfg = self.config
        if cfg is None or self.objective is None:
            return False
        return (self._fused_boosting_ok()
                and (not self.valid_score_updaters or ignore_train_metrics)
                and (cfg.metric_freq <= 0 or not self.training_metrics
                     or ignore_train_metrics)
                and self.early_stopping_round <= 0
                and getattr(self.objective, "_grad", None) is not None
                # linear leaves refit on host AFTER each structure, and
                # the refit changes the residuals the next iteration
                # sees — the scan cannot bake that in. train_many falls
                # back to the per-iteration loop transparently.
                and not bool(getattr(cfg, "linear_tree", False))
                and type(self.tree_learner).__name__ == "SerialTreeLearner")

    def _get_fused_fn(self, num_iters):
        if not hasattr(self, "_fused_cache"):
            self._fused_cache = {}
        learner_shapes = (self.tree_learner.num_data, self.tree_learner.n_pad,
                          self.tree_learner.f_pad)
        key = (num_iters, float(self.shrinkage_rate), id(self.tree_learner),
               learner_shapes, id(self.objective))
        if key in self._fused_cache:
            return self._fused_cache[key]
        learner = self.tree_learner
        n, n_pad = learner.num_data, learner.n_pad
        pad = n_pad - n
        core = learner._build_core
        shrink = jnp.float32(self.shrinkage_rate)
        # every data-dependent array rides as a runtime ARGUMENT of the
        # compiled program, not a closure: closed-over arrays embed
        # their VALUES in the lowered HLO, so two runs with (say)
        # different labels would hash to different persistent-cache
        # entries and recompile. With the operands as arguments the
        # program bytes depend only on shapes/dtypes — one lowered
        # executable per (shape bucket, config) per machine.
        grad_pure = getattr(self.objective, "_grad_pure", None)
        data = {
            "bins": learner._bins,
            "nbpf": learner._num_bin_pf,
            "iscat": learner._is_cat,
            "inbag": jnp.concatenate([jnp.ones(n, jnp.float32),
                                      jnp.zeros(pad, jnp.float32)]),
        }
        if grad_pure is not None:
            data["gops"] = self.objective._grad_ops
        else:
            grad_fn = self.objective._grad  # closure fallback

        # the fused program embeds the learner's builder: resolve THIS
        # learner's hist_mode for the trace (a sibling Booster may have
        # moved the process global since learner init)
        learner.apply_hist_mode()
        num_class = self.num_class
        # both the partitioned and the gather-compacted builders dispatch
        # histogram work through a bucketed lax.switch: vmapping them
        # over the class axis would execute EVERY bucket branch per
        # split, so those cores scan classes instead
        use_switch_core = (getattr(learner, "_use_partitioned", False)
                           or getattr(learner, "_use_compact", False))
        inbag_fn = self._fused_inbag_fn()

        def fused(score, fmasks, iters, d):
            bins, nbpf, iscat, inbag = (d["bins"], d["nbpf"], d["iscat"],
                                        d["inbag"])

            def step(score, xs):
                fmask, it = xs  # fmask: (K, F) — one mask PER CLASS
                # TREE, matching the sequential path's per-tree feature
                # sampling (serial_tree_learner.cpp:160-165)
                if grad_pure is not None:
                    g, h = grad_pure(d["gops"], score)
                else:
                    g, h = grad_fn(score)
                gp = jnp.pad(g, ((0, 0), (0, pad)))
                hp = jnp.pad(h, ((0, 0), (0, pad)))
                # per-iteration in-bag weights (GOSS); pad rows stay zero
                ib = (inbag if inbag_fn is None
                      else inbag_fn(it, gp, hp) * inbag)
                if num_class == 1:
                    out = core(bins, gp[0], hp[0], ib, fmask[0], nbpf,
                               iscat)
                    upd = jnp.take(out["leaf_value"],
                                   out["row_leaf"][:n])[None, :]
                elif not use_switch_core:
                    # one device program for ALL classes: vmap the
                    # whole-tree builder over the class axis (SURVEY M2;
                    # the reference loops classes serially,
                    # gbdt.cpp:210-245)
                    out = jax.vmap(
                        lambda gg, hh, fm: core(bins, gg, hh, ib, fm,
                                                nbpf, iscat))(gp, hp, fmask)
                    upd = jax.vmap(
                        lambda lv, rl: jnp.take(lv, rl[:n]))(
                            out["leaf_value"], out["row_leaf"])
                else:
                    # partitioned/compacted builder: scan the class axis
                    # instead of vmap — vmapping the bucketed lax.switch
                    # would execute EVERY bucket branch per split; scan
                    # keeps one branch per class (still a single
                    # compiled program, matching the reference's
                    # sequential class loop)
                    def class_step(_, gh):
                        gg, hh, fm = gh
                        o = core(bins, gg, hh, ib, fm, nbpf, iscat)
                        u = jnp.take(o["leaf_value"], o["row_leaf"][:n])
                        return None, (o, u)

                    _, (out, upd) = jax.lax.scan(class_step, None,
                                                 (gp, hp, fmask))
                score = score + upd * shrink
                del out["row_leaf"]  # keep the ys O(iter * num_leaves)
                return score, out

            return jax.lax.scan(step, score, (fmasks, iters))

        score = self.train_score_updater.score
        fmasks = jnp.ones((num_iters, num_class, learner.f_pad), dtype=bool)
        iters = jnp.arange(num_iters, dtype=jnp.int32)
        from ..config import compile_cache_hits
        from ..telemetry.ledger import LEDGER
        hits_before = compile_cache_hits()
        # the compile ledger attributes this lowering to its shape
        # bucket — the fused scan length is what keys recompiles.
        # 1-core/1-device runners deadlock embedded host callbacks
        # (ops/histogram.py host_callbacks_hazardous; our entry points
        # clear the hazard by forcing a second virtual device, see
        # utils/hostenv) — trace on the segment kernel as a last
        # resort so library users there terminate instead of hanging
        from ..ops import histogram as hist_ops
        guard = (hist_ops.callbacks_disabled
                 if hist_ops.host_callbacks_hazardous()
                 else contextlib.nullcontext)
        with LEDGER.label(f"fused_scan_{num_iters}it"), guard():
            compiled = jax.jit(fused).lower(score, fmasks, iters,
                                            data).compile()
        # whether the persistent compile cache served this lowering —
        # surfaced by bench.py as phases.compile_cache_hit
        self.last_compile_cache_hit = compile_cache_hits() > hits_before
        if self.last_compile_cache_hit:
            # counted HERE, once per actual lowering — blocks reusing
            # the in-process runner never touch the persistent cache
            self.metrics.inc("compile_cache_hits")

        def runner(score, fmasks, iters):
            return compiled(score, fmasks, iters, data)

        self._fused_cache[key] = runner
        return runner

    def warm_up_fused(self, num_iters):
        """Pre-compile the fused trainer (compile time is not training
        time, same as the reference's ahead-of-time C++ build)."""
        if self._fused_eligible():
            self._get_fused_fn(num_iters)
            return True
        return False

    def _run_fused_block(self, num_iters):
        """Run ONE fused scan of `num_iters` iterations and append the
        materialized trees. Returns (stacked_device, t_eff, k_stop,
        n_before): the block's stacked tree arrays still on device (for
        snapshot traversal), the number of full iterations kept, the
        partial-class count at a natural stop, and the model-list length
        before the block. The train score is set to the scan's final
        score (which, at a natural stop, still includes discarded
        trees — callers fix that up)."""
        # a fused block is ONE device program: a preemption anywhere
        # inside it loses the whole block, which is exactly what
        # crashing at its launch models (utils/faults.py)
        faults.crash_if_reached(self.iter, num_iters)
        faults.rank_crash_if_reached(self.iter, num_iters)
        faults.rank_hang_if_reached(self.iter, num_iters)
        heartbeat.WATCHDOG.set_iteration(self.iter)
        fn = self._get_fused_fn(num_iters)
        learner = self.tree_learner
        # same RNG stream and consumption order as the sequential path:
        # one mask per (iteration, class) tree
        fmasks = jnp.asarray(np.stack(
            [[learner._sample_features() for _ in range(self.num_class)]
             for _ in range(num_iters)]))
        iters = jnp.arange(self.iter, self.iter + num_iters, dtype=jnp.int32)
        # the whole block is one device program; its host-side waits
        # (score pull, stacked-tree transfer) are THE block-boundary
        # sync points the collective watchdog brackets
        with self.tracer.phase("fused_block", iterations=num_iters), \
                heartbeat.collective_guard("fused_block"):
            final_score, stacked = fn(self.train_score_updater.score,
                                      fmasks, iters)
            self.train_score_updater.score = final_score
            policy = getattr(self.config, "nonfinite_guard", "raise")
            if policy != "off":
                # in-graph iterations cannot be guarded individually;
                # the block boundary is where divergence becomes
                # detectable
                guardrails.guard_scores(np.asarray(final_score),
                                        self.iter + num_iters, policy)
            host = jax.device_get(stacked)  # ONE transfer for the block
        nsp = np.asarray(host["n_splits"]).reshape(num_iters, -1)  # (T, K)
        empty = (nsp == 0).any(axis=1)
        t_eff = int(np.argmax(empty)) if bool(empty.any()) else num_iters
        # classes BEFORE the first empty one in the stopping iteration are
        # kept, matching the sequential path (gbdt.cpp:222-236 push_back
        # each class tree until the empty one)
        k_stop = (int(np.argmax(nsp[t_eff] == 0))
                  if t_eff < num_iters else 0)

        def slice_at(t, k):
            if self.num_class == 1:
                return {key: v[t] for key, v in host.items()}
            return {key: v[t, k] for key, v in host.items()}

        n_before = len(self.models)
        for t in range(t_eff):
            for k in range(self.num_class):
                self.models.append(learner.host_out_to_tree(
                    slice_at(t, k), shrink=self.shrinkage_rate))
        if t_eff < num_iters:
            for k in range(k_stop):
                self.models.append(learner.host_out_to_tree(
                    slice_at(t_eff, k), shrink=self.shrinkage_rate))
        self.iter += t_eff
        self.metrics.inc("tree_build_dispatches",
                         len(self.models) - n_before)
        self.metrics.inc("transfer_bytes",
                         sum(np.asarray(v).nbytes for v in host.values()))
        self.metrics.set("iteration", self.iter)
        if self.journal is not None and t_eff > 0:
            # per-iteration host phases do not exist inside one XLA
            # program: the block record covers its t_eff iterations
            self._journal_iteration(
                block=int(t_eff), fused=True,
                compile_cache_hit=bool(self.last_compile_cache_hit))
        self._journal_quality()
        return stacked, t_eff, k_stop, n_before

    def _natural_stop_score_exact(self):
        """At a natural stop (an empty tree mid-block), whether the
        scan's final score is already exact: constant in-bag weights and
        feature masks keep gradients unchanged, so every discarded tree
        was empty and added zero score."""
        return (self.num_class == 1 and self._fused_inbag_fn() is None
                and self.config.feature_fraction >= 1.0)

    def _rebuild_train_score_from_models(self):
        """Recompute the train score from the kept model list (used when
        a natural stop discards scan iterations whose score
        contributions were not zero)."""
        self.train_score_updater = ScoreUpdater(self.train_data,
                                                self.num_class)
        # skip merged/loaded init trees: the fresh updater's init
        # score already covers them (reset_training_data replays the
        # same range)
        first = self.num_init_iteration * self.num_class
        for idx in range(first, len(self.models)):
            self.train_score_updater.add_score_by_tree(
                self.models[idx], idx % self.num_class)

    def train_many(self, num_iters, ignore_train_metrics=False):
        """Train `num_iters` boosting iterations; uses the fused in-graph
        scan when eligible, else the per-iteration loop. Returns True if
        training stopped early. ignore_train_metrics runs the scan even
        with training metrics attached (the caller prints between
        blocks; application.py train)."""
        if num_iters <= 0:
            return False
        if not self._fused_eligible(ignore_train_metrics):
            for _ in range(num_iters):
                if self.train_one_iter():
                    return True
            return False
        _, t_eff, _, n_before = self._run_fused_block(num_iters)
        # valid scores stay in sync with the model list no matter who
        # called (the scan only carries TRAIN scores): one batched
        # update per valid set for the whole block
        if self.valid_score_updaters and len(self.models) > n_before:
            # n_before is a multiple of num_class (partial-class appends
            # only happen when training ends), so the slice is class-major
            new_trees = self.models[n_before:]
            for updater in self.valid_score_updaters:
                updater.add_score_by_trees(new_trees, self.num_class)
        if t_eff < num_iters:
            Log.info("Stopped training because there are no more leafs "
                     "that meet the split requirements.")
            if self._natural_stop_score_exact():
                return True
            # multiclass (classes after k_stop kept learning) or
            # per-iteration bag/feature sampling (a later sample can
            # split again): the scan's score includes discarded trees —
            # rebuild from the kept trees so booster state matches the
            # model list
            self._rebuild_train_score_from_models()
            return True
        return False

    def train_many_eval(self, num_iters):
        """Fused block + per-iteration score snapshots for metric replay
        (the engine's valid+early-stopping fast path: gbdt.cpp:210-349
        interleaves build and eval per iteration; here the whole block
        builds in ONE device program and the per-iteration valid/train
        scores are reconstructed afterwards from the block's stacked
        tree arrays by one vmapped device traversal per dataset chunk).

        Returns (t_eff, snapshots). Caller contract (engine.train):
        - walk t = 0..t_eff-1 forward, calling
          snapshots.set_scores_at(t) before evaluating metrics;
        - on an early-stop break at t: snapshots.set_scores_at(t,
          with_train=True) then snapshots.drop_tail_to(t);
        - on a completed walk: snapshots.finalize() — returns True at
          a natural stop (an empty tree ended the block early).
        Requires _fused_eligible(ignore_train_metrics=True)."""
        base_train = self.train_score_updater.score
        base_valids = [u.score for u in self.valid_score_updaters]
        stacked, t_eff, k_stop, n_before = self._run_fused_block(num_iters)
        snap = _BlockSnapshots(self, stacked, base_train, base_valids,
                               t_eff, n_before, k_stop,
                               natural_stop=t_eff < num_iters)
        return t_eff, snap


    def rollback_one_iter(self):
        """gbdt.cpp:247-264. Indexes from the end of the model list so it
        stays valid after early-stopping truncation."""
        if self.iter == 0 or len(self.models) < self.num_class:
            return
        for k in range(self.num_class):
            tree = self.models[-self.num_class + k]
            tree.shrinkage(-1.0)
            self.train_score_updater.add_score_by_tree(tree, k)
            for updater in self.valid_score_updaters:
                updater.add_score_by_tree(tree, k)
        del self.models[-self.num_class:]
        self.iter -= 1
        if self.quality is not None:
            # snap the split ledger to the surviving trees NOW: a
            # retrained iteration restores the old list LENGTH, which
            # a later length-only sync could not tell from no change
            self.quality.sync(self.models)

    # ------------------------------------------------------------ evaluation
    def eval_and_check_early_stopping(self):
        """gbdt.cpp:266-281. Unlike the reference (which only pops the model
        list), the dropped trees' score contributions are also subtracted so
        the booster state stays consistent for rollback / continued use."""
        best_msg = self.output_metric(self.iter)
        if best_msg:
            Log.info("Early stopping at iteration %d, the best iteration round is %d",
                     self.iter, self.iter - self.early_stopping_round)
            Log.info("Output of best iteration round:\n%s", best_msg)
            self._truncate_iters(self.early_stopping_round)
            return True
        return False

    def _truncate_iters(self, k):
        """Drop the last k iterations, subtracting their score contributions
        in one batched pass per dataset (the reference only pops the model
        list, gbdt.cpp:271-279, leaving scores stale)."""
        k = min(k, self.iter)
        if k <= 0:
            return
        dropped = self.models[-k * self.num_class:]
        del self.models[-k * self.num_class:]
        self.iter -= k
        for updater in [self.train_score_updater] + self.valid_score_updaters:
            updater.sub_score_by_trees(dropped, self.num_class)
        if self.journal is not None:
            self.journal.event("truncate", iteration=int(self.iter),
                               dropped_iters=int(k), reason="early_stop")
        self._journal_quality()  # snap the split ledger to the kept trees

    def output_metric(self, it):
        """gbdt.cpp:292-349: print metrics, track early stopping."""
        need_output = self.config is not None and self.config.metric_freq > 0 \
            and (it % self.config.metric_freq) == 0
        ret = ""
        msg_lines = []
        met_pairs = []
        met_values = {}
        if need_output:
            for metric in self.training_metrics:
                scores = metric.eval(self.train_score_updater.host_score())
                for name, sc in zip(metric.names, scores):
                    line = f"Iteration:{it}, training {name} : {sc:g}"
                    Log.info("%s", line)
                    met_values[f"training {name}"] = float(sc)
                    if self.early_stopping_round > 0:
                        msg_lines.append(line)
        if need_output or self.early_stopping_round > 0:
            for i, metrics in enumerate(self.valid_metrics):
                for j, metric in enumerate(metrics):
                    scores = metric.eval(self.valid_score_updaters[i].host_score())
                    for name, sc in zip(metric.names, scores):
                        line = f"Iteration:{it}, valid_{i + 1} {name} : {sc:g}"
                        met_values[f"valid_{i + 1} {name}"] = float(sc)
                        if need_output:
                            Log.info("%s", line)
                        if self.early_stopping_round > 0:
                            msg_lines.append(line)
                    if not ret and self.early_stopping_round > 0:
                        cur = metric.factor_to_bigger_better * scores[-1]
                        if cur > self.best_score[i][j]:
                            self.best_score[i][j] = cur
                            self.best_iter[i][j] = it
                            met_pairs.append((i, j))
                        elif it - self.best_iter[i][j] >= self.early_stopping_round:
                            ret = self.best_msg[i][j]
        msg = "\n".join(msg_lines)
        for i, j in met_pairs:
            self.best_msg[i][j] = msg
        if met_values:
            # latest eval values ride the next `quality` record too
            # (per-iteration eval metrics in the model-health timeline)
            self._last_metric_values = met_values
        if self.journal is not None and met_values:
            # metric values (train loss/AUC/...) in the same timeline as
            # the iteration records they describe
            self.journal.event("metrics", iteration=int(it),
                               values=met_values)
        return ret

    def get_eval_at(self, data_idx):
        """gbdt.cpp:352-373. 0 = train, i+1 = valid i."""
        out = []
        if data_idx == 0:
            for metric in self.training_metrics:
                out.extend(metric.eval(self.train_score_updater.host_score()))
        else:
            for metric in self.valid_metrics[data_idx - 1]:
                out.extend(metric.eval(self.valid_score_updaters[data_idx - 1].host_score()))
        if out:
            # latest eval values ride the next `quality` record (the
            # Python-API eval path; the CLI path lands here via
            # output_metric's own loop)
            prefix = "training" if data_idx == 0 else f"valid_{data_idx}"
            self._last_metric_values.update(
                {f"{prefix} {n}": float(v)
                 for n, v in zip(self.get_eval_names(data_idx), out)})
        return out

    def get_eval_names(self, data_idx):
        metrics = (self.training_metrics if data_idx == 0
                   else self.valid_metrics[data_idx - 1])
        names = []
        for m in metrics:
            names.extend(m.names)
        return names

    def get_predict_at(self, data_idx):
        """gbdt.cpp:381-419: transformed per-row predictions of a bound dataset."""
        if data_idx == 0:
            updater = self.train_score_updater
        else:
            updater = self.valid_score_updaters[data_idx - 1]
        raw = updater.host_score()
        n = updater.num_data
        if self.num_class > 1:
            mat = raw.reshape(self.num_class, n).T
            p = common.softmax(mat, axis=1)
            return p.T.reshape(-1)
        if self.sigmoid > 0:
            return 1.0 / (1.0 + np.exp(-2.0 * self.sigmoid * raw))
        return raw

    def get_training_score(self):
        return self.train_score_updater.host_score()

    # ------------------------------------------------------------ prediction
    def _num_used_models(self, num_iteration=-1):
        total = len(self.models)
        if num_iteration > 0:
            return min(num_iteration * self.num_class, total)
        if self.num_iteration_for_pred > 0 and not self.train_data:
            return min(self.num_iteration_for_pred * self.num_class, total)
        return total

    def _stacked_model_arrays(self, n_used):
        """Pad all trees' arrays to one (T, ...) tensor set so prediction
        traverses EVERY tree at once (the reference parallelizes file
        prediction across rows with OpenMP, predictor.hpp:82-130; here
        the tree axis is vectorized too). Cached per model-list state."""
        key = (n_used, len(self.models),
               getattr(self.models, "version", -1))
        cached = getattr(self, "_stack_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        trees = [self.models[i].materialize()
                 if hasattr(self.models[i], "materialize") else self.models[i]
                 for i in range(n_used)]
        max_l = max(t.num_leaves for t in trees)
        t_cnt = len(trees)
        sf = np.zeros((t_cnt, max(max_l - 1, 1)), np.int32)
        thr = np.zeros_like(sf, dtype=np.float64)
        dt = np.zeros_like(sf, dtype=np.int8)
        lc = np.full_like(sf, ~0)
        rc = np.full_like(sf, ~0)
        lv = np.zeros((t_cnt, max_l), np.float64)
        has_split = np.zeros(t_cnt, bool)
        depth = 1
        for i, t in enumerate(trees):
            ns = t.num_leaves - 1
            if ns > 0:
                sf[i, :ns] = t.split_feature_real
                thr[i, :ns] = t.threshold
                dt[i, :ns] = t.decision_type
                lc[i, :ns] = t.left_child
                rc[i, :ns] = t.right_child
                has_split[i] = True
                depth = max(depth, t.max_depth)
            lv[i, :t.num_leaves] = t.leaf_value
        stacked = (sf, thr, dt, lc, rc, lv, has_split, depth)
        self._stack_cache = (key, stacked)
        return stacked

    def _stacked_linear_arrays(self, n_used):
        """Per-leaf linear-model arrays stacked across the first n_used
        trees, or None when none is linear: (const (T, L) f64,
        coeff (T, L, C) f64, feat (T, L, C) int32 real column ids,
        cnt (T, L) int32) with L matching _stacked_model_arrays' leaf
        axis and C the widest leaf model in the ensemble. Constant
        leaves (and whole constant trees) carry cnt 0 and zero rows, so
        a fused serving kernel can branch per (row, tree) lane on
        cnt > 0 alone (serving/compiled_model.py)."""
        lin_idx = set(self._linear_model_indices(n_used))
        if not lin_idx:
            return None
        trees = [self.models[i].materialize()
                 if hasattr(self.models[i], "materialize")
                 else self.models[i] for i in range(n_used)]
        max_l = max(t.num_leaves for t in trees)
        width = max(t.leaf_coeff.shape[1] for i, t in enumerate(trees)
                    if i in lin_idx)
        const = np.zeros((n_used, max_l), np.float64)
        coeff = np.zeros((n_used, max_l, width), np.float64)
        feat = np.zeros((n_used, max_l, width), np.int32)
        cnt = np.zeros((n_used, max_l), np.int32)
        for i, t in enumerate(trees):
            if i not in lin_idx:
                continue
            nl, c = t.num_leaves, t.leaf_coeff.shape[1]
            const[i, :nl] = t.leaf_const
            coeff[i, :nl, :c] = t.leaf_coeff
            feat[i, :nl, :c] = t.leaf_coeff_feat
            cnt[i, :nl] = t.leaf_coeff_count
        return const, coeff, feat, cnt

    # rows*trees above this run the jitted device traversal (the
    # reference parallelizes prediction with OpenMP, predictor.hpp:82-130;
    # here rows AND trees vectorize on device, class reduction on the MXU).
    # Class-level defaults; `device_predict_cells` / `host_traverse_cells`
    # config knobs override per booster (reset_training_data), and the
    # `device_predict` knob / LIGHTGBM_TPU_DEVICE_PREDICT env flag force
    # the path outright (docs/Parameters.md).
    DEVICE_PREDICT_CELLS = 20_000_000
    # single-dispatch (lax.map) predict when the padded f32 input fits
    # this budget; beyond it, per-block dispatches bound device memory
    DEVICE_PREDICT_INPUT_MAX = 2 << 30
    _PREDICT_BLOCK = 65_536
    # host-path (rows x trees) cells per traversal block (peak memory)
    _HOST_TRAVERSE_CELLS = 4_000_000

    def _device_model(self, n_used):
        """Stacked tree arrays placed on device (f32/int32), cached per
        model-list state."""
        key = (n_used, len(self.models),
               getattr(self.models, "version", -1))
        cached = getattr(self, "_dev_model_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        sf, thr, dt, lc, rc, lv, has_split, depth = \
            self._stacked_model_arrays(n_used)
        # numeric thresholds are f64 on the host path; see
        # f32_safe_thresholds for the round-toward--inf cast contract
        thr32 = f32_safe_thresholds(thr, dt)
        dev = (jnp.asarray(sf), jnp.asarray(thr32, jnp.float32),
               jnp.asarray(dt == Tree.CATEGORICAL),
               jnp.asarray(lc), jnp.asarray(rc),
               jnp.asarray(lv, jnp.float32),
               jnp.asarray(np.where(has_split, 0, ~0).astype(np.int32)),
               int(depth))
        self._dev_model_cache = (key, dev)
        return dev

    @staticmethod
    @functools.partial(jax.jit, static_argnums=(9,))
    def _predict_block_device(xb, sf, thr, cat, lc, rc, lv, node0,
                              cls_onehot, depth):
        """(B, F) raw f32 rows -> (B, K) class sums: the lockstep
        traversal (device_traverse; NaN routes right on BOTH numeric
        and categorical nodes, matching the host path), then the
        per-class reduction runs as a (B, T) x (T, K) matmul inside
        the same program (MXU)."""
        node = device_traverse(xb, sf, thr, cat, lc, rc, node0, depth)
        t_idx = jnp.arange(sf.shape[0])
        vals = lv[t_idx[None, :], ~node]                        # (B, T)
        return vals @ cls_onehot                                # (B, K)

    def _predict_raw_device(self, x, n_used):
        """Device batch prediction: fixed-size row blocks through ONE
        compiled traversal+reduction program. f32 thresholds/values —
        the host path remains the f64 reference for small batches."""
        sf, thr, cat, lc, rc, lv, node0, depth = self._device_model(n_used)
        t_cnt = sf.shape[0]
        cls_onehot = jnp.asarray(
            (np.arange(t_cnt)[:, None] % self.num_class
             == np.arange(self.num_class)[None, :]).astype(np.float32))
        n = x.shape[0]
        block = self._PREDICT_BLOCK
        nb = -(-n // block)
        # bucket the block count (round up to a multiple of the
        # 3rd-highest bit) so distinct batch sizes share O(log N)
        # compiled map shapes instead of one trace+compile per size —
        # through the tunnel a recompile costs more than the dispatches
        # saved. Worst-case padding overhead ~12.5% of traversal
        # compute.
        if nb > 4:
            step = 1 << max(nb.bit_length() - 3, 0)
            nb = -(-nb // step) * step
        f = x.shape[1]
        if nb > 1 and nb * block * f * 4 <= self.DEVICE_PREDICT_INPUT_MAX:
            # whole matrix in ONE dispatch: lax.map over row blocks
            # (168 per-block RPCs at 11M rows through the remote-TPU
            # tunnel cost more than the traversal itself)
            xall = np.zeros((nb * block, f), dtype=np.float32)
            xall[:n] = x
            out = self._predict_map_device(
                jnp.asarray(xall).reshape(nb, block, f), sf, thr, cat,
                lc, rc, lv, node0, cls_onehot, depth)
            return np.asarray(out).reshape(nb * block, -1)[:n] \
                .astype(np.float64)
        outs = []
        for s in range(0, n, block):
            xb = np.asarray(x[s:s + block], dtype=np.float32)
            pad = block - xb.shape[0]
            if pad:
                xb = np.pad(xb, ((0, pad), (0, 0)))
            outs.append(self._predict_block_device(
                jnp.asarray(xb), sf, thr, cat, lc, rc, lv, node0,
                cls_onehot, depth))
        host = np.concatenate([np.asarray(o) for o in outs], axis=0)[:n]
        return host.astype(np.float64)

    @staticmethod
    @functools.partial(jax.jit, static_argnums=(9,))
    def _predict_map_device(xblocks, sf, thr, cat, lc, rc, lv, node0,
                            cls_onehot, depth):
        """(NB, B, F) -> (NB, B, K): sequential lax.map over the same
        per-block traversal — one compiled program, one dispatch."""
        def one(xb):
            # nested jit traces inline
            return GBDT._predict_block_device(
                xb, sf, thr, cat, lc, rc, lv, node0, cls_onehot, depth)
        return jax.lax.map(one, xblocks)

    def predict_raw(self, x, num_iteration=-1):
        """Raw scores for (N, num_total_features) raw values -> (N, K).

        All trees traverse together: per depth step one (rows, trees)
        gather instead of a Python loop over trees. Large batches
        (rows x trees >= DEVICE_PREDICT_CELLS) run the jitted device
        traversal instead of the host loop."""
        x = np.atleast_2d(np.asarray(x, dtype=np.float64))
        n_used = self._num_used_models(num_iteration)
        n = x.shape[0]
        out = np.zeros((n, self.num_class))
        if n_used == 0 or n == 0:
            return out
        if self._use_device_predict(n, n_used):
            return self._predict_raw_device(x, n_used)
        lv = self._stacked_model_arrays(n_used)[5]
        lin_idx = self._linear_model_indices(n_used)
        t_cnt = lv.shape[0]
        t_idx = np.arange(t_cnt)
        cls = t_idx % self.num_class       # class-major model list
        block = max(1, min(n, self._HOST_TRAVERSE_CELLS // max(t_cnt, 1)))
        for s in range(0, n, block):
            xb = x[s:s + block]
            node = self._traverse_host(xb, n_used)               # (b, T)
            vals = lv[t_idx[None, :], ~node]                     # (b, T)
            # linear leaves: the gathered constant is exactly the
            # missing-value fallback, so overwrite in place per tree
            for i in lin_idx:
                vals[:, i] = self.models[i]._linear_values(
                    xb, (~node[:, i]).astype(np.int32), vals[:, i])
            for k in range(self.num_class):
                out[s:s + block, k] = vals[:, cls == k].sum(axis=1)
        return out

    def apply_predict_config(self, config):
        """Plumb the predict-routing knobs (docs/Parameters.md) onto
        this booster. Called from reset_training_data AND the predict-
        only CLI path (application.py init_predict), which loads models
        without ever training; class attrs remain the defaults for
        boosters that never saw a config."""
        self.DEVICE_PREDICT_CELLS = int(getattr(
            config, "device_predict_cells", self.DEVICE_PREDICT_CELLS))
        self._HOST_TRAVERSE_CELLS = int(getattr(
            config, "host_traverse_cells", self._HOST_TRAVERSE_CELLS))
        self.device_predict = str(getattr(config, "device_predict", "auto"))

    def _use_device_predict(self, n, n_used):
        """Route a predict_raw call host vs device. The env flag wins
        when set ("0"/"false" forces host, "force"/"true" forces
        device), else the `device_predict` config knob, else the
        cells-threshold auto rule (docs/Parameters.md).
        `force_host_predict` beats even the env: a booster serving as
        a PRECISION REFERENCE (serving/drift.py host_reference_scorer)
        must stay on the host f64 path no matter how the deployment
        tunes its own predictors."""
        if getattr(self, "force_host_predict", False):
            return False
        if self._linear_model_indices(n_used):
            # the training-side device traversal gathers CONSTANTS; the
            # fused traversal+dot kernels live in serving
            # (serving/compiled_model.py) — training predict stays on
            # the host f64 path for linear models, even under "force"
            return False
        knob = os.environ.get("LIGHTGBM_TPU_DEVICE_PREDICT")
        if knob in (None, "", "1"):  # "1" was the legacy auto default
            knob = str(getattr(self, "device_predict", "auto"))
        knob = knob.lower()
        if knob in ("0", "false", "off", "-"):
            return False
        if knob in ("force", "true", "+"):
            return True
        return n * n_used >= self.DEVICE_PREDICT_CELLS

    def _linear_model_indices(self, n_used):
        """Model-list indices of linear-leaf trees among the first
        n_used. LazyTree carries is_linear=False as a class attribute,
        so this probe never forces a materialization."""
        return [i for i in range(n_used)
                if getattr(self.models[i], "is_linear", False)]

    def _traverse_host(self, xb, n_used):
        """Host traversal of one row block through all stacked trees:
        returns the final (b, T) node states (~leaf encoded). Shared by
        predict_raw's host path and predict_leaf_index."""
        sf, thr, dt, lc, rc, lv, has_split, depth = \
            self._stacked_model_arrays(n_used)
        t_cnt = sf.shape[0]
        t_idx = np.arange(t_cnt)
        xbs = np.nan_to_num(xb)  # the int cast below needs a finite input
        node = np.where(has_split[None, :], 0, ~0).astype(np.int32)
        node = np.broadcast_to(node, (len(xb), t_cnt)).copy()
        for _ in range(depth):
            active = node >= 0
            if not active.any():
                break
            nd = np.maximum(node, 0)
            feat = sf[t_idx[None, :], nd]
            th = thr[t_idx[None, :], nd]
            d = dt[t_idx[None, :], nd]
            fval = xb[np.arange(len(xb))[:, None], feat]
            fcat = xbs[np.arange(len(xb))[:, None], feat]
            # NaN routes RIGHT on categorical nodes too (a missing value
            # is not a category id; reference default-direction
            # semantics) — numeric NaN already goes right via <= False
            go_left = np.where(d == Tree.CATEGORICAL,
                               (fcat.astype(np.int64) == th.astype(np.int64))
                               & ~np.isnan(fval),
                               fval <= th)
            nxt = np.where(go_left, lc[t_idx[None, :], nd],
                           rc[t_idx[None, :], nd])
            node = np.where(active, nxt, node)
        return node

    def predict(self, x, num_iteration=-1):
        """gbdt.cpp:622-636: sigmoid/softmax-transformed predictions."""
        raw = self.predict_raw(x, num_iteration)
        if self.sigmoid > 0 and self.num_class == 1:
            return 1.0 / (1.0 + np.exp(-2.0 * self.sigmoid * raw))
        if self.num_class > 1:
            return common.softmax(raw, axis=1)
        return raw

    def predict_leaf_index(self, x, num_iteration=-1):
        """(N, T) leaf indices via the same all-trees host traversal as
        predict_raw (the reference runs this OpenMP-parallel per row,
        predictor.hpp:108-118)."""
        x = np.atleast_2d(np.asarray(x, dtype=np.float64))
        n_used = self._num_used_models(num_iteration)
        n = x.shape[0]
        if n_used == 0 or n == 0:
            # (N, T) even when empty: vstacking chunked calls must work
            return np.zeros((n, n_used), dtype=np.int32)
        block = max(1, min(n, self._HOST_TRAVERSE_CELLS // n_used))
        outs = []
        for s in range(0, n, block):
            node = self._traverse_host(x[s:s + block], n_used)
            outs.append((~node).astype(np.int32))
        return np.concatenate(outs, axis=0)

    # --------------------------------------------------------- serialization
    def feature_importance_values(self, importance_type="split"):
        """Reference-semantics importance vector over the model list
        (telemetry/quality.py — the ONE aggregation every consumer
        shares): int64 split counts or float64 gain sums, length
        max_feature_idx + 1."""
        from ..telemetry.quality import feature_importance_from_models
        return feature_importance_from_models(
            self.models, self.max_feature_idx + 1, importance_type)

    def feature_importance(self):
        """Split-count importance pairs for the model file's
        "feature importances:" block (gbdt.cpp:585-610)."""
        imp = self.feature_importance_values("split")
        pairs = [(int(imp[i]), self.feature_names[i] if i < len(self.feature_names)
                  else f"Column_{i}") for i in range(len(imp)) if imp[i] > 0]
        pairs.sort(key=lambda p: -p[0])
        return pairs

    def save_model_to_string(self, num_iteration=-1):
        """gbdt.cpp:468-513 text format.

        Models with linear leaves declare `format_version=2` right
        after the name line (MODEL_FORMAT_VERSION); constant-leaf
        models omit the line entirely so their output stays
        byte-identical to every pre-linear reader and writer."""
        n_used = len(self.models) if num_iteration <= 0 else min(
            num_iteration * self.num_class, len(self.models))
        lines = [self.name]
        if any(getattr(self.models[i], "is_linear", False)
               for i in range(n_used)):
            lines.append(f"format_version={MODEL_FORMAT_VERSION}")
        lines += [f"num_class={self.num_class}",
                  f"label_index={self.label_idx}",
                  f"max_feature_idx={self.max_feature_idx}"]
        if self.objective is not None:
            lines.append(f"objective={self.objective.name}")
        elif getattr(self, "_loaded_objective_name", ""):
            # a loaded booster has no live objective; keep the declared
            # name so save(load(s)) round-trips byte-identically
            lines.append(f"objective={self._loaded_objective_name}")
        lines.append(f"sigmoid={self.sigmoid:g}")
        lines.append("feature_names=" + " ".join(self.feature_names))
        lines.append("")
        for i in range(n_used):
            lines.append(f"Tree={i}")
            lines.append(self.models[i].to_string())
        lines.append("")
        lines.append("feature importances:")
        for cnt, fname in self.feature_importance():
            lines.append(f"{fname}={cnt}")
        return "\n".join(lines) + "\n"

    def save_model_to_file(self, num_iteration, filename):
        # crash-atomic: a kill mid-save must never leave a truncated
        # model where a valid one stood (utils/checkpoint.py)
        from ..utils.checkpoint import atomic_write_text
        atomic_write_text(filename, self.save_model_to_string(num_iteration))
        if self.dataset_profile is not None:
            # the training-time baseline distribution travels with the
            # model: <model>.profile.json is what the serving drift
            # monitor loads (io/profile.py, serving/drift.py)
            from ..io.profile import model_profile_path
            try:
                self.dataset_profile.save(model_profile_path(filename))
            except OSError as e:
                Log.warning("could not write dataset profile next to "
                            "%s: %s", filename, e)

    def load_model_from_string(self, model_str):
        """gbdt.cpp:515-583."""
        self.models = _VersionedList()
        lines = model_str.split("\n")

        def find_line(prefix):
            for ln in lines:
                if prefix in ln:
                    return ln
            return ""

        line = find_line("format_version=")
        fmt = int(line.split("=")[1]) if line else 1
        if fmt > MODEL_FORMAT_VERSION:
            Log.fatal("model declares format_version=%d but this reader "
                      "supports versions <= %d — load it with the "
                      "lightgbm_tpu release that wrote it", fmt,
                      MODEL_FORMAT_VERSION)
        line = find_line("num_class=")
        if not line:
            Log.fatal("Model file doesn't specify the number of classes")
        self.num_class = int(line.split("=")[1])
        line = find_line("label_index=")
        if not line:
            Log.fatal("Model file doesn't specify the label index")
        self.label_idx = int(line.split("=")[1])
        line = find_line("max_feature_idx=")
        if not line:
            Log.fatal("Model file doesn't specify max_feature_idx")
        self.max_feature_idx = int(line.split("=")[1])
        line = find_line("objective=")
        self._loaded_objective_name = (line.split("=", 1)[1].strip()
                                       if line else "")
        line = find_line("sigmoid=")
        self.sigmoid = float(line.split("=")[1]) if line else -1.0
        line = find_line("feature_names=")
        if not line:
            Log.fatal("Model file doesn't contain feature names")
        self.feature_names = line.split("=", 1)[1].split(" ")
        if len(self.feature_names) != self.max_feature_idx + 1:
            Log.fatal("Wrong size of feature_names")

        i = 0
        while i < len(lines):
            if lines[i].startswith("Tree="):
                i += 1
                start = i
                while i < len(lines) and not lines[i].startswith("Tree="):
                    if lines[i].startswith("feature importances:"):
                        break
                    i += 1
                self.models.append(Tree.from_string(
                    "\n".join(lines[start:i]), format_version=fmt))
            else:
                i += 1
        Log.info("Finished loading %d models", len(self.models))
        self.num_iteration_for_pred = len(self.models) // max(self.num_class, 1)
        self.num_init_iteration = self.num_iteration_for_pred

    def dump_model(self):
        """JSON dump (gbdt.cpp:431-466)."""
        out = ["{"]
        out.append(f'"name":"{self.name}",')
        out.append(f'"num_class":{self.num_class},')
        out.append(f'"label_index":{self.label_idx},')
        out.append(f'"max_feature_idx":{self.max_feature_idx},')
        out.append(f'"sigmoid":{self.sigmoid:g},')
        names = '","'.join(self.feature_names)
        out.append(f'"feature_names":["{names}"],')
        tree_parts = []
        for i, tree in enumerate(self.models):
            tree_parts.append('{' + f'"tree_index":{i},' + tree.to_json() + '}')
        out.append('"tree_info":[' + ",".join(tree_parts) + "]")
        out.append("}")
        return "\n".join(out) + "\n"

    def merge_from(self, other):
        """Booster merge for continued training (gbdt.h:44-61)."""
        self.models = _VersionedList(list(other.models) + self.models)
        self.num_init_iteration += len(other.models) // max(self.num_class, 1)

    # -------------------------------------------------------- checkpointing
    def _rng_registry(self):
        """Named stateful HOST RNGs that must survive a resume for
        bit-identical continuation. Device sampling (bagging, GOSS) is
        stateless — keyed on the iteration index — so only the numpy
        streams need capturing: the feature sampler, and DART's drop
        sampler when present."""
        regs = {}
        learner = self.tree_learner
        if learner is not None and getattr(learner, "random", None) is not None:
            regs["feature_sampler"] = learner.random
        if getattr(self, "_random_for_drop", None) is not None:
            regs["drop_sampler"] = self._random_for_drop
        return regs

    def _multihost_row_sharded(self):
        """True when training rows are partitioned across processes —
        the layout under which each rank's train score covers only its
        local block (parallel/learners.py)."""
        learner = self.tree_learner
        return (learner is not None
                and getattr(learner, "n_proc", 1) > 1
                and getattr(learner, "shard_rows", False))

    def _allgather_row_counts(self):
        """(P,) local-row counts in rank order. COLLECTIVE: every
        process must call this at the same point (watchdog-armed — a
        peer wedged at a snapshot point must not hang the others
        forever)."""
        from jax.experimental import multihost_utils
        n_local = int(np.asarray(self.train_score_updater.score).shape[-1])
        with heartbeat.collective_guard("snapshot_counts_gather"):
            return np.asarray(multihost_utils.process_allgather(
                np.asarray([n_local], dtype=np.int64))).reshape(-1)

    def _gather_global_train_score(self):
        """Assemble the GLOBAL (num_class, N) train score from every
        rank's local block (ranks hold contiguous row ranges in rank
        order, parallel/distributed.py partition_rows). COLLECTIVE —
        which is why multi-host snapshots require every rank to call
        capture_training_state at the cadence point even though only
        rank 0 writes the file (application.py train): a rank-local
        snapshot would be useless to a restart whose surviving ranks
        re-partition the rows (the shrunken-world resume path)."""
        from jax.experimental import multihost_utils
        local = np.asarray(self.train_score_updater.score,
                           dtype=np.float32)            # (K, n_local)
        counts = self._allgather_row_counts()
        n_max = int(counts.max())
        padded = np.zeros((local.shape[0], n_max), dtype=np.float32)
        padded[:, :local.shape[1]] = local
        with heartbeat.collective_guard("snapshot_score_gather"):
            blocks = np.asarray(multihost_utils.process_allgather(padded))
        return np.concatenate(
            [blocks[r][:, :int(counts[r])] for r in range(len(counts))],
            axis=1)

    def capture_training_state(self):
        """Full mid-training state for utils/checkpoint.py: everything
        `restore_training_state` needs to continue training on the SAME
        config + dataset and produce the bit-identical model string of
        an uninterrupted run. Score arrays are saved verbatim (float32
        bits) — recomputing them from trees would change summation
        order and diverge the histogram sums. Multi-host row-sharded
        training stores the allgathered GLOBAL score with a layout tag,
        so a restart can re-slice it for any surviving topology."""
        if self._multihost_row_sharded():
            train_score = self._gather_global_train_score()
            score_layout = "global_rows"
        else:
            train_score = np.asarray(self.train_score_updater.score)
            score_layout = "local"
        state = {
            "state_version": 1,
            "model_str": self.save_model_to_string(-1),
            "iter": int(self.iter),
            "num_init_iteration": int(self.num_init_iteration),
            "num_class": int(self.num_class),
            "train_score": train_score,
            "train_score_layout": score_layout,
            "valid_scores": [np.asarray(u.score)
                             for u in self.valid_score_updaters],
            "best_iter": [list(map(int, x)) for x in self.best_iter],
            "best_score": [list(map(float, x)) for x in self.best_score],
            "best_msg": [list(x) for x in self.best_msg],
        }
        for name, rng in self._rng_registry().items():
            algo, keys, pos, has_gauss, cached = rng._rng.get_state()
            state[f"rng_{name}"] = {"algo": algo, "pos": int(pos),
                                    "has_gauss": int(has_gauss),
                                    "cached": float(cached)}
            state[f"rng_{name}_keys"] = np.asarray(keys)
        # bin-space split encoding: the model TEXT stores real-valued
        # thresholds only, but continued training re-scores restored
        # trees in bin space (DART's drop/normalize, early-stopping
        # truncation) — so the in-bin arrays ride along, concatenated
        # across trees
        n_splits, tib, sfi = [], [], []
        lin_counts, lin_feats = [], []
        for model in self.models:
            tree = (model.materialize() if hasattr(model, "materialize")
                    else model)
            ns = tree.num_leaves - 1
            n_splits.append(ns)
            if ns > 0:
                tib.append(np.asarray(tree.threshold_in_bin[:ns], np.int32))
                sfi.append(np.asarray(tree.split_feature[:ns], np.int32))
            # linear leaves also need their INNER coefficient feature
            # ids for bin-space re-scoring after resume (the text
            # format stores real column ids only): per-leaf counts +
            # flattened inner ids, concatenated across trees
            if getattr(tree, "is_linear", False):
                cnts = np.asarray(tree.leaf_coeff_count, np.int32)
                lin_counts.append(cnts)
                lin_feats.append(np.concatenate(
                    [tree.leaf_coeff_feat_inner[leaf, :cnts[leaf]]
                     for leaf in range(tree.num_leaves)]
                    or [np.zeros(0, np.int32)]).astype(np.int32))
            else:
                lin_counts.append(np.zeros(ns + 1, np.int32))
                lin_feats.append(np.zeros(0, np.int32))
        state["tree_n_splits"] = np.asarray(n_splits, np.int32)
        state["tree_threshold_in_bin"] = (
            np.concatenate(tib) if tib else np.zeros(0, np.int32))
        state["tree_split_feature_inner"] = (
            np.concatenate(sfi) if sfi else np.zeros(0, np.int32))
        state["tree_leaf_coeff_counts"] = (
            np.concatenate(lin_counts) if lin_counts
            else np.zeros(0, np.int32))
        state["tree_leaf_feat_inner"] = (
            np.concatenate(lin_feats) if lin_feats
            else np.zeros(0, np.int32))
        return state

    def restore_training_state(self, state):
        """Inverse of `capture_training_state`, applied to a freshly
        initialized booster bound to the same config/datasets."""
        if int(state.get("state_version", 0)) != 1:
            Log.fatal("Unsupported checkpoint state version %s",
                      state.get("state_version"))
        if int(state["num_class"]) != self.num_class:
            Log.fatal("Checkpoint num_class %d does not match booster "
                      "num_class %d", int(state["num_class"]), self.num_class)
        n_valid = len(state.get("valid_scores", []))
        if n_valid != len(self.valid_score_updaters):
            Log.fatal("Checkpoint has %d valid-set scores but booster has "
                      "%d valid sets bound", n_valid,
                      len(self.valid_score_updaters))
        self.load_model_from_string(state["model_str"])
        # re-attach the bin-space split encoding the text format drops
        # (see capture_training_state)
        n_splits = np.asarray(state.get("tree_n_splits", []), np.int32)
        if len(n_splits) == len(self.models):
            offsets = np.concatenate([[0], np.cumsum(n_splits)])
            tib = np.asarray(state["tree_threshold_in_bin"], np.int32)
            sfi = np.asarray(state["tree_split_feature_inner"], np.int32)
            lin_counts = np.asarray(
                state.get("tree_leaf_coeff_counts", []), np.int32)
            lin_feats = np.asarray(
                state.get("tree_leaf_feat_inner", []), np.int32)
            leaf_off = np.concatenate([[0], np.cumsum(n_splits + 1)])
            feat_pos = 0
            for idx, tree in enumerate(self.models):
                lo, hi = offsets[idx], offsets[idx + 1]
                if hi > lo:
                    tree.threshold_in_bin = tib[lo:hi].copy()
                    tree.split_feature = sfi[lo:hi].copy()
                if len(lin_counts) != leaf_off[-1]:
                    continue  # pre-linear checkpoint (no linear trees)
                cnts = lin_counts[leaf_off[idx]:leaf_off[idx + 1]]
                if getattr(tree, "is_linear", False):
                    for leaf in range(tree.num_leaves):
                        k = int(cnts[leaf])
                        tree.leaf_coeff_feat_inner[leaf, :k] = \
                            lin_feats[feat_pos:feat_pos + k]
                        feat_pos += k
                else:
                    feat_pos += int(cnts.sum())
        # load_model_from_string prepares for PREDICTION (treats every
        # tree as an init tree); a resume continues TRAINING, so the
        # split between init trees and this run's own is the captured one
        self.num_init_iteration = int(state["num_init_iteration"])
        self.num_iteration_for_pred = 0
        self.iter = int(state["iter"])
        train_score = np.asarray(state["train_score"], dtype=np.float32)
        if (state.get("train_score_layout") == "global_rows"
                and self._multihost_row_sharded()):
            # global capture -> this topology's local block: contiguous
            # rank-order slices, valid for the ORIGINAL topology and for
            # a shrunken world that re-partitioned the rows. (On a
            # single process the global score IS the local score and
            # the plain shape check below covers it.)
            counts = self._allgather_row_counts()
            if int(counts.sum()) != train_score.shape[-1]:
                Log.fatal("Checkpoint global train score has %d rows "
                          "but the current topology holds %d "
                          "(different training data?)",
                          train_score.shape[-1], int(counts.sum()))
            rank = jax.process_index()
            offset = int(counts[:rank].sum())
            train_score = train_score[:, offset:offset + int(counts[rank])]
        if train_score.shape != tuple(self.train_score_updater.score.shape):
            Log.fatal("Checkpoint train-score shape %s does not match "
                      "dataset shape %s (different training data?)",
                      train_score.shape,
                      tuple(self.train_score_updater.score.shape))
        self.train_score_updater.score = jnp.asarray(train_score)
        for updater, score in zip(self.valid_score_updaters,
                                  state["valid_scores"]):
            updater.score = jnp.asarray(np.asarray(score, dtype=np.float32))
        self.best_iter = [list(x) for x in state.get("best_iter", [])]
        self.best_score = [list(x) for x in state.get("best_score", [])]
        self.best_msg = [list(x) for x in state.get("best_msg", [])]
        for name, rng in self._rng_registry().items():
            meta = state.get(f"rng_{name}")
            keys = state.get(f"rng_{name}_keys")
            if meta is None or keys is None:
                continue
            rng._rng.set_state((meta["algo"],
                                np.asarray(keys, dtype=np.uint32),
                                int(meta["pos"]), int(meta["has_gauss"]),
                                float(meta["cached"])))
        # bag cache and prediction caches may describe pre-restore state
        self._bag_rows = None
        self._bag_window = None
        self._stack_cache = None
        self._dev_model_cache = None
        Log.info("Restored training state at iteration %d (%d trees)",
                 self.iter, len(self.models))


def create_boosting(boosting_type, input_model=""):
    """Factory + model-file type sniffing (src/boosting/boosting.cpp:7-66).
    "goss" is a post-reference extension (models/goss.py)."""
    from .dart import DART
    from .goss import GOSS
    if input_model:
        with open(input_model) as f:
            first = f.readline().strip()
        boosting_type = (first if first in ("gbdt", "dart", "goss")
                         else boosting_type)
    if boosting_type == "gbdt":
        return GBDT()
    if boosting_type == "dart":
        return DART()
    if boosting_type == "goss":
        return GOSS()
    Log.fatal("Unknown boosting type %s", boosting_type)
