"""GBDT: the boosting loop.

Reference: src/boosting/gbdt.h:17-310, src/boosting/gbdt.cpp. Covers:
gradient boosting with bagging (record- and query-unit), per-class tree
training, shrinkage, out-of-bag score updates, metric output with early
stopping + model truncation, rollback, model text/JSON serialization,
load-from-string, split-count feature importance, raw/sigmoid/softmax
prediction paths, and booster merging for continued training.

Bagging note: the reference draws a sequential selection sample
(gbdt.cpp:161-169) which is uniform over fixed-size subsets; we draw the
same distribution with a vectorized random-key argpartition instead of
the O(N) sequential scan.
"""

import numpy as np

from ..metrics import create_metric
from ..utils import common
from ..utils.log import Log
from ..utils.random import Random
from .score_updater import ScoreUpdater
from .tree import Tree
from .tree_learner import create_tree_learner

K_MIN_SCORE = -np.inf


class GBDT:
    name = "gbdt"

    def __init__(self):
        self.models = []            # list[Tree], class-major per iteration
        self.iter = 0
        self.num_init_iteration = 0
        self.num_iteration_for_pred = 0
        self.num_class = 1
        self.sigmoid = -1.0
        self.label_idx = 0
        self.max_feature_idx = 0
        self.feature_names = []
        self.train_data = None
        self.config = None
        self.objective = None
        self.tree_learner = None
        self.train_score_updater = None
        self.valid_score_updaters = []
        self.valid_metrics = []
        self.training_metrics = []
        self.early_stopping_round = 0
        self.shrinkage_rate = 0.1
        self.best_iter = []
        self.best_score = []
        self.best_msg = []
        self.random = Random(3)
        self._bag_rows = None       # in-bag float mask or None

    # ------------------------------------------------------------------ init
    def init(self, config, train_data, objective, training_metrics=()):
        self.iter = 0
        self.num_class = config.num_class
        self.random = Random(config.bagging_seed)
        self.config = None
        self.train_data = None
        self.reset_training_data(config, train_data, objective, training_metrics)

    def reset_training_data(self, config, train_data, objective, training_metrics=()):
        """gbdt.cpp:42-115."""
        if self.train_data is not None and not self.train_data.check_align(train_data):
            Log.fatal("cannot reset training data, since new training data has "
                      "different bin mappers")
        self.early_stopping_round = config.early_stopping_round
        self.shrinkage_rate = config.learning_rate
        self.objective = objective
        self.sigmoid = -1.0
        if objective is not None and objective.name == "binary":
            self.sigmoid = config.sigmoid

        data_changed = train_data is not None and train_data is not self.train_data
        if data_changed:
            if self.tree_learner is None:
                self.tree_learner = create_tree_learner(config.tree_learner, config)
            else:
                self.tree_learner.config = config
            self.tree_learner.init(train_data)
            self.training_metrics = list(training_metrics)
            self.train_score_updater = ScoreUpdater(train_data, self.num_class)
            # replay THIS booster's trees onto the new data; merged init
            # trees are covered by the dataset's init score (gbdt.cpp:77-79)
            for i in range(self.iter):
                for k in range(self.num_class):
                    t = self.models[(i + self.num_init_iteration) * self.num_class + k]
                    self.train_score_updater.add_score_by_tree(t, k)
            self.num_data = train_data.num_data
            self.max_feature_idx = train_data.num_total_features - 1
            self.label_idx = train_data.label_idx
            self.feature_names = list(train_data.feature_names)
        self.train_data = train_data
        self.config = config
        # data_changed already init'ed the learner with this config
        if self.tree_learner is not None and not data_changed:
            self.tree_learner.reset_config(config)

    def add_valid_dataset(self, valid_data, valid_metrics):
        """gbdt.cpp:117-147."""
        if not self.train_data.check_align(valid_data):
            Log.fatal("cannot add validation data, since it has different bin "
                      "mappers with training data")
        updater = ScoreUpdater(valid_data, self.num_class)
        # only this booster's own trees: merged init trees are covered by
        # the valid set's init score (gbdt.cpp:125-129)
        for i in range(self.iter):
            for k in range(self.num_class):
                idx = (i + self.num_init_iteration) * self.num_class + k
                updater.add_score_by_tree(self.models[idx], k)
        self.valid_score_updaters.append(updater)
        self.valid_metrics.append(list(valid_metrics))
        if self.early_stopping_round > 0:
            self.best_iter.append([0] * len(valid_metrics))
            self.best_score.append([K_MIN_SCORE] * len(valid_metrics))
            self.best_msg.append([""] * len(valid_metrics))

    # --------------------------------------------------------------- bagging
    def _bagging(self, it):
        """gbdt.cpp:150-201; returns in-bag float mask or None."""
        cfg = self.config
        if not (cfg.bagging_fraction < 1.0 and cfg.bagging_freq > 0):
            return None
        if it % cfg.bagging_freq != 0 and self._bag_rows is not None:
            return self._bag_rows
        n = self.num_data
        meta = self.train_data.metadata
        mask = np.zeros(n, dtype=np.float32)
        if meta.query_boundaries is None:
            bag_cnt = int(cfg.bagging_fraction * n)
            keys = self.random._rng.random_sample(n)
            idx = np.argpartition(keys, bag_cnt)[:bag_cnt] if bag_cnt < n else np.arange(n)
            mask[idx] = 1.0
        else:
            qb = meta.query_boundaries
            nq = len(qb) - 1
            bag_q = int(nq * cfg.bagging_fraction)
            keys = self.random._rng.random_sample(nq)
            qidx = np.argpartition(keys, bag_q)[:bag_q] if bag_q < nq else np.arange(nq)
            for q in qidx:
                mask[qb[q]:qb[q + 1]] = 1.0
        Log.debug("Re-bagging, using %d data to train", int(mask.sum()))
        self._bag_rows = mask
        return mask

    # -------------------------------------------------------------- training
    def train_one_iter(self, gradients=None, hessians=None, is_eval=True):
        """gbdt.cpp:210-245. Returns True if training should stop."""
        if gradients is None or hessians is None:
            if self.objective is None:
                Log.fatal("No object function provided")
            gradients, hessians = self.objective.get_gradients(
                self._score_for_boosting())
        else:
            gradients = np.asarray(gradients, dtype=np.float32).reshape(
                self.num_class, self.num_data)
            hessians = np.asarray(hessians, dtype=np.float32).reshape(
                self.num_class, self.num_data)
        inbag = self._bagging(self.iter)
        for k in range(self.num_class):
            tree, row_leaf, leaf_values = self.tree_learner.train(
                gradients[k], hessians[k], inbag)
            if tree.num_leaves <= 1:
                Log.info("Stopped training because there are no more leafs "
                         "that meet the split requirements.")
                return True
            tree.shrinkage(self.shrinkage_rate)
            # train scores via partition gather (covers in-bag AND out-of-bag
            # rows: the partition is computed over all rows, the bag mask only
            # gates the histogram statistics)
            self.train_score_updater.add_score_by_partition(
                np.asarray(leaf_values, dtype=np.float32) * self.shrinkage_rate,
                row_leaf, k)
            for updater in self.valid_score_updaters:
                updater.add_score_by_tree(tree, k)
            self.models.append(tree)
        self.iter += 1
        if is_eval:
            return self.eval_and_check_early_stopping()
        return False

    def _score_for_boosting(self):
        """Hook for DART's tree-dropping (dart.hpp GetTrainingScore)."""
        return self.train_score_updater.score

    def rollback_one_iter(self):
        """gbdt.cpp:247-264. Indexes from the end of the model list so it
        stays valid after early-stopping truncation."""
        if self.iter == 0 or len(self.models) < self.num_class:
            return
        for k in range(self.num_class):
            tree = self.models[-self.num_class + k]
            tree.shrinkage(-1.0)
            self.train_score_updater.add_score_by_tree(tree, k)
            for updater in self.valid_score_updaters:
                updater.add_score_by_tree(tree, k)
        del self.models[-self.num_class:]
        self.iter -= 1

    # ------------------------------------------------------------ evaluation
    def eval_and_check_early_stopping(self):
        """gbdt.cpp:266-281. Unlike the reference (which only pops the model
        list), the dropped trees' score contributions are also subtracted so
        the booster state stays consistent for rollback / continued use."""
        best_msg = self.output_metric(self.iter)
        if best_msg:
            Log.info("Early stopping at iteration %d, the best iteration round is %d",
                     self.iter, self.iter - self.early_stopping_round)
            Log.info("Output of best iteration round:\n%s", best_msg)
            self._truncate_iters(self.early_stopping_round)
            return True
        return False

    def _truncate_iters(self, k):
        """Drop the last k iterations, subtracting their score contributions
        in one batched pass per dataset (the reference only pops the model
        list, gbdt.cpp:271-279, leaving scores stale)."""
        k = min(k, self.iter)
        if k <= 0:
            return
        dropped = self.models[-k * self.num_class:]
        del self.models[-k * self.num_class:]
        self.iter -= k
        for updater in [self.train_score_updater] + self.valid_score_updaters:
            updater.sub_score_by_trees(dropped, self.num_class)

    def output_metric(self, it):
        """gbdt.cpp:292-349: print metrics, track early stopping."""
        need_output = self.config is not None and self.config.metric_freq > 0 \
            and (it % self.config.metric_freq) == 0
        ret = ""
        msg_lines = []
        met_pairs = []
        if need_output:
            for metric in self.training_metrics:
                scores = metric.eval(self.train_score_updater.host_score())
                for name, sc in zip(metric.names, scores):
                    line = f"Iteration:{it}, training {name} : {sc:g}"
                    Log.info("%s", line)
                    if self.early_stopping_round > 0:
                        msg_lines.append(line)
        if need_output or self.early_stopping_round > 0:
            for i, metrics in enumerate(self.valid_metrics):
                for j, metric in enumerate(metrics):
                    scores = metric.eval(self.valid_score_updaters[i].host_score())
                    for name, sc in zip(metric.names, scores):
                        line = f"Iteration:{it}, valid_{i + 1} {name} : {sc:g}"
                        if need_output:
                            Log.info("%s", line)
                        if self.early_stopping_round > 0:
                            msg_lines.append(line)
                    if not ret and self.early_stopping_round > 0:
                        cur = metric.factor_to_bigger_better * scores[-1]
                        if cur > self.best_score[i][j]:
                            self.best_score[i][j] = cur
                            self.best_iter[i][j] = it
                            met_pairs.append((i, j))
                        elif it - self.best_iter[i][j] >= self.early_stopping_round:
                            ret = self.best_msg[i][j]
        msg = "\n".join(msg_lines)
        for i, j in met_pairs:
            self.best_msg[i][j] = msg
        return ret

    def get_eval_at(self, data_idx):
        """gbdt.cpp:352-373. 0 = train, i+1 = valid i."""
        out = []
        if data_idx == 0:
            for metric in self.training_metrics:
                out.extend(metric.eval(self.train_score_updater.host_score()))
        else:
            for metric in self.valid_metrics[data_idx - 1]:
                out.extend(metric.eval(self.valid_score_updaters[data_idx - 1].host_score()))
        return out

    def get_eval_names(self, data_idx):
        metrics = (self.training_metrics if data_idx == 0
                   else self.valid_metrics[data_idx - 1])
        names = []
        for m in metrics:
            names.extend(m.names)
        return names

    def get_predict_at(self, data_idx):
        """gbdt.cpp:381-419: transformed per-row predictions of a bound dataset."""
        if data_idx == 0:
            updater = self.train_score_updater
        else:
            updater = self.valid_score_updaters[data_idx - 1]
        raw = updater.host_score()
        n = updater.num_data
        if self.num_class > 1:
            mat = raw.reshape(self.num_class, n).T
            p = common.softmax(mat, axis=1)
            return p.T.reshape(-1)
        if self.sigmoid > 0:
            return 1.0 / (1.0 + np.exp(-2.0 * self.sigmoid * raw))
        return raw

    def get_training_score(self):
        return self.train_score_updater.host_score()

    # ------------------------------------------------------------ prediction
    def _num_used_models(self, num_iteration=-1):
        total = len(self.models)
        if num_iteration > 0:
            return min(num_iteration * self.num_class, total)
        if self.num_iteration_for_pred > 0 and not self.train_data:
            return min(self.num_iteration_for_pred * self.num_class, total)
        return total

    def predict_raw(self, x, num_iteration=-1):
        """Raw scores for (N, num_total_features) raw values -> (N, K)."""
        x = np.atleast_2d(np.asarray(x, dtype=np.float64))
        n_used = self._num_used_models(num_iteration)
        out = np.zeros((x.shape[0], self.num_class))
        for i in range(n_used):
            out[:, i % self.num_class] += self.models[i].predict(x)
        return out

    def predict(self, x, num_iteration=-1):
        """gbdt.cpp:622-636: sigmoid/softmax-transformed predictions."""
        raw = self.predict_raw(x, num_iteration)
        if self.sigmoid > 0 and self.num_class == 1:
            return 1.0 / (1.0 + np.exp(-2.0 * self.sigmoid * raw))
        if self.num_class > 1:
            return common.softmax(raw, axis=1)
        return raw

    def predict_leaf_index(self, x, num_iteration=-1):
        x = np.atleast_2d(np.asarray(x, dtype=np.float64))
        n_used = self._num_used_models(num_iteration)
        return np.stack([self.models[i].get_leaf(x) for i in range(n_used)], axis=1)

    # --------------------------------------------------------- serialization
    def feature_importance(self):
        """Split-count importance (gbdt.cpp:585-610)."""
        imp = np.zeros(self.max_feature_idx + 1, dtype=np.int64)
        for tree in self.models:
            for s in range(tree.num_leaves - 1):
                imp[tree.split_feature_real[s]] += 1
        pairs = [(int(imp[i]), self.feature_names[i] if i < len(self.feature_names)
                  else f"Column_{i}") for i in range(len(imp)) if imp[i] > 0]
        pairs.sort(key=lambda p: -p[0])
        return pairs

    def save_model_to_string(self, num_iteration=-1):
        """gbdt.cpp:468-513 text format."""
        lines = [self.name,
                 f"num_class={self.num_class}",
                 f"label_index={self.label_idx}",
                 f"max_feature_idx={self.max_feature_idx}"]
        if self.objective is not None:
            lines.append(f"objective={self.objective.name}")
        lines.append(f"sigmoid={self.sigmoid:g}")
        lines.append("feature_names=" + " ".join(self.feature_names))
        lines.append("")
        n_used = len(self.models) if num_iteration <= 0 else min(
            num_iteration * self.num_class, len(self.models))
        for i in range(n_used):
            lines.append(f"Tree={i}")
            lines.append(self.models[i].to_string())
        lines.append("")
        lines.append("feature importances:")
        for cnt, fname in self.feature_importance():
            lines.append(f"{fname}={cnt}")
        return "\n".join(lines) + "\n"

    def save_model_to_file(self, num_iteration, filename):
        with open(filename, "w") as f:
            f.write(self.save_model_to_string(num_iteration))

    def load_model_from_string(self, model_str):
        """gbdt.cpp:515-583."""
        self.models = []
        lines = model_str.split("\n")

        def find_line(prefix):
            for ln in lines:
                if prefix in ln:
                    return ln
            return ""

        line = find_line("num_class=")
        if not line:
            Log.fatal("Model file doesn't specify the number of classes")
        self.num_class = int(line.split("=")[1])
        line = find_line("label_index=")
        if not line:
            Log.fatal("Model file doesn't specify the label index")
        self.label_idx = int(line.split("=")[1])
        line = find_line("max_feature_idx=")
        if not line:
            Log.fatal("Model file doesn't specify max_feature_idx")
        self.max_feature_idx = int(line.split("=")[1])
        line = find_line("sigmoid=")
        self.sigmoid = float(line.split("=")[1]) if line else -1.0
        line = find_line("feature_names=")
        if not line:
            Log.fatal("Model file doesn't contain feature names")
        self.feature_names = line.split("=", 1)[1].split(" ")
        if len(self.feature_names) != self.max_feature_idx + 1:
            Log.fatal("Wrong size of feature_names")

        i = 0
        while i < len(lines):
            if lines[i].startswith("Tree="):
                i += 1
                start = i
                while i < len(lines) and not lines[i].startswith("Tree="):
                    if lines[i].startswith("feature importances:"):
                        break
                    i += 1
                self.models.append(Tree.from_string("\n".join(lines[start:i])))
            else:
                i += 1
        Log.info("Finished loading %d models", len(self.models))
        self.num_iteration_for_pred = len(self.models) // max(self.num_class, 1)
        self.num_init_iteration = self.num_iteration_for_pred

    def dump_model(self):
        """JSON dump (gbdt.cpp:431-466)."""
        out = ["{"]
        out.append(f'"name":"{self.name}",')
        out.append(f'"num_class":{self.num_class},')
        out.append(f'"label_index":{self.label_idx},')
        out.append(f'"max_feature_idx":{self.max_feature_idx},')
        out.append(f'"sigmoid":{self.sigmoid:g},')
        names = '","'.join(self.feature_names)
        out.append(f'"feature_names":["{names}"],')
        tree_parts = []
        for i, tree in enumerate(self.models):
            tree_parts.append('{' + f'"tree_index":{i},' + tree.to_json() + '}')
        out.append('"tree_info":[' + ",".join(tree_parts) + "]")
        out.append("}")
        return "\n".join(out) + "\n"

    def merge_from(self, other):
        """Booster merge for continued training (gbdt.h:44-61)."""
        self.models = list(other.models) + self.models
        self.num_init_iteration += len(other.models) // max(self.num_class, 1)


def create_boosting(boosting_type, input_model=""):
    """Factory + model-file type sniffing (src/boosting/boosting.cpp:7-66)."""
    from .dart import DART
    if input_model:
        with open(input_model) as f:
            first = f.readline().strip()
        boosting_type = first if first in ("gbdt", "dart") else boosting_type
    if boosting_type == "gbdt":
        return GBDT()
    if boosting_type == "dart":
        return DART()
    Log.fatal("Unknown boosting type %s", boosting_type)
