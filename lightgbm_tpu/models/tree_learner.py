"""Serial tree learner: the whole leaf-wise tree build as ONE device program.

Reference: src/treelearner/serial_tree_learner.cpp:19-442 (leaf-wise loop),
src/treelearner/data_partition.hpp (row->leaf partition),
src/treelearner/leaf_splits.hpp (per-leaf state),
src/treelearner/feature_histogram.hpp:97-106 (subtraction trick).

TPU-first design (diverges deliberately from the C++ class graph):

- The entire tree grows inside one jitted `lax.fori_loop`: static
  shapes, no host round-trips per split.
- The row partition is ONLY the dense (N,) `row_leaf` map. The
  reference's DataPartition (ordered row indices per leaf,
  data_partition.hpp:90-140) exists to make per-leaf histogram cost
  proportional to leaf size via gathers; on TPU random gathers are
  latency-bound, so per-split histograms instead stream the full bin
  matrix with the leaf selected by a row_leaf mask — sequential HBM
  reads at full bandwidth (ops/pallas_hist.py). Updating the partition
  after a split is a single vectorized `where` on row_leaf.
- Histograms: only the SMALLER child (by global in-bag count) is
  computed per split; the larger child is parent − smaller from a
  per-leaf (L, F, B, 3) histogram cache (the subtraction trick; the
  reference's LRU HistogramPool becomes a fixed HBM buffer — 63 leaves
  × 28 feat × 256 bins × 3 stats ≈ 5 MB for the HIGGS shape).
- Collectives are injected through hooks so the parallel learners
  (parallel/learners.py) reuse this exact builder under `shard_map`:
  `hist_psum_fn` reduces histograms across row shards (the reference's
  ReduceScatter sync point), `sum_psum_fn` reduces root sums, and
  `evaluate_fn`/`split_col_fn` override split search and split-column
  fetch for the feature-parallel / voting learners.

Split semantics (gain formulas, epsilons, tie-breaks, max_depth guard,
min_data/min_sum_hessian constraints) follow the reference exactly; see
ops/split.py.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

import contextlib

from ..ops.histogram import (callbacks_disabled, compacted_histograms,
                             frontier_histograms, host_callbacks_hazardous,
                             set_hist_mode)
from ..ops.ordered_hist import canonical_row_chunks
from ..ops.pallas_hist import masked_histograms, HIST_CHUNK
from ..ops.split import SplitParams, find_best_split, K_MIN_SCORE
from ..utils.random import Random
from ..utils.log import Log
from .tree import Tree


def _identity(x):
    return x


def _tristate(value, name):
    """Normalize a config tri-state to "auto"/"true"/"false"."""
    mode = str(value).lower()
    if mode in ("true", "1", "on", "+"):
        return "true"
    if mode in ("false", "0", "off", "-"):
        return "false"
    if mode != "auto":
        Log.fatal('%s must be "auto", "true" or "false", got [%s]',
                  name, mode)
    return "auto"


def _partitioned_mode(cfg):
    """Validate + normalize partitioned_build to "auto"/"true"/"false"."""
    return _tristate(getattr(cfg, "partitioned_build", "auto"),
                     "partitioned_build")


def pow2_scan_chunk(chunk):
    """Largest power-of-two scan chunk <= `chunk`, capped at HIST_CHUNK —
    the only values guaranteed to divide HIST_CHUNK-padded row counts.
    Shared by the serial and meshed learners' _effective_chunk."""
    if chunk >= HIST_CHUNK:
        return HIST_CHUNK
    return 1 << (max(int(chunk), 1).bit_length() - 1)


def init_split_state(l, root_split, root_c):
    """Per-leaf candidate + tree arrays shared by both builders
    (masked build_tree_device and models/partitioned.py)."""
    f32 = jnp.float32

    def set0(arr, v):
        return arr.at[0].set(v)

    return {
        "done": jnp.asarray(False),
        "n_splits": jnp.asarray(0, dtype=jnp.int32),
        # per-leaf split candidates (LeafSplits + best_split_per_leaf_)
        "best_gain": jnp.full(l, K_MIN_SCORE, dtype=f32).at[0].set(root_split.gain),
        "best_feature": set0(jnp.zeros(l, jnp.int32), root_split.feature),
        "best_threshold": set0(jnp.zeros(l, jnp.int32), root_split.threshold),
        "best_lg": set0(jnp.zeros(l, f32), root_split.left_sum_gradient),
        "best_lh": set0(jnp.zeros(l, f32), root_split.left_sum_hessian),
        "best_lc": set0(jnp.zeros(l, f32), root_split.left_count),
        "best_rg": set0(jnp.zeros(l, f32), root_split.right_sum_gradient),
        "best_rh": set0(jnp.zeros(l, f32), root_split.right_sum_hessian),
        "best_rc": set0(jnp.zeros(l, f32), root_split.right_count),
        "best_lout": set0(jnp.zeros(l, f32), root_split.left_output),
        "best_rout": set0(jnp.zeros(l, f32), root_split.right_output),
        "leaf_depth": jnp.zeros(l, dtype=jnp.int32),
        # tree arrays (models/tree.py)
        "split_feature": jnp.zeros(l - 1, dtype=jnp.int32),
        "split_threshold_bin": jnp.zeros(l - 1, dtype=jnp.int32),
        "split_gain": jnp.zeros(l - 1, dtype=f32),
        "left_child": jnp.zeros(l - 1, dtype=jnp.int32),
        "right_child": jnp.zeros(l - 1, dtype=jnp.int32),
        "leaf_parent": jnp.full(l, -1, dtype=jnp.int32),
        "leaf_value": jnp.zeros(l, dtype=f32),
        "leaf_count": jnp.zeros(l, dtype=jnp.int32).at[0].set(root_c.astype(jnp.int32)),
        "internal_value": jnp.zeros(l - 1, dtype=f32),
        "internal_count": jnp.zeros(l - 1, dtype=jnp.int32),
    }


def apply_tree_split(st, i, best_leaf, gain, l):
    """Tree bookkeeping for splitting `best_leaf` at iteration i
    (Tree::Split, tree.cpp:51-97).
    Returns (st, node, right_id, split_feature, split_threshold_bin)."""
    node = i  # splits happen on consecutive iterations
    right_id = i + 1  # new leaf id == num_leaves so far (tree.cpp:55)
    feat = st["best_feature"][best_leaf]
    thr = st["best_threshold"][best_leaf]

    parent = st["leaf_parent"][best_leaf]
    was_left = st["left_child"][jnp.maximum(parent, 0)] == ~best_leaf
    lc = st["left_child"]
    rc = st["right_child"]
    lc = jnp.where(
        (jnp.arange(l - 1) == parent) & (parent >= 0) & was_left, node, lc)
    rc = jnp.where(
        (jnp.arange(l - 1) == parent) & (parent >= 0) & ~was_left, node, rc)
    st["left_child"] = lc.at[node].set(~best_leaf)
    st["right_child"] = rc.at[node].set(~right_id)
    st["split_feature"] = st["split_feature"].at[node].set(feat)
    st["split_threshold_bin"] = st["split_threshold_bin"].at[node].set(thr)
    st["split_gain"] = st["split_gain"].at[node].set(gain)
    st["leaf_parent"] = (st["leaf_parent"].at[best_leaf].set(node)
                         .at[right_id].set(node))
    st["internal_value"] = st["internal_value"].at[node].set(
        st["leaf_value"][best_leaf])
    st["internal_count"] = st["internal_count"].at[node].set(
        (st["best_lc"][best_leaf] + st["best_rc"][best_leaf]).astype(jnp.int32))
    st["leaf_value"] = (st["leaf_value"]
                        .at[best_leaf].set(st["best_lout"][best_leaf])
                        .at[right_id].set(st["best_rout"][best_leaf]))
    st["leaf_count"] = (st["leaf_count"]
                        .at[best_leaf].set(st["best_lc"][best_leaf].astype(jnp.int32))
                        .at[right_id].set(st["best_rc"][best_leaf].astype(jnp.int32)))
    st["n_splits"] = st["n_splits"] + 1
    return st, node, right_id, feat, thr


def write_candidate(st, leaf_id, sp, gain_v):
    """Store a leaf's best-split candidate in the per-leaf state."""
    st["best_gain"] = st["best_gain"].at[leaf_id].set(gain_v)
    st["best_feature"] = st["best_feature"].at[leaf_id].set(sp.feature)
    st["best_threshold"] = st["best_threshold"].at[leaf_id].set(sp.threshold)
    st["best_lg"] = st["best_lg"].at[leaf_id].set(sp.left_sum_gradient)
    st["best_lh"] = st["best_lh"].at[leaf_id].set(sp.left_sum_hessian)
    st["best_lc"] = st["best_lc"].at[leaf_id].set(sp.left_count)
    st["best_rg"] = st["best_rg"].at[leaf_id].set(sp.right_sum_gradient)
    st["best_rh"] = st["best_rh"].at[leaf_id].set(sp.right_sum_hessian)
    st["best_rc"] = st["best_rc"].at[leaf_id].set(sp.right_count)
    st["best_lout"] = st["best_lout"].at[leaf_id].set(sp.left_output)
    st["best_rout"] = st["best_rout"].at[leaf_id].set(sp.right_output)
    return st


def _collapse_pair(pair):
    """Default hist reduction hook: no shards, just collapse the
    compensated (value, residual) pair."""
    hi, lo = pair
    return hi + lo


def build_tree_device(bins, grad, hess, inbag, feature_mask,
                      num_bin_pf, is_cat,
                      *, num_leaves, max_bin, params: SplitParams,
                      max_depth, row_chunk,
                      hist_psum_fn=_collapse_pair, sum_psum_fn=_identity,
                      evaluate_fn=None, split_col_fn=None,
                      expand_fn=_identity, cache_hists=True,
                      compact_hist=False, use_frontier=True):
    """Grow one leaf-wise tree on device. All shapes static.

    Args:
      bins: (F, N_pad) int bins (pad rows have no effect: inbag=0 there).
      grad, hess: (N_pad,) float32.
      inbag: (N_pad,) float32 0/1 bagging+validity mask.
      feature_mask: (F,) bool feature_fraction mask.
      num_bin_pf: (F,) int32 bins per feature; is_cat: (F,) bool.
      num_leaves/max_bin/params/max_depth/row_chunk: static config.
      hist_psum_fn: takes the compensated (hist, residual) pair from
        masked_histograms and returns the reduced+collapsed histogram.
        Default: collapse only (single device / feature-sharded
        learner); the data-parallel learner reduces shard pairs in a
        FIXED order so every shard (and the serial learner) sees
        histograms equal to ~f64 accuracy — the reference gets the same
        guarantee from f64 accumulators (bin.h:18-26). The reduction
        may RETURN FEWER FEATURES than it was fed: the reduce-scatter
        exchange (parallel/mesh.py) hands each shard only its owned
        (f_loc, B, 3) block, and the histogram cache, subtraction trick
        and evaluate_fn all operate in that owned space (the builder
        sizes them from the reduced root histogram, not from `bins`).
      sum_psum_fn: reduces scalar root sums across row shards. Root
        sums are derived FROM the reduced histogram (any feature's bins
        partition the rows), so learners whose hist_psum_fn already
        produces the global histogram pass identity here.
      evaluate_fn: optional (hist3, sum_g, sum_h, cnt) -> SplitInfo
        override. `hist3` is the hist_psum_fn-reduced histogram for the
        serial/data-parallel learners; the voting learner keeps the
        default pair-collapse (so hist3 is its LOCAL histogram) and does
        its own selective reduction here
        (voting_parallel_tree_learner.cpp:137-293).
      split_col_fn: optional (feature_id) -> (N_pad,) int32 bin column,
        overridden by the feature-parallel learner to broadcast the
        owner shard's column, and by bundled datasets to decode a
        virtual feature out of its slot.
      expand_fn: stored->virtual histogram expansion for bundled
        datasets (io/bundling.py); identity otherwise. Histograms are
        cached and subtracted in STORED space (cheap), expanded only at
        split evaluation.
      cache_hists: keep the (L, F, B, 3) per-leaf histogram cache and
        get the larger child by parent subtraction (the reference's
        HistogramPool fast path). False = memory-bounded mode
        (histogram_pool_size exceeded, feature_histogram.hpp:337-481's
        LRU analog): both children's histograms are recomputed at each
        split, memory O(F * B) instead of O(L * F * B).
      compact_hist: per-split child histograms gather the leaf's rows
        into a bucket-padded contiguous buffer first (ops/histogram.py
        compacted_histograms) — cost O(rows-in-child) instead of the
        full-scan's O(N); N_pad must then be a multiple of HIST_CHUNK.
        The root histogram stays a full streaming scan (its bucket IS
        the whole array). Works under every collective hook: the pair
        contract is unchanged and the bucketed lax.switch holds no
        collectives, so hist_psum_fn still meets shards in lockstep.
      use_frontier: route the root/bagging re-init pass through the
        multi-leaf frontier primitive (ops/histogram.py
        frontier_histograms), and — in cache-less (memory-bounded)
        mode on the masked path — build BOTH children of a split in
        one data pass instead of two, halving that mode's full-matrix
        streams. Per-leaf values are bitwise identical to the
        single-leaf kernels (same chunk decomposition and accumulation
        order), so this changes pass count, not numerics. The
        hist_frontier config tri-state maps here ("auto" = on).

    Returns a dict of tree arrays + the final row->leaf partition.
    """
    f, n_pad = bins.shape
    l = num_leaves
    b = max_bin
    f32 = jnp.float32

    if evaluate_fn is None:
        def evaluate_fn(hist3, sum_g, sum_h, cnt):
            return find_best_split(hist3, sum_g, sum_h, cnt,
                                   num_bin_pf, is_cat, feature_mask, params)

    def scan_leaf(hist3, sum_g, sum_h, cnt):
        return evaluate_fn(expand_fn(hist3), sum_g, sum_h, cnt)

    if split_col_fn is None:
        def split_col_fn(feat):
            return jnp.take(bins, feat, axis=0).astype(jnp.int32)

    g_in = grad * inbag
    h_in = hess * inbag
    # packed per-row stats, stats-major for the masked histogram kernel
    ghc_t = jnp.stack([g_in, h_in, inbag], axis=0)  # (3, N_pad)

    # The masked (non-compacted) configuration is THE engine carrying
    # the exact serial == data-parallel contract: its chunk kernels
    # must resolve identically in the serial and meshed learners, and
    # the meshed learners trace under callbacks_disabled (host
    # callbacks deadlock multi-device shard_map CPU programs) — so the
    # serial masked trace disables them too. The compacted engine
    # (documented ~1e-6 vs masked, opt-in on row shards) keeps the
    # bincount callback kernel.
    hist_guard = (contextlib.nullcontext if compact_hist
                  else callbacks_disabled)

    def full_scan_histogram(row_leaf, leaf_id):
        """Full-bandwidth streaming pass selecting `leaf_id`'s rows by
        mask (ops/pallas_hist.py) — the TPU replacement for the
        reference's ordered-gather ConstructHistogram."""
        with hist_guard():
            return masked_histograms(bins, ghc_t, row_leaf, leaf_id, b,
                                     row_chunk)

    if compact_hist:
        def leaf_histogram(row_leaf, leaf_id):
            """Gather-compacted smaller-child pass: stream only the
            geometric chunk bucket covering the leaf's rows."""
            return compacted_histograms(bins, ghc_t, row_leaf, leaf_id,
                                        b, row_chunk)
    else:
        leaf_histogram = full_scan_histogram

    # ---- root ----------------------------------------------------------
    # (re)built at every tree under bagging/GOSS: the in-bag weights
    # rode in through ghc_t, so this full pass IS the bagging re-init
    row_leaf0 = jnp.zeros(n_pad, dtype=jnp.int32)
    if use_frontier:
        with hist_guard():
            root_pair = frontier_histograms(bins, ghc_t, row_leaf0,
                                            jnp.zeros(1, jnp.int32), b,
                                            row_chunk)
        hist_root = hist_psum_fn(root_pair)[0]
    else:
        hist_root = hist_psum_fn(full_scan_histogram(row_leaf0,
                                                     jnp.int32(0)))
    # root sums from the reduced histogram: feature 0's bins partition
    # the rows, so its bin sums ARE the leaf totals — this keeps parent
    # sums bit-consistent with the histogram across serial/parallel
    root_g = sum_psum_fn(jnp.sum(hist_root[0, :, 0]))
    root_h = sum_psum_fn(jnp.sum(hist_root[0, :, 1]))
    root_c = sum_psum_fn(jnp.sum(hist_root[0, :, 2]))
    root_split = scan_leaf(hist_root, root_g, root_h, root_c)

    state = init_split_state(l, root_split, root_c)
    state["row_leaf"] = row_leaf0
    # feature count of the REDUCED histogram space: equals f except
    # under a scattering hist_psum_fn (reduce-scatter hands each shard
    # its owned f_loc block; cache/subtraction stay in owned space)
    f_hist = hist_root.shape[0]
    if cache_hists:
        # per-leaf histogram cache (HistogramPool, fixed buffer)
        state["hist_cache"] = (jnp.zeros((l, f_hist, b, 3), dtype=f32)
                               .at[0].set(hist_root))

    def body(i, st):
        best_leaf = jnp.argmax(st["best_gain"]).astype(jnp.int32)
        gain = st["best_gain"][best_leaf]
        do = jnp.logical_and(jnp.logical_not(st["done"]), gain > 0.0)

        def no_split(st):
            st = dict(st)
            st["done"] = jnp.asarray(True)
            return st

        def do_split(st):
            st = dict(st)
            st, node, right_id, feat, thr = apply_tree_split(
                st, i, best_leaf, gain, l)

            # ---- partition update (DataPartition::Split): one where()
            col = split_col_fn(feat)
            go_left_row = jnp.where(is_cat[feat], col == thr, col <= thr)
            in_leaf = st["row_leaf"] == best_leaf
            st["row_leaf"] = jnp.where(in_leaf & ~go_left_row, right_id,
                                       st["row_leaf"])

            if cache_hists:
                # ---- smaller-child histogram + parent subtraction
                # smaller side by GLOBAL in-bag count (consistent across
                # row shards; data_parallel_tree_learner.cpp:178-187)
                left_is_small = (st["best_lc"][best_leaf]
                                 <= st["best_rc"][best_leaf])
                small_leaf = jnp.where(left_is_small, best_leaf, right_id)
                hist_small = hist_psum_fn(leaf_histogram(
                    st["row_leaf"], small_leaf.astype(jnp.int32)))
                hist_large = st["hist_cache"][best_leaf] - hist_small
                hist_left = jnp.where(left_is_small, hist_small, hist_large)
                hist_right = jnp.where(left_is_small, hist_large, hist_small)
                st["hist_cache"] = (st["hist_cache"]
                                    .at[best_leaf].set(hist_left)
                                    .at[right_id].set(hist_right))
            elif use_frontier and not compact_hist:
                # memory-bounded mode, frontier-batched: BOTH children
                # from ONE streamed pass (leaf-indexed accumulator /
                # combined leaf x bin key) — half the full-matrix
                # streams of the two-pass recompute below
                leaf_vec = jnp.stack([best_leaf,
                                      right_id]).astype(jnp.int32)
                with hist_guard():
                    both_pair = frontier_histograms(
                        bins, ghc_t, st["row_leaf"], leaf_vec, b,
                        row_chunk)
                both = hist_psum_fn(both_pair)
                hist_left, hist_right = both[0], both[1]
            else:
                # memory-bounded mode: both children recomputed
                hist_left = hist_psum_fn(
                    leaf_histogram(st["row_leaf"], best_leaf))
                hist_right = hist_psum_fn(
                    leaf_histogram(st["row_leaf"], right_id))

            # ---- children leaf state (LeafSplits::Init after split)
            child_depth = st["leaf_depth"][best_leaf] + 1
            st["leaf_depth"] = (st["leaf_depth"].at[best_leaf].set(child_depth)
                                .at[right_id].set(child_depth))

            lsplit = scan_leaf(hist_left, st["best_lg"][best_leaf],
                               st["best_lh"][best_leaf], st["best_lc"][best_leaf])
            rsplit = scan_leaf(hist_right, st["best_rg"][best_leaf],
                               st["best_rh"][best_leaf], st["best_rc"][best_leaf])

            # max_depth guard (serial_tree_learner.cpp:238-247)
            depth_ok = jnp.logical_or(max_depth < 0, child_depth < max_depth)
            lgain = jnp.where(depth_ok, lsplit.gain, K_MIN_SCORE)
            rgain = jnp.where(depth_ok, rsplit.gain, K_MIN_SCORE)

            st = write_candidate(st, best_leaf, lsplit, lgain)
            st = write_candidate(st, right_id, rsplit, rgain)
            return st

        return jax.lax.cond(do, do_split, no_split, st)

    state = jax.lax.fori_loop(0, l - 1, body, state)
    return {
        "n_splits": state["n_splits"],
        "row_leaf": state["row_leaf"],
        "split_feature": state["split_feature"],
        "split_threshold_bin": state["split_threshold_bin"],
        "split_gain": state["split_gain"],
        "left_child": state["left_child"],
        "right_child": state["right_child"],
        "leaf_parent": state["leaf_parent"],
        "leaf_value": state["leaf_value"],
        "leaf_count": state["leaf_count"],
        "internal_value": state["internal_value"],
        "internal_count": state["internal_count"],
    }


def cache_hists_fits(cfg, stored, max_bin):
    """Whether the per-leaf histogram cache (the fixed-buffer
    HistogramPool analog) fits the configured budget. The reference
    LRU-pages histograms under histogram_pool_size MB
    (feature_histogram.hpp:337-481); dynamic eviction is XLA-hostile,
    so over budget we instead RECOMPUTE both children's histograms at
    each split (no parent subtraction): memory drops from
    O(num_leaves * F * B) to O(F * B), cost at most doubles.

    ONE shared rule: cache-vs-recompute changes the f32 histogram
    arithmetic (parent subtraction vs direct build), so the out-of-core
    streaming learner must make the identical decision to the in-RAM
    masked engine or its bit-parity contract breaks at configs near the
    pool boundary (lightgbm_tpu/data/ooc_learner.py)."""
    cache_mb = (int(cfg.num_leaves) * stored * max_bin * 3 * 4
                ) / (1024.0 * 1024.0)
    pool = float(cfg.histogram_pool_size)
    if 0 <= pool < cache_mb:
        Log.info("Histogram cache (%.0f MB at %d leaves x %d stored "
                 "features x %d bins) exceeds histogram_pool_size="
                 "%.0f MB: recomputing child histograms instead of "
                 "caching for subtraction", cache_mb,
                 int(cfg.num_leaves), stored, max_bin, pool)
        return False
    if pool < 0 and cache_mb > 4096:
        Log.warning("Histogram cache needs %.0f MB of device memory "
                    "(%d leaves x %d stored features x %d bins); set "
                    "histogram_pool_size (MB) to cap it via "
                    "recompute mode", cache_mb, int(cfg.num_leaves),
                    stored, max_bin)
    return True


class SerialTreeLearner:
    """Host-side driver owning the jitted builder (tree_learner.h:19-71)."""

    name = "serial"

    def __init__(self, config):
        self.config = config
        self.random = Random(config.feature_fraction_seed)
        self.train_set = None
        # persistent compile cache: the jitted builders are the
        # process's big XLA programs — make their compile a
        # once-per-machine cost (config.py setup_compilation_cache)
        from ..config import setup_compilation_cache
        setup_compilation_cache(config)

    def init(self, train_set):
        if getattr(train_set, "block_store", None) is not None:
            Log.fatal("the training data is an out-of-core block store "
                      "but out_of_core=false; set out_of_core=true (or "
                      "rebuild the dataset in-RAM)")
        self.train_set = train_set
        cfg = self.config
        self.num_features = train_set.num_features
        self.num_data = train_set.num_data
        # histogram width follows the STORED matrix (bundle slots pack
        # several features' bin ranges; io/bundling.py)
        self.max_bin = int(train_set.max_stored_bin)
        self._bundle = train_set.bundle_plan
        # histogram formulation knob (config wins over the env default;
        # ops/histogram.py set_hist_mode) — must land before any
        # builder jit so the resolved mode is baked consistently. The
        # mode is re-asserted before every build/trace (apply_hist_mode)
        # so two Boosters with different hist_mode in one process
        # cannot cross-contaminate a later retrace (new shape bucket).
        self._hist_mode_cfg = getattr(cfg, "hist_mode", "auto")
        set_hist_mode(self._hist_mode_cfg)
        self._use_partitioned = self._partitioned_enabled(cfg)
        self._use_compact = self._compaction_enabled(cfg)
        self._use_frontier = _tristate(
            getattr(cfg, "hist_frontier", "auto"),
            "hist_frontier") != "false"
        self._use_shape_bucketing = _tristate(
            getattr(cfg, "shape_bucketing", "auto"),
            "shape_bucketing") != "false"
        if self._bundle is not None:
            from ..io.bundling import expansion_maps
            src, slot_of = expansion_maps(self._bundle, train_set.bin_mappers,
                                          int(train_set.max_num_bin))
            self._bundle_src = self._place_rep(src)
            self._bundle_slot_of = self._place_rep(slot_of)
            self._bundle_feat_slot = self._place_rep(self._bundle.feat_slot)
            self._bundle_feat_off = self._place_rep(self._bundle.feat_offset)
        chunk = int(cfg.device_row_chunk)
        n_pad = self._pad_rows(self.num_data, chunk)
        self.n_pad = n_pad
        chunk = self._effective_chunk(chunk)
        self.row_chunk = chunk
        bins = train_set.bins
        if n_pad != self.num_data:
            pad = np.zeros((bins.shape[0], n_pad - self.num_data), dtype=bins.dtype)
            bins = np.concatenate([bins, pad], axis=1)
        if self._bundle is not None and self._use_partitioned:
            # bundled + partitioned: the packed words carry the STORED
            # slot matrix (padded to the packer's 4-per-word alignment)
            # while the split scan stays in VIRTUAL feature space via
            # the expand/decode hooks — so the virtual arrays
            # (num_bin_pf / is_cat / feature masks) are NOT padded
            s_rows = bins.shape[0]
            s_pad = ((s_rows + 3) // 4) * 4
            if s_pad != s_rows:
                bins = np.concatenate(
                    [bins, np.zeros((s_pad - s_rows, bins.shape[1]),
                                    dtype=bins.dtype)], axis=0)
            f_pad = self.num_features
        else:
            f_pad = self._pad_feature_count(self.num_features)
        self.f_pad = f_pad
        num_bin_pf = train_set.num_bin_array()
        is_cat = train_set.feature_is_categorical()
        if f_pad != self.num_features:
            extra = f_pad - self.num_features
            bins = np.concatenate(
                [bins, np.zeros((extra, bins.shape[1]), dtype=bins.dtype)], axis=0)
            num_bin_pf = np.concatenate([num_bin_pf, np.ones(extra, np.int32)])
            is_cat = np.concatenate([is_cat, np.zeros(extra, bool)])
        self._bins = self._place_bins(bins)
        self._num_bin_pf = self._place_rep(num_bin_pf)
        self._is_cat = self._place_rep(is_cat)
        # host-side lookup tables for vectorized device->Tree conversion:
        # bin -> representative value per feature (Feature::BinToValue) and
        # the per-feature decision type, so _to_host_tree needs no Python
        # loop over splits.
        table = np.zeros((self.num_features, self.max_bin), dtype=np.float64)
        for i, m in enumerate(train_set.bin_mappers):
            vals = (m.bin_upper_bound if m.bin_type != 1
                    else m.bin_2_categorical.astype(np.float64))
            table[i, :len(vals)] = vals
        self._bin_value_table = table
        self._decision_type_host = np.asarray(
            [1 if m.bin_type == 1 else 0 for m in train_set.bin_mappers],
            dtype=np.int8)
        self.params = SplitParams(
            min_data_in_leaf=float(cfg.min_data_in_leaf),
            min_sum_hessian_in_leaf=float(cfg.min_sum_hessian_in_leaf),
            lambda_l1=float(cfg.lambda_l1),
            lambda_l2=float(cfg.lambda_l2),
            min_gain_to_split=float(cfg.min_gain_to_split),
        )
        self._build = self._make_build_fn(cfg, chunk)
        Log.info("Number of data: %d, number of features: %d",
                 self.num_data, self.num_features)

    # which learner classes can run the leaf-contiguous builder
    # (parallel/learners.py sets True on the data-parallel learner)
    partitioned_capable = True

    def _partitioned_enabled(self, cfg):
        """Leaf-contiguous builder (models/partitioned.py): "auto"
        turns it on for TPU backends. Bundled (EFB) datasets run it
        too — the packed words carry the slot matrix and the bundle's
        expand/decode hooks bridge to virtual features. Needs
        uint8-storable bins (<= 256 stored bins per slot, which EFB's
        MAX_SLOT_BINS already guarantees for bundles)."""
        mode = _partitioned_mode(cfg)
        if not self.partitioned_capable:
            if mode == "true":
                Log.warning("partitioned_build=true ignored: the %s "
                            "learner has no leaf-contiguous core",
                            getattr(self, "name", "this"))
            return False
        if mode == "false":
            return False
        eligible = int(self.train_set.max_stored_bin) <= 256
        if mode == "true":
            if not eligible:
                Log.warning("partitioned_build=true ignored: needs "
                            "max_bin <= 256")
            return eligible
        return eligible and jax.default_backend() == "tpu"

    def _compaction_enabled(self, cfg):
        """Gather-compacted smaller-child histograms (ops/histogram.py
        compacted_histograms) on the dense masked builder. "auto" turns
        it on everywhere EXCEPT the TPU masked path, whose pallas
        streaming kernel already reads HBM at full bandwidth and where
        random gathers are latency-bound (BASELINE.md); "true" forces
        it there too. Moot when the leaf-contiguous builder is active —
        that path is already row-proportional."""
        mode = _tristate(getattr(cfg, "hist_compaction", "auto"),
                         "hist_compaction")
        if self._use_partitioned or mode == "false":
            return False
        if mode == "true":
            return True
        # single-chunk datasets gain nothing: the one bucket IS the
        # whole array, so compaction would only add the per-split
        # gather plus HIST_CHUNK row padding the masked path avoids
        return (jax.default_backend() != "tpu"
                and self.num_data > HIST_CHUNK)

    # hooks overridden by the parallel learners (parallel/learners.py) -------
    def _chunk_pad(self, n):
        """HIST_CHUNK-granular row padding, canonicalized to the
        shape-bucket grid so nearby dataset sizes reuse one lowered
        executable from the persistent compile cache."""
        n_chunks = (n + HIST_CHUNK - 1) // HIST_CHUNK
        if self._use_shape_bucketing:
            n_chunks = canonical_row_chunks(n_chunks)
        return n_chunks * HIST_CHUNK

    def _pad_rows(self, n, chunk):
        if (jax.default_backend() == "tpu" or self._use_partitioned
                or self._use_compact):
            # the pallas/segment/compacted histogram paths grid over
            # fixed HIST_CHUNK blocks
            return self._chunk_pad(n)
        return ((n + chunk - 1) // chunk) * chunk if n > chunk else n

    def _effective_chunk(self, chunk):
        if (jax.default_backend() == "tpu" or self._use_partitioned
                or self._use_compact):
            # rows are padded to HIST_CHUNK multiples; the XLA-fallback
            # scan chunk must DIVIDE that
            return pow2_scan_chunk(chunk)
        return min(chunk, self.n_pad)

    def _pad_feature_count(self, f):
        if self._use_partitioned:
            return ((f + 3) // 4) * 4  # packed words hold 4 features
        return f

    def _place_bins(self, bins):
        if self._use_partitioned:
            from ..ops.ordered_hist import pack_feature_words
            return jnp.asarray(pack_feature_words(bins))
        return jnp.asarray(bins)

    def _place_rows(self, arr):
        return arr

    def _place_rep(self, arr):
        return jnp.asarray(arr)

    def local_row_leaf(self, out, n_local):
        """This process's rows of the row->leaf partition (trivial in
        single-process; overridden by the meshed learners)."""
        return out["row_leaf"][:n_local]

    def local_leaf_values(self, out):
        """Leaf values as a process-local array (overridden multi-host)."""
        return out["leaf_value"]

    def linear_fit_context(self):
        """(chunks, bin_value_table, fit_chunk) for the linear leaf fit
        (models/linear_leaves.py). The resident path exposes the whole
        dataset as ONE (lo, hi, bins, base) block over the virtual-
        space traversal bins; the fit re-chunks it on the
        device_row_chunk grid the streamed learner's blocks align to,
        which is what keeps the f64 accumulation bit-identical across
        the two paths."""
        tv = self.train_set.traversal_bins()
        chunks = [(0, self.num_data, tv, 0)]
        # the DATASET's representative table, not the learner's split-
        # threshold table: the fit must dot against the same (finite,
        # inf-clamped) values Tree.predict_by_bins will use
        return chunks, self.train_set.bin_value_table(), int(
            self.config.device_row_chunk)

    def _bundle_expand_fn(self):
        """Stored->virtual histogram expansion closure (io/bundling.py
        expansion_maps). Slices the histogram to the REAL slot count
        first: the partitioned layout pads stored rows to the packer's
        alignment, and a pad slot's bin-0 cell holds row totals — the
        gather's zero-pad index must land past the real slots only."""
        src = self._bundle_src
        slot_of = self._bundle_slot_of
        num_slots = int(self._bundle.num_slots)

        def expand(h):
            k = h.shape[-1]
            hs = h[:num_slots]
            flat = jnp.concatenate(
                [hs.reshape(-1, k), jnp.zeros((1, k), h.dtype)], axis=0)
            hv = jnp.take(flat, src, axis=0)                 # (F, B_v, 3)
            slot_tot = jnp.sum(hs, axis=1)                   # (S, 3)
            hv0 = (jnp.take(slot_tot, slot_of, axis=0)
                   - jnp.sum(hv[:, 1:, :], axis=1))
            return hv.at[:, 0, :].set(hv0)

        return expand

    def _bundle_window(self, sc, feat, num_bin_pf):
        """Stored slot column -> virtual feature's bin values: member
        `feat` owns the window (off, off + nb - 1]; anything outside it
        (another member's bins, or slot bin 0) is the member's bin 0.
        THE decode rule — every stored->virtual column path (masked
        split_col, partitioned decode) must share it."""
        off = self._bundle_feat_off[feat]
        nb = num_bin_pf[feat]
        return jnp.where((sc > off) & (sc <= off + nb - 1), sc - off, 0)

    def _bundle_kwargs(self, bins, num_bin_pf):
        """Bundled-dataset hooks for build_tree_device: stored->virtual
        histogram expansion + slot-decoding split columns. Shared with
        the row-sharded parallel learners (parallel/learners.py)."""
        if getattr(self, "_bundle", None) is None:
            return {}
        fslot = self._bundle_feat_slot

        def split_col(feat):
            sc = jnp.take(bins, fslot[feat], axis=0).astype(jnp.int32)
            return self._bundle_window(sc, feat, num_bin_pf)

        return {"expand_fn": self._bundle_expand_fn(),
                "split_col_fn": split_col}

    def _bundle_partitioned_kwargs(self, num_bin_pf):
        """Bundled-dataset hooks for build_tree_partitioned: the same
        histogram expansion, plus a word-slice slot decode for the
        segment partition step (ordered_sparse_bin.hpp:25-133 is the
        reference's leaf-grouped sparse analog)."""
        if getattr(self, "_bundle", None) is None:
            return {}
        from ..ops.ordered_hist import unpack_feature
        fslot = self._bundle_feat_slot

        def decode(w_sl, feat):
            return self._bundle_window(unpack_feature(w_sl, fslot[feat]),
                                       feat, num_bin_pf)

        return {"expand_fn": self._bundle_expand_fn(), "decode_fn": decode}

    def _cache_hists(self, cfg):
        stored = self._bins.shape[0] * (4 if self._use_partitioned else 1)
        return cache_hists_fits(cfg, stored, self.max_bin)

    def _make_build_core(self, cfg, chunk):
        """The un-jitted builder closure — also consumed directly by the
        fused multi-iteration trainer (models/gbdt.py train_many), which
        embeds it inside its own scanned program."""
        cache_hists = self._cache_hists(cfg)
        if self._use_partitioned:
            from .partitioned import build_tree_partitioned
            base_p = functools.partial(
                build_tree_partitioned,
                num_leaves=int(cfg.num_leaves),
                max_bin=self.max_bin,
                params=self.params,
                max_depth=int(cfg.max_depth),
                f_real=self.num_features,
                cache_hists=cache_hists,
            )
            if getattr(self, "_bundle", None) is None:
                return base_p

            def bundled_p(words, grad, hess, inbag, fmask, num_bin_pf,
                          is_cat):
                return base_p(words, grad, hess, inbag, fmask,
                              num_bin_pf, is_cat,
                              **self._bundle_partitioned_kwargs(num_bin_pf))
            return bundled_p
        base = functools.partial(
            build_tree_device,
            num_leaves=int(cfg.num_leaves),
            max_bin=self.max_bin,
            params=self.params,
            max_depth=int(cfg.max_depth),
            row_chunk=chunk,
            cache_hists=cache_hists,
            compact_hist=self._use_compact,
            use_frontier=self._use_frontier,
        )
        if getattr(self, "_bundle", None) is None:
            return base

        def bundled(bins, grad, hess, inbag, fmask, num_bin_pf, is_cat):
            return base(bins, grad, hess, inbag, fmask, num_bin_pf, is_cat,
                        **self._bundle_kwargs(bins, num_bin_pf))
        return bundled

    def _make_build_fn(self, cfg, chunk):
        self._build_core = self._make_build_core(cfg, chunk)
        return jax.jit(self._build_core)

    def reset_config(self, config):
        self.config = config
        if self.train_set is not None:
            self.init(self.train_set)

    def _sample_features(self):
        """feature_fraction per tree (serial_tree_learner.cpp:160-165)."""
        cfg = self.config
        if cfg.feature_fraction >= 1.0:
            mask = np.ones(self.num_features, dtype=bool)
        else:
            used_cnt = int(self.num_features * cfg.feature_fraction)
            mask = self.random.sample_mask(self.num_features, max(used_cnt, 1))
        if self.f_pad != self.num_features:
            mask = np.concatenate(
                [mask, np.zeros(self.f_pad - self.num_features, bool)])
        return mask

    def apply_hist_mode(self):
        """Re-assert THIS learner's configured hist_mode on the process
        global before a build call or fused-program trace (a jit retrace
        on a new shape bucket resolves the mode at that moment, and a
        sibling Booster may have moved it since init)."""
        set_hist_mode(getattr(self, "_hist_mode_cfg", "auto"))

    def train_device(self, grad, hess, inbag=None):
        """Grow one tree entirely on device; NO host synchronization.

        Returns the raw device output dict of build_tree_device (tree
        arrays + (N_pad,) row->leaf partition). The caller decides when
        (and whether) to pull anything to host — see models/gbdt.py
        LazyTree.
        """
        self.apply_hist_mode()
        n, n_pad = self.num_data, self.n_pad
        grad = jnp.asarray(grad, dtype=jnp.float32)
        hess = jnp.asarray(hess, dtype=jnp.float32)
        if inbag is None:
            inbag = jnp.ones(n, dtype=jnp.float32)
        else:
            inbag = jnp.asarray(inbag, dtype=jnp.float32)
        if n_pad != n:
            grad = jnp.pad(grad, (0, n_pad - n))
            hess = jnp.pad(hess, (0, n_pad - n))
            inbag = jnp.pad(inbag, (0, n_pad - n))
        grad = self._place_rows(grad)
        hess = self._place_rows(hess)
        inbag = self._place_rows(inbag)
        fmask = self._place_rep(self._sample_features())
        # 1-core, 1-device runners deadlock the bincount callbacks on
        # this async-dispatched program (ops/histogram.py
        # host_callbacks_hazardous) — trace with callbacks disabled so
        # the builder resolves the segment kernel there. The guard only
        # matters on the first trace per shape bucket; the hazard is
        # process-stable so later cache hits see the same program.
        guard = (callbacks_disabled if host_callbacks_hazardous()
                 else contextlib.nullcontext)
        with guard():
            return self._build(self._bins, grad, hess, inbag, fmask,
                               self._num_bin_pf, self._is_cat)

    def train(self, grad, hess, inbag=None):
        """Grow one tree. grad/hess: (N,) device or host float32.

        Returns (Tree, row_leaf device array of shape (N,), leaf_values).
        """
        out = self.train_device(grad, hess, inbag)
        tree = self._to_host_tree(out)
        return tree, out["row_leaf"][:self.num_data], out["leaf_value"]

    def _to_host_tree(self, out, shrink=1.0) -> Tree:
        """ONE batched device->host transfer, then vectorized conversion.

        With jax's async dispatch this fetch is the FIRST blocking sync
        after the (guarded) builder launch — for the meshed learners a
        dead peer wedges the process right here, so the watchdog must
        bracket it (graftlint unguarded-collective; the guard is
        zero-overhead unarmed and feeds sync_wait_s when a timing sink
        is bound)."""
        from ..parallel.heartbeat import collective_guard
        with collective_guard("tree_host_fetch"):
            host = jax.device_get(
                {k: v for k, v in out.items() if k != "row_leaf"})
        return self.host_out_to_tree(host, shrink)

    def host_out_to_tree(self, host, shrink=1.0) -> Tree:
        """Convert one tree's host arrays (already fetched) into a Tree.
        Also used by the fused multi-iteration path on per-iteration
        slices of the scan-stacked outputs."""
        n_splits = int(host["n_splits"])
        num_leaves = n_splits + 1
        t = Tree(num_leaves)
        if n_splits == 0:
            return t
        ds = self.train_set
        sf = np.asarray(host["split_feature"])[:n_splits]
        tb = np.asarray(host["split_threshold_bin"])[:n_splits]
        t.split_feature = sf.astype(np.int32)
        t.split_feature_real = ds.real_feature_idx[sf].astype(np.int32)
        t.threshold_in_bin = tb.astype(np.int32)
        t.threshold = self._bin_value_table[sf, tb]
        t.decision_type = self._decision_type_host[sf]
        t.split_gain = np.asarray(host["split_gain"])[:n_splits].astype(np.float64)
        t.left_child = np.asarray(host["left_child"])[:n_splits]
        t.right_child = np.asarray(host["right_child"])[:n_splits]
        t.leaf_parent = np.asarray(host["leaf_parent"])[:num_leaves]
        t.leaf_value = (np.asarray(host["leaf_value"])[:num_leaves]
                        .astype(np.float64) * shrink)
        t.leaf_count = np.asarray(host["leaf_count"])[:num_leaves]
        t.internal_value = np.asarray(host["internal_value"])[:n_splits].astype(np.float64)
        t.internal_count = np.asarray(host["internal_count"])[:n_splits]
        return t


def create_tree_learner(learner_type, config):
    """Factory (src/treelearner/tree_learner.cpp:8-19). out_of_core=true
    swaps the serial learner for the block-store streaming learner
    (lightgbm_tpu/data/ooc_learner.py, docs/Out-of-Core.md); with
    tree_learner=data and num_machines>1 it becomes the gang learner
    over one shared store (lightgbm_tpu/data/ooc_parallel.py)."""
    if getattr(config, "out_of_core", False):
        if learner_type == "data" and int(getattr(config, "num_machines",
                                                  1)) > 1:
            from ..data.ooc_parallel import OutOfCoreGangLearner
            return OutOfCoreGangLearner(config)
        if learner_type != "serial":
            Log.fatal("out_of_core=true supports tree_learner=serial or "
                      "tree_learner=data with num_machines>1 (got %s); "
                      "feature/voting-parallel need per-shard feature "
                      "stores", learner_type)
        from ..data.ooc_learner import OutOfCoreTreeLearner
        return OutOfCoreTreeLearner(config)
    if learner_type == "serial":
        return SerialTreeLearner(config)
    try:
        from ..parallel.learners import (
            DataParallelTreeLearner, FeatureParallelTreeLearner,
            VotingParallelTreeLearner)
    except ImportError as e:
        Log.fatal("Parallel tree learner %s is unavailable: %s", learner_type, e)
    if learner_type == "data":
        return DataParallelTreeLearner(config)
    if learner_type == "feature":
        return FeatureParallelTreeLearner(config)
    if learner_type == "voting":
        return VotingParallelTreeLearner(config)
    Log.fatal("Unknown tree learner type %s", learner_type)
