"""Piece-wise linear leaf models (linear_tree=true).

Shi et al. (arXiv:1802.05640): after the histogram split search fixes a
tree's STRUCTURE, refit each leaf as a small ridge model over the
features on the leaf's root->leaf path instead of a single constant.
The second-order boosting objective makes this a weighted least-squares
problem per leaf — with hessian weights w_i and gradients g_i the leaf
model beta minimizes

    sum_i w_i (x_i . beta)^2 + 2 g_i (x_i . beta) + lambda |beta_f|^2

whose normal equations are (X^T W X + lambda I_f) beta = -X^T W' g
(x_i carries a leading 1 for the intercept; the intercept dimension is
NOT regularized; W' applies the in-bag mask to the gradient side).

Precision contract: accumulation runs on HOST in float64, over a fixed
`fit_chunk`-aligned row grid combined in ascending order — the same
chunk-grid discipline the histogram fold uses for its serial==streamed
bit-parity contract — so the resident (serial) and block-streamed
(out-of-core) learners accumulate the IDENTICAL normal equations and
the whole frontier solves as ONE stacked np.linalg.solve. Training data
lives as bins; features enter the fit as their bin representative
values (Feature::BinToValue), the same quantization the split search
saw.

Fallback rules (each leaf independently; `is_linear[leaf]=False` keeps
the builder's constant Newton value):

- no path features (the root leaf of a 0-split tree);
- fewer in-bag rows than `len(features) + 2`;
- zero accumulated hessian mass;
- a singular or non-finite solve (e.g. linear_lambda=0 on a leaf whose
  feature slice is constant).
"""

import numpy as np

from ..utils.log import Log


def leaf_path_features(split_feature, left_child, right_child,
                       leaf_parent, num_leaves, max_features):
    """Per-leaf distinct split features on the root->leaf path.

    Root-first order, deduplicated, capped at `max_features` (the first
    N distinct features seen walking DOWN from the root). Feature ids
    stay in whatever space `split_feature` uses (inner indices during
    training). Returns a list of (k_leaf,) int32 arrays, one per leaf.
    """
    n_splits = int(num_leaves) - 1
    if n_splits <= 0:
        return [np.zeros(0, np.int32)]
    parent = np.full(n_splits, -1, np.int32)
    for node in range(n_splits):
        for child in (int(left_child[node]), int(right_child[node])):
            if child >= 0:
                parent[child] = node
    out = []
    for leaf in range(int(num_leaves)):
        path = []
        node = int(leaf_parent[leaf])
        while node >= 0:
            path.append(int(split_feature[node]))
            node = parent[node]
        path.reverse()
        seen, feats = set(), []
        for f in path:
            if f not in seen:
                seen.add(f)
                feats.append(f)
                if len(feats) >= int(max_features):
                    break
        out.append(np.asarray(feats, np.int32))
    return out


def _leaf_segments(row_leaf_chunk):
    """(leaf_id, local_row_indices) groups for one chunk, rows ascending
    within each group (stable sort on the leaf key)."""
    order = np.argsort(row_leaf_chunk, kind="stable")
    sorted_rl = row_leaf_chunk[order]
    uniq, starts = np.unique(sorted_rl, return_index=True)
    bounds = np.append(starts, len(order))
    return [(int(uniq[i]), order[bounds[i]:bounds[i + 1]])
            for i in range(len(uniq))]


def fit_linear_leaves(leaf_feats, leaf_value, leaf_count, bin_value_table,
                      row_leaf, grad, hess, inbag, chunks, fit_chunk,
                      linear_lambda):
    """Fit every eligible leaf of one tree's frontier; one stacked solve.

    leaf_feats: list of per-leaf (k,) inner-feature arrays
        (`leaf_path_features` output, already capped).
    leaf_value/leaf_count: the builder's UNSHRUNK constant values and
        in-bag row counts, (L,).
    bin_value_table: (F, max_bin) float64 bin representative values.
    row_leaf: (N,) host row->leaf partition; grad/hess: (N,) float32.
    inbag: (N,) float in-bag weights or None (all-ones).
    chunks: RE-ITERABLE of (lo, hi, bins, base) host blocks covering
        rows [lo, hi) in ascending contiguous order; `bins` is
        [feat_arr, row_arr]-indexable with rows given relative to
        `base`. Block boundaries must land on the `fit_chunk` grid
        (the block store guarantees block_rows % device_row_chunk == 0;
        the resident path is one block).
    fit_chunk: canonical accumulation grid (device_row_chunk) — both
        learner paths MUST pass the same value for bit-parity.

    Returns (leaf_const, leaf_coeffs, is_linear, train_values), all in
    UNSHRUNK value space: intercepts (L,) f64, per-leaf coefficient
    arrays (list of (k,) f64), the per-leaf linear mask, and the (N,)
    f64 per-row tree output (linear where fitted, the constant value
    elsewhere).
    """
    num_leaves = len(leaf_feats)
    leaf_value = np.asarray(leaf_value, np.float64)
    counts = np.asarray(leaf_count, np.int64)
    kmax = max((len(f) for f in leaf_feats), default=0)
    n = int(row_leaf.shape[0])
    fit_chunk = max(1, int(fit_chunk))

    coeffs = [np.zeros(0, np.float64) for _ in range(num_leaves)]
    is_linear = np.zeros(num_leaves, bool)
    const = leaf_value.copy()
    cand = np.asarray([
        len(leaf_feats[l]) > 0 and counts[l] >= len(leaf_feats[l]) + 2
        for l in range(num_leaves)])
    if kmax == 0 or not cand.any():
        return const, coeffs, is_linear, leaf_value[row_leaf]

    grad = np.asarray(grad, np.float64)
    hess = np.asarray(hess, np.float64)
    if inbag is None:
        weight, gw = hess, grad
    else:
        inbag = np.asarray(inbag, np.float64)
        weight, gw = hess * inbag, grad * inbag

    # ---- pass 1: f64 normal equations over the canonical chunk grid
    norm = np.zeros((num_leaves, kmax + 1, kmax + 1), np.float64)
    rhs = np.zeros((num_leaves, kmax + 1), np.float64)
    for lo, hi, bins, base in chunks:
        for c0 in range(int(lo), int(hi), fit_chunk):
            c1 = min(c0 + fit_chunk, int(hi))
            for leaf, local in _leaf_segments(row_leaf[c0:c1]):
                if leaf >= num_leaves or not cand[leaf]:
                    continue
                rows = local + c0
                feats = leaf_feats[leaf]
                k = len(feats)
                ids = np.asarray(bins[feats[:, None],
                                      (rows - base)[None, :]])
                xs = bin_value_table[feats[:, None], ids]      # (k, m)
                xa = np.concatenate(
                    [np.ones((1, xs.shape[1]), np.float64), xs], axis=0)
                norm[leaf, :k + 1, :k + 1] += (xa * weight[rows]) @ xa.T
                rhs[leaf, :k + 1] += xa @ (-gw[rows])

    # zero hessian mass (fully bagged-out leaf): nothing to fit
    cand &= norm[:, 0, 0] > 0.0

    # ---- one stacked solve across the frontier
    idx = np.nonzero(cand)[0]
    if len(idx):
        mats = norm[idx].copy()
        vecs = rhs[idx].copy()
        lam = float(linear_lambda)
        for j, leaf in enumerate(idx):
            k = len(leaf_feats[leaf])
            diag = np.arange(1, k + 1)
            mats[j, diag, diag] += lam
            pad = np.arange(k + 1, kmax + 1)
            mats[j, pad, pad] = 1.0
        try:
            betas = np.linalg.solve(mats, vecs[..., None])[..., 0]
        except np.linalg.LinAlgError:
            # a singular leaf poisons the batched call: re-solve leaf
            # by leaf so only the degenerate ones fall back
            betas = np.full((len(idx), kmax + 1), np.nan)
            for j in range(len(idx)):
                try:
                    betas[j] = np.linalg.solve(mats[j], vecs[j])
                except np.linalg.LinAlgError:
                    pass
        for j, leaf in enumerate(idx):
            k = len(leaf_feats[leaf])
            beta = betas[j, :k + 1]
            if np.all(np.isfinite(beta)):
                const[leaf] = beta[0]
                coeffs[leaf] = beta[1:].copy()
                is_linear[leaf] = True

    if not is_linear.any():
        return const, coeffs, is_linear, leaf_value[row_leaf]

    # ---- pass 2: per-row tree output (chunk layout is free here — a
    # per-row dot over k terms reduces identically however rows batch)
    values = np.empty(n, np.float64)
    for lo, hi, bins, base in chunks:
        rl = row_leaf[int(lo):int(hi)]
        vals = leaf_value[rl]
        for leaf, local in _leaf_segments(rl):
            if leaf >= num_leaves or not is_linear[leaf]:
                continue
            rows = local + int(lo)
            feats = leaf_feats[leaf]
            ids = np.asarray(bins[feats[:, None], (rows - base)[None, :]])
            xs = bin_value_table[feats[:, None], ids]
            vals[local] = const[leaf] + coeffs[leaf] @ xs
        values[int(lo):int(hi)] = vals
    n_fit = int(is_linear.sum())
    Log.debug("linear leaves: fitted %d/%d leaves (kmax=%d)",
              n_fit, num_leaves, kmax)
    return const, coeffs, is_linear, values
