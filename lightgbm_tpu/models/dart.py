"""DART boosting (Dropouts meet Multiple Additive Regression Trees).

Reference: src/boosting/dart.hpp:17-142. Per iteration: select dropped
trees (binomial by drop_rate, plus-one fallback), subtract them from the
training score, train the new tree against the dropped score with
shrinkage lr/(k+lr), then re-normalize dropped trees to weight k/(k+lr).
"""

from ..utils.random import Random
from .gbdt import GBDT


class DART(GBDT):
    name = "dart"

    def __init__(self):
        super().__init__()
        self.drop_index = []
        self._random_for_drop = Random(4)

    def init(self, config, train_data, objective, training_metrics=()):
        super().init(config, train_data, objective, training_metrics)
        self._random_for_drop = Random(config.drop_seed)

    def train_one_iter(self, gradients=None, hessians=None, is_eval=True):
        if gradients is not None:
            # custom-gradient path never calls the dropping hook; clear the
            # drop set so Normalize is a no-op (the reference leaves the
            # previous iteration's drop_index_ in place here, which would
            # re-normalize stale trees — deliberately diverging).
            self.drop_index = []
        self._dropped_this_iter = False
        stop = super().train_one_iter(gradients, hessians, is_eval=False)
        self._normalize()
        if stop:
            return True
        if is_eval:
            return self.eval_and_check_early_stopping()
        return False

    def _score_for_boosting(self):
        if not self._dropped_this_iter:
            self._dropping_trees()
            self._dropped_this_iter = True
        return self.train_score_updater.score

    def _dropping_trees(self):
        """dart.hpp:85-110."""
        cfg = self.config
        self.drop_index = []
        if cfg.drop_rate > 1e-15:
            for i in range(self.iter):
                if self._random_for_drop.next_double() < cfg.drop_rate:
                    self.drop_index.append(i)
        if not self.drop_index:
            self.drop_index = [int(i) for i in self._random_for_drop.sample(self.iter, 1)]
        for i in self.drop_index:
            for k in range(self.num_class):
                tree = self.models[i * self.num_class + k]
                tree.shrinkage(-1.0)
                self.train_score_updater.add_score_by_tree(tree, k)
        self.shrinkage_rate = cfg.learning_rate / (
            cfg.learning_rate + float(len(self.drop_index)))

    def _normalize(self):
        """dart.hpp:111-135."""
        k_drop = float(len(self.drop_index))
        for i in self.drop_index:
            for k in range(self.num_class):
                tree = self.models[i * self.num_class + k]
                tree.shrinkage(self.shrinkage_rate)
                for updater in self.valid_score_updaters:
                    updater.add_score_by_tree(tree, k)
                tree.shrinkage(-k_drop / self.config.learning_rate)
                self.train_score_updater.add_score_by_tree(tree, k)
