"""Tree model: flat-array binary tree + text/JSON serialization.

Reference: include/LightGBM/tree.h:18-198, src/io/tree.cpp:24-231.
Leaves are encoded as `~leaf_index` (negative) in the child arrays.
The text format round-trips with the reference's model files (same
field names, same `Tree=i` block layout), which is the compatibility
contract exercised by the reference tests.

Unlike the reference (which grows node arrays via repeated Split calls)
the TPU build materializes a whole tree's arrays in one device program
(models/tree_learner.py) and wraps them here for serialization and
host-side prediction; prediction is vectorized over rows with a
node-pointer iteration instead of a per-row walk.
"""

import numpy as np

from ..utils import common
from ..utils.log import Log


class Tree:
    NUMERICAL = 0
    CATEGORICAL = 1

    def __init__(self, num_leaves=1):
        n = max(int(num_leaves), 1)
        self.num_leaves = n
        self.split_feature = np.zeros(max(n - 1, 0), dtype=np.int32)       # inner idx
        self.split_feature_real = np.zeros(max(n - 1, 0), dtype=np.int32)  # column idx
        self.threshold_in_bin = np.zeros(max(n - 1, 0), dtype=np.int32)
        self.threshold = np.zeros(max(n - 1, 0), dtype=np.float64)
        self.decision_type = np.zeros(max(n - 1, 0), dtype=np.int8)
        self.split_gain = np.zeros(max(n - 1, 0), dtype=np.float64)
        self.left_child = np.zeros(max(n - 1, 0), dtype=np.int32)
        self.right_child = np.zeros(max(n - 1, 0), dtype=np.int32)
        self.leaf_parent = np.full(n, -1, dtype=np.int32)
        self.leaf_value = np.zeros(n, dtype=np.float64)
        self.leaf_count = np.zeros(n, dtype=np.int32)
        self.internal_value = np.zeros(max(n - 1, 0), dtype=np.float64)
        self.internal_count = np.zeros(max(n - 1, 0), dtype=np.int32)
        # piece-wise linear leaves (models/linear_leaves.py, format
        # version 2): per-leaf ridge models over the leaf's path
        # features. `leaf_value` keeps the constant Newton fit — it is
        # the prediction for non-linear leaves AND the fallback for
        # rows with missing values in a linear leaf's feature slice.
        self.is_linear = False
        self.leaf_coeff_count = None    # (L,) int32
        self.leaf_const = None          # (L,) float64 intercepts
        self.leaf_coeff = None          # (L, C) float64, zero-padded
        self.leaf_coeff_feat = None     # (L, C) int32 real column idx
        self.leaf_coeff_feat_inner = None  # (L, C) int32 inner idx

    # ------------------------------------------------------------- training
    def shrinkage(self, rate):
        """Scale leaf outputs by the learning rate (tree.h:103-107).
        A linear leaf's output is linear in its coefficients, so the
        whole model block scales too (DART's drop/normalize relies on
        shrinkage being exactly multiplicative)."""
        self.leaf_value *= rate
        if self.is_linear:
            self.leaf_const *= rate
            self.leaf_coeff *= rate

    def set_linear(self, const, coeffs, is_linear, feats_inner,
                   real_feature_idx=None):
        """Attach per-leaf linear models (UN-scaled; call before
        shrinkage). coeffs/feats_inner are per-leaf ragged lists;
        leaves with is_linear False keep count 0 and predict their
        constant `leaf_value`. real_feature_idx maps inner -> column
        ids (None = identity, e.g. for loaded models)."""
        n = self.num_leaves
        width = max([len(c) for c in coeffs] + [1])
        self.leaf_coeff_count = np.zeros(n, np.int32)
        self.leaf_const = np.asarray(const, np.float64).copy()
        self.leaf_coeff = np.zeros((n, width), np.float64)
        self.leaf_coeff_feat = np.zeros((n, width), np.int32)
        self.leaf_coeff_feat_inner = np.zeros((n, width), np.int32)
        for leaf in range(n):
            if not is_linear[leaf]:
                continue
            k = len(coeffs[leaf])
            self.leaf_coeff_count[leaf] = k
            self.leaf_coeff[leaf, :k] = coeffs[leaf]
            inner = np.asarray(feats_inner[leaf], np.int32)
            self.leaf_coeff_feat_inner[leaf, :k] = inner
            self.leaf_coeff_feat[leaf, :k] = (
                inner if real_feature_idx is None
                else np.asarray(real_feature_idx)[inner].astype(np.int32))
        self.is_linear = bool(np.any(np.asarray(is_linear)))

    @property
    def max_depth(self):
        """Longest root->leaf path (for bounding vectorized traversal)."""
        if self.num_leaves <= 1:
            return 0
        depth = np.zeros(self.num_leaves - 1, dtype=np.int32)
        best = 1
        for node in range(self.num_leaves - 1):
            d = depth[node]
            for child in (self.left_child[node], self.right_child[node]):
                if child >= 0:
                    depth[child] = d + 1
                    best = max(best, d + 2)
                else:
                    best = max(best, d + 1)
        return best

    # ----------------------------------------------------------- prediction
    def get_leaf(self, x):
        """Vectorized leaf lookup on raw feature values.

        x: (N, num_total_features) float array. Returns (N,) leaf indices.
        Equivalent to tree.h:226-238 per row.
        """
        x = np.asarray(x, dtype=np.float64)
        n = x.shape[0]
        if self.num_leaves <= 1:
            return np.zeros(n, dtype=np.int32)
        node = np.zeros(n, dtype=np.int32)
        active = node >= 0
        for _ in range(self.max_depth + 1):
            if not active.any():
                break
            nd = node[active]
            feat = self.split_feature_real[nd]
            thr = self.threshold[nd]
            dt = self.decision_type[nd]
            fval = x[active, feat]
            # NaN routes RIGHT everywhere: numeric via `<=` being False,
            # categorical explicitly (a missing value is not a category
            # id — without the isnan mask the nan_to_num cast would
            # silently match category 0)
            go_left = np.where(dt == self.CATEGORICAL,
                               (np.nan_to_num(fval).astype(np.int64)
                                == thr.astype(np.int64)) & ~np.isnan(fval),
                               fval <= thr)
            nxt = np.where(go_left, self.left_child[nd], self.right_child[nd])
            node[active] = nxt
            active = node >= 0
        return (~node).astype(np.int32)

    def predict(self, x):
        leaf = self.get_leaf(x)
        base = self.leaf_value[leaf]
        if not self.is_linear:
            return base
        return self._linear_values(np.asarray(x, np.float64), leaf, base)

    def _linear_values(self, x, leaf, fallback):
        """Per-row linear-leaf outputs on raw feature values; host f64.
        Rows whose leaf model touches a NaN feature fall back to the
        leaf's constant value (a missing value has no coordinate to
        enter the dot product)."""
        cnt = self.leaf_coeff_count[leaf]                     # (N,)
        feats = self.leaf_coeff_feat[leaf]                    # (N, C)
        coef = self.leaf_coeff[leaf]                          # (N, C)
        xf = x[np.arange(x.shape[0])[:, None], feats]         # (N, C)
        valid = np.arange(coef.shape[1])[None, :] < cnt[:, None]
        has_nan = np.any(np.isnan(xf) & valid, axis=1)
        # sequential (not np.sum) accumulation over coefficient slots:
        # np.sum's pairwise association depends on the axis LENGTH, so
        # the serving predictor's COEF_PAD-padded copy of this reduce
        # would round differently. A left-to-right chain makes trailing
        # zero slots exact no-ops — serving matches bit-for-bit.
        lin = self.leaf_const[leaf].copy()
        for j in range(coef.shape[1]):
            lin += np.where(valid[:, j] & ~np.isnan(xf[:, j]),
                            coef[:, j] * xf[:, j], 0.0)
        return np.where((cnt > 0) & ~has_nan, lin, fallback)

    def get_leaf_by_bins(self, bins):
        """Leaf lookup on a binned (F, N) matrix (tree.h:211-224); used to
        add scores on aligned train/valid datasets."""
        n = bins.shape[1]
        if self.num_leaves <= 1:
            return np.zeros(n, dtype=np.int32)
        node = np.zeros(n, dtype=np.int32)
        active = node >= 0
        for _ in range(self.max_depth + 1):
            if not active.any():
                break
            nd = node[active]
            feat = self.split_feature[nd]
            thr = self.threshold_in_bin[nd]
            dt = self.decision_type[nd]
            fval = bins[feat, np.nonzero(active)[0]].astype(np.int64)
            go_left = np.where(dt == self.CATEGORICAL, fval == thr, fval <= thr)
            node[active] = np.where(go_left, self.left_child[nd], self.right_child[nd])
            active = node >= 0
        return (~node).astype(np.int32)

    def predict_by_bins(self, bins, bin_values=None):
        """Per-row outputs on a binned (F, N) matrix. Linear leaves need
        `bin_values` — the dataset's (F, max_bin) f64 bin representative
        table (CoreDataset.bin_value_table()) — because a dot product
        needs VALUES, not bin ids; feature ids here are INNER indices
        (leaf_coeff_feat_inner), matching `split_feature`."""
        leaf = self.get_leaf_by_bins(bins)
        base = self.leaf_value[leaf]
        if not self.is_linear:
            return base
        if bin_values is None:
            Log.fatal("scoring a linear tree in bin space needs the "
                      "dataset's bin_value_table")
        cnt = self.leaf_coeff_count[leaf]                     # (N,)
        feats = self.leaf_coeff_feat_inner[leaf]              # (N, C)
        coef = self.leaf_coeff[leaf]                          # (N, C)
        rows = np.arange(leaf.shape[0])
        ids = np.asarray(bins[feats, rows[:, None]])          # (N, C)
        xf = bin_values[feats, ids]
        valid = np.arange(coef.shape[1])[None, :] < cnt[:, None]
        lin = self.leaf_const[leaf] + np.sum(
            np.where(valid, coef * xf, 0.0), axis=1)
        return np.where(cnt > 0, lin, base)

    # -------------------------------------------------------- serialization
    def to_string(self):
        """Text block (tree.cpp ToString)."""
        n = self.num_leaves
        lines = [
            f"num_leaves={n}",
            "split_feature=" + common.array_to_string(self.split_feature_real[:n - 1]),
            "split_gain=" + common.array_to_string(self.split_gain[:n - 1].astype(np.float64)),
            "threshold=" + common.array_to_string(self.threshold[:n - 1].astype(np.float64)),
            "decision_type=" + common.array_to_string(self.decision_type[:n - 1]),
            "left_child=" + common.array_to_string(self.left_child[:n - 1]),
            "right_child=" + common.array_to_string(self.right_child[:n - 1]),
            "leaf_parent=" + common.array_to_string(self.leaf_parent[:n]),
            "leaf_value=" + common.array_to_string(self.leaf_value[:n].astype(np.float64)),
            "leaf_count=" + common.array_to_string(self.leaf_count[:n]),
            "internal_value=" + common.array_to_string(self.internal_value[:n - 1].astype(np.float64)),
            "internal_count=" + common.array_to_string(self.internal_count[:n - 1]),
        ]
        if self.is_linear:
            # format version 2 coefficient block (docs/Linear-Trees.md):
            # ragged per-leaf models flattened in leaf order; repr-
            # precision doubles make save->load bit-exact like
            # leaf_value above
            flat_feat, flat_coef = [], []
            for leaf in range(n):
                k = int(self.leaf_coeff_count[leaf])
                flat_feat.extend(int(v) for v in self.leaf_coeff_feat[leaf, :k])
                flat_coef.extend(float(v) for v in self.leaf_coeff[leaf, :k])
            lines.append("is_linear=1")
            lines.append("leaf_const=" + common.array_to_string(
                self.leaf_const[:n].astype(np.float64)))
            lines.append("num_leaf_coeff=" + common.array_to_string(
                self.leaf_coeff_count[:n]))
            lines.append("leaf_coeff_feature=" + common.array_to_string(
                np.asarray(flat_feat, np.int32)))
            lines.append("leaf_coeff=" + common.array_to_string(
                np.asarray(flat_coef, np.float64)))
        return "\n".join(lines) + "\n"

    REQUIRED_KEYS = ("num_leaves", "split_feature", "split_gain", "threshold",
                     "left_child", "right_child", "leaf_parent", "leaf_value",
                     "internal_value", "internal_count", "leaf_count",
                     "decision_type")
    LINEAR_KEYS = ("is_linear", "leaf_const", "num_leaf_coeff",
                   "leaf_coeff_feature", "leaf_coeff")

    @classmethod
    def from_string(cls, s, format_version=1):
        """Parse a `Tree=i` block (tree.cpp:192-230).

        Forward-compat contract: an unknown key is a hard error — a
        newer writer's section must never be silently dropped (the
        model would load and mis-predict). Coefficient blocks are only
        legal when the file header declared format_version >= 2."""
        kv = {}
        for line in s.split("\n"):
            parts = line.split("=", 1)
            if len(parts) == 2 and parts[0].strip() and parts[1].strip():
                kv[parts[0].strip()] = parts[1].strip()
        required = cls.REQUIRED_KEYS
        for key in required:
            if key not in kv:
                Log.fatal("Tree model string format error: missing %s", key)
        for key in kv:
            if key not in required and key not in cls.LINEAR_KEYS:
                Log.fatal("Tree model string format error: unknown section "
                          "%r — this model was written by a newer format "
                          "version than this reader supports", key)
            if key in cls.LINEAR_KEYS and format_version < 2:
                Log.fatal("Tree model string format error: coefficient "
                          "section %r requires format_version>=2 but the "
                          "model header declares version %d", key,
                          format_version)
        n = int(kv["num_leaves"])
        t = cls(n)
        if n > 1:
            t.left_child = common.string_to_array(kv["left_child"], int)
            t.right_child = common.string_to_array(kv["right_child"], int)
            t.split_feature_real = common.string_to_array(kv["split_feature"], int)
            t.split_feature = t.split_feature_real.copy()  # inner map unknown after load
            t.threshold = common.string_to_array(kv["threshold"], float)
            t.split_gain = common.string_to_array(kv["split_gain"], float)
            t.internal_count = common.string_to_array(kv["internal_count"], int)
            t.internal_value = common.string_to_array(kv["internal_value"], float)
            t.decision_type = common.string_to_array(kv["decision_type"], int).astype(np.int8)
        t.leaf_count = common.string_to_array(kv["leaf_count"], int)
        t.leaf_parent = common.string_to_array(kv["leaf_parent"], int)
        t.leaf_value = common.string_to_array(kv["leaf_value"], float)
        if kv.get("is_linear") == "1":
            counts = common.string_to_array(kv["num_leaf_coeff"], int)
            if len(counts) != n:
                Log.fatal("Tree model string format error: num_leaf_coeff "
                          "has %d entries for %d leaves", len(counts), n)
            flat_feat = (common.string_to_array(kv["leaf_coeff_feature"], int)
                         if "leaf_coeff_feature" in kv
                         else np.zeros(0, np.int32))
            flat_coef = (common.string_to_array(kv["leaf_coeff"], float)
                         if "leaf_coeff" in kv else np.zeros(0, np.float64))
            total = int(counts.sum())
            if len(flat_feat) != total or len(flat_coef) != total:
                Log.fatal("Tree model string format error: coefficient "
                          "block length mismatch (%d features, %d coeffs, "
                          "counts sum %d)", len(flat_feat), len(flat_coef),
                          total)
            const = common.string_to_array(kv["leaf_const"], float)
            offs = np.concatenate([[0], np.cumsum(counts)])
            coeffs = [flat_coef[offs[i]:offs[i + 1]] for i in range(n)]
            feats = [flat_feat[offs[i]:offs[i + 1]] for i in range(n)]
            # inner map unknown after load (same convention as
            # split_feature above): inner ids default to column ids
            t.set_linear(const, coeffs, counts > 0, feats)
        return t

    def to_json(self):
        out = [f'"num_leaves":{self.num_leaves},']
        out.append(f'"tree_structure":{self._node_to_json(0 if self.num_leaves > 1 else ~0)}')
        return "\n".join(out) + "\n"

    def _node_to_json(self, index):
        if index >= 0 and self.num_leaves > 1:
            dt = "no_greater" if self.decision_type[index] == 0 else "is"
            return (
                "{\n"
                f'"split_index":{index},\n'
                f'"split_feature":{int(self.split_feature_real[index])},\n'
                f'"split_gain":{self.split_gain[index]:g},\n'
                f'"threshold":{self.threshold[index]:g},\n'
                f'"decision_type":"{dt}",\n'
                f'"internal_value":{self.internal_value[index]:g},\n'
                f'"internal_count":{int(self.internal_count[index])},\n'
                f'"left_child":{self._node_to_json(self.left_child[index])},\n'
                f'"right_child":{self._node_to_json(self.right_child[index])}\n'
                "}"
            )
        index = ~index if index < 0 else index
        linear = ""
        if self.is_linear and self.leaf_coeff_count[index] > 0:
            k = int(self.leaf_coeff_count[index])
            coefs = ",".join(f"{v:g}" for v in self.leaf_coeff[index, :k])
            feats = ",".join(str(int(v))
                             for v in self.leaf_coeff_feat[index, :k])
            linear = (f',\n"leaf_const":{self.leaf_const[index]:g},\n'
                      f'"leaf_coeff":[{coefs}],\n'
                      f'"leaf_coeff_feature":[{feats}]')
        return (
            "{\n"
            f'"leaf_index":{index},\n'
            f'"leaf_parent":{int(self.leaf_parent[index])},\n'
            f'"leaf_value":{self.leaf_value[index]:g},\n'
            f'"leaf_count":{int(self.leaf_count[index])}'
            f"{linear}\n"
            "}"
        )
