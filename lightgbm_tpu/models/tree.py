"""Tree model: flat-array binary tree + text/JSON serialization.

Reference: include/LightGBM/tree.h:18-198, src/io/tree.cpp:24-231.
Leaves are encoded as `~leaf_index` (negative) in the child arrays.
The text format round-trips with the reference's model files (same
field names, same `Tree=i` block layout), which is the compatibility
contract exercised by the reference tests.

Unlike the reference (which grows node arrays via repeated Split calls)
the TPU build materializes a whole tree's arrays in one device program
(models/tree_learner.py) and wraps them here for serialization and
host-side prediction; prediction is vectorized over rows with a
node-pointer iteration instead of a per-row walk.
"""

import numpy as np

from ..utils import common
from ..utils.log import Log


class Tree:
    NUMERICAL = 0
    CATEGORICAL = 1

    def __init__(self, num_leaves=1):
        n = max(int(num_leaves), 1)
        self.num_leaves = n
        self.split_feature = np.zeros(max(n - 1, 0), dtype=np.int32)       # inner idx
        self.split_feature_real = np.zeros(max(n - 1, 0), dtype=np.int32)  # column idx
        self.threshold_in_bin = np.zeros(max(n - 1, 0), dtype=np.int32)
        self.threshold = np.zeros(max(n - 1, 0), dtype=np.float64)
        self.decision_type = np.zeros(max(n - 1, 0), dtype=np.int8)
        self.split_gain = np.zeros(max(n - 1, 0), dtype=np.float64)
        self.left_child = np.zeros(max(n - 1, 0), dtype=np.int32)
        self.right_child = np.zeros(max(n - 1, 0), dtype=np.int32)
        self.leaf_parent = np.full(n, -1, dtype=np.int32)
        self.leaf_value = np.zeros(n, dtype=np.float64)
        self.leaf_count = np.zeros(n, dtype=np.int32)
        self.internal_value = np.zeros(max(n - 1, 0), dtype=np.float64)
        self.internal_count = np.zeros(max(n - 1, 0), dtype=np.int32)

    # ------------------------------------------------------------- training
    def shrinkage(self, rate):
        """Scale leaf outputs by the learning rate (tree.h:103-107)."""
        self.leaf_value *= rate

    @property
    def max_depth(self):
        """Longest root->leaf path (for bounding vectorized traversal)."""
        if self.num_leaves <= 1:
            return 0
        depth = np.zeros(self.num_leaves - 1, dtype=np.int32)
        best = 1
        for node in range(self.num_leaves - 1):
            d = depth[node]
            for child in (self.left_child[node], self.right_child[node]):
                if child >= 0:
                    depth[child] = d + 1
                    best = max(best, d + 2)
                else:
                    best = max(best, d + 1)
        return best

    # ----------------------------------------------------------- prediction
    def get_leaf(self, x):
        """Vectorized leaf lookup on raw feature values.

        x: (N, num_total_features) float array. Returns (N,) leaf indices.
        Equivalent to tree.h:226-238 per row.
        """
        x = np.asarray(x, dtype=np.float64)
        n = x.shape[0]
        if self.num_leaves <= 1:
            return np.zeros(n, dtype=np.int32)
        node = np.zeros(n, dtype=np.int32)
        active = node >= 0
        for _ in range(self.max_depth + 1):
            if not active.any():
                break
            nd = node[active]
            feat = self.split_feature_real[nd]
            thr = self.threshold[nd]
            dt = self.decision_type[nd]
            fval = x[active, feat]
            # NaN routes RIGHT everywhere: numeric via `<=` being False,
            # categorical explicitly (a missing value is not a category
            # id — without the isnan mask the nan_to_num cast would
            # silently match category 0)
            go_left = np.where(dt == self.CATEGORICAL,
                               (np.nan_to_num(fval).astype(np.int64)
                                == thr.astype(np.int64)) & ~np.isnan(fval),
                               fval <= thr)
            nxt = np.where(go_left, self.left_child[nd], self.right_child[nd])
            node[active] = nxt
            active = node >= 0
        return (~node).astype(np.int32)

    def predict(self, x):
        return self.leaf_value[self.get_leaf(x)]

    def get_leaf_by_bins(self, bins):
        """Leaf lookup on a binned (F, N) matrix (tree.h:211-224); used to
        add scores on aligned train/valid datasets."""
        n = bins.shape[1]
        if self.num_leaves <= 1:
            return np.zeros(n, dtype=np.int32)
        node = np.zeros(n, dtype=np.int32)
        active = node >= 0
        for _ in range(self.max_depth + 1):
            if not active.any():
                break
            nd = node[active]
            feat = self.split_feature[nd]
            thr = self.threshold_in_bin[nd]
            dt = self.decision_type[nd]
            fval = bins[feat, np.nonzero(active)[0]].astype(np.int64)
            go_left = np.where(dt == self.CATEGORICAL, fval == thr, fval <= thr)
            node[active] = np.where(go_left, self.left_child[nd], self.right_child[nd])
            active = node >= 0
        return (~node).astype(np.int32)

    def predict_by_bins(self, bins):
        return self.leaf_value[self.get_leaf_by_bins(bins)]

    # -------------------------------------------------------- serialization
    def to_string(self):
        """Text block (tree.cpp ToString)."""
        n = self.num_leaves
        lines = [
            f"num_leaves={n}",
            "split_feature=" + common.array_to_string(self.split_feature_real[:n - 1]),
            "split_gain=" + common.array_to_string(self.split_gain[:n - 1].astype(np.float64)),
            "threshold=" + common.array_to_string(self.threshold[:n - 1].astype(np.float64)),
            "decision_type=" + common.array_to_string(self.decision_type[:n - 1]),
            "left_child=" + common.array_to_string(self.left_child[:n - 1]),
            "right_child=" + common.array_to_string(self.right_child[:n - 1]),
            "leaf_parent=" + common.array_to_string(self.leaf_parent[:n]),
            "leaf_value=" + common.array_to_string(self.leaf_value[:n].astype(np.float64)),
            "leaf_count=" + common.array_to_string(self.leaf_count[:n]),
            "internal_value=" + common.array_to_string(self.internal_value[:n - 1].astype(np.float64)),
            "internal_count=" + common.array_to_string(self.internal_count[:n - 1]),
        ]
        return "\n".join(lines) + "\n"

    @classmethod
    def from_string(cls, s):
        """Parse a `Tree=i` block (tree.cpp:192-230)."""
        kv = {}
        for line in s.split("\n"):
            parts = line.split("=", 1)
            if len(parts) == 2 and parts[0].strip() and parts[1].strip():
                kv[parts[0].strip()] = parts[1].strip()
        required = ("num_leaves", "split_feature", "split_gain", "threshold",
                    "left_child", "right_child", "leaf_parent", "leaf_value",
                    "internal_value", "internal_count", "leaf_count", "decision_type")
        for key in required:
            if key not in kv:
                Log.fatal("Tree model string format error: missing %s", key)
        n = int(kv["num_leaves"])
        t = cls(n)
        if n > 1:
            t.left_child = common.string_to_array(kv["left_child"], int)
            t.right_child = common.string_to_array(kv["right_child"], int)
            t.split_feature_real = common.string_to_array(kv["split_feature"], int)
            t.split_feature = t.split_feature_real.copy()  # inner map unknown after load
            t.threshold = common.string_to_array(kv["threshold"], float)
            t.split_gain = common.string_to_array(kv["split_gain"], float)
            t.internal_count = common.string_to_array(kv["internal_count"], int)
            t.internal_value = common.string_to_array(kv["internal_value"], float)
            t.decision_type = common.string_to_array(kv["decision_type"], int).astype(np.int8)
        t.leaf_count = common.string_to_array(kv["leaf_count"], int)
        t.leaf_parent = common.string_to_array(kv["leaf_parent"], int)
        t.leaf_value = common.string_to_array(kv["leaf_value"], float)
        return t

    def to_json(self):
        out = [f'"num_leaves":{self.num_leaves},']
        out.append(f'"tree_structure":{self._node_to_json(0 if self.num_leaves > 1 else ~0)}')
        return "\n".join(out) + "\n"

    def _node_to_json(self, index):
        if index >= 0 and self.num_leaves > 1:
            dt = "no_greater" if self.decision_type[index] == 0 else "is"
            return (
                "{\n"
                f'"split_index":{index},\n'
                f'"split_feature":{int(self.split_feature_real[index])},\n'
                f'"split_gain":{self.split_gain[index]:g},\n'
                f'"threshold":{self.threshold[index]:g},\n'
                f'"decision_type":"{dt}",\n'
                f'"internal_value":{self.internal_value[index]:g},\n'
                f'"internal_count":{int(self.internal_count[index])},\n'
                f'"left_child":{self._node_to_json(self.left_child[index])},\n'
                f'"right_child":{self._node_to_json(self.right_child[index])}\n'
                "}"
            )
        index = ~index if index < 0 else index
        return (
            "{\n"
            f'"leaf_index":{index},\n'
            f'"leaf_parent":{int(self.leaf_parent[index])},\n'
            f'"leaf_value":{self.leaf_value[index]:g},\n'
            f'"leaf_count":{int(self.leaf_count[index])}\n'
            "}"
        )
