"""Partitioned (leaf-contiguous) tree builder: histogram cost scales
with leaf size, not dataset size.

Reference: the combination of DataPartition (data_partition.hpp:17-201,
contiguous per-leaf row indices), OrderedSparseBin's leaf-grouped
re-partitioning (ordered_sparse_bin.hpp:25-133) and the ordered-
gradient gathers of SerialTreeLearner::BeforeFindBestSplit
(serial_tree_learner.cpp:236-337) — the reference's machinery for
making per-leaf histogram cost proportional to rows-in-leaf.

This is the heaviest of the three histogram engines (see
docs/Histogram-Engine.md): the masked builder streams ALL N rows per
split (O(N), exact), the gather-compacted builder (the dense default,
ops/histogram.py compacted_histograms) gathers the child's rows into a
bucket-padded buffer (O(child rows), no layout change), and this
builder goes one further by keeping the bin matrix PHYSICALLY sorted
by leaf — no per-split O(N) mask/rank pass at all, at the cost of
moving the packed words on every split. All three share the same
per-chunk histogram kernel (ops/histogram.py _hist_chunk: one-hot MXU
contraction on TPU, segment-sum scatter-add on CPU):

- rows live in packed words (4 features/int32, ops/ordered_hist.py);
  a leaf is a position range [seg_begin[leaf], +seg_cnt[leaf]);
- a split stable-partitions the segment with one vectorized prefix-sum
  pass + one scatter + gathers (ops/partition.py) — the TPU analog of
  DataPartition::Split's per-thread buffers + prefix-sum copy-back;
- the smaller child's histogram streams only the chunks covering its
  segment (geometric-bucketed `lax.switch`, ops/ordered_hist.py);
  the larger child is parent - smaller, as everywhere else.

Semantics (split scans, gain formulas, tie-breaks, depth guard,
subtraction trick, leaf-wise best-leaf order) are identical to the
masked builder; only the row-summation ORDER inside a histogram
differs, so f32 round-off can differ in the last ulps. The serial
masked builder remains the reference point for the exact
serial == parallel equality tests (tests/test_parallel.py).

Everything runs inside one `lax.fori_loop` — no host round-trips — so
the fused multi-iteration trainer (models/gbdt.py train_many) embeds
this builder exactly like the masked one.
"""

import jax
import jax.numpy as jnp

from ..ops.ordered_hist import (bucket_sizes, cover_index,
                                segment_histograms, unpack_feature,
                                window_start)
from ..ops.pallas_hist import HIST_CHUNK
from ..ops.partition import (apply_partition, invert_permutation,
                             split_destinations)
from ..ops.split import SplitParams, find_best_split, K_MIN_SCORE
from .tree_learner import apply_tree_split, init_split_state, write_candidate


def _partition_segment(words, ghc, perm, seg_b, seg_c, feat, thr, cat,
                       decode_fn):
    """Stable-partition the segment [seg_b, seg_b+seg_c) by the split
    decision, touching only the geometric chunk bucket covering it.

    The permutation is identical to a full-array stable partition —
    split_destinations runs on the slice with slice-local bounds, where
    the segment's relative order is the global one — but the
    slice/gather/write-back traffic is O(bucket), not O(N): ~38x less
    movement per 63-leaf tree. Chunk-cover dispatch is shared with
    segment_histograms (ops/ordered_hist.py cover_index/window_start).

    decode_fn(word_slice, feat) -> the VIRTUAL feature's bin column of
    the slice (plain unpack for unbundled data; slot decode for EFB).

    Returns (words, ghc, perm, n_left) with n_left counting ALL left
    rows of the segment (in-bag + out-of-bag + padding).
    """
    w, n = words.shape
    n_chunks = n // HIST_CHUNK
    idx, c_first = cover_index(seg_b, seg_c, n_chunks)

    def make_branch(bk):
        length = bk * HIST_CHUNK

        def branch(seg_b, seg_c):
            start = window_start(c_first, bk, n_chunks)
            w_sl = jax.lax.dynamic_slice(words, (jnp.int32(0), start),
                                         (w, length))
            g_sl = jax.lax.dynamic_slice(ghc, (jnp.int32(0), start),
                                         (3, length))
            p_sl = jax.lax.dynamic_slice(perm, (start,), (length,))
            col = decode_fn(w_sl, feat)
            go_left = jnp.where(cat, col == thr, col <= thr)
            dest, n_left = split_destinations(go_left, seg_b - start, seg_c)
            src = invert_permutation(dest)
            w_new, g_new, p_new = apply_partition(src, w_sl, g_sl, p_sl)
            return (jax.lax.dynamic_update_slice(
                        words, w_new, (jnp.int32(0), start)),
                    jax.lax.dynamic_update_slice(
                        ghc, g_new, (jnp.int32(0), start)),
                    jax.lax.dynamic_update_slice(perm, p_new, (start,)),
                    n_left)

        return branch

    return jax.lax.switch(idx, [make_branch(b) for b in bucket_sizes(n_chunks)],
                          seg_b, seg_c)


def _identity(x):
    return x


def build_tree_partitioned(words, grad, hess, inbag, feature_mask,
                           num_bin_pf, is_cat,
                           *, num_leaves, max_bin, params: SplitParams,
                           max_depth, f_real, hist_reduce_fn=_identity,
                           expand_fn=_identity, decode_fn=None,
                           cache_hists=True, evaluate_fn=None,
                           sum_psum_fn=_identity):
    """Grow one leaf-wise tree on device over the packed-word layout.

    Args:
      words: (W, N_pad) int32 packed STORED bin columns,
        N_pad % HIST_CHUNK == 0. Unbundled: stored == virtual features,
        4 * W == the padded virtual feature count. Bundled (EFB): the
        words pack the SLOT matrix; histograms build and cache in slot
        space and `expand_fn`/`decode_fn` bridge to virtual features.
      grad, hess, inbag: (N_pad,) float32 (pad rows: inbag == 0).
      feature_mask: (F_v,) bool; num_bin_pf: (F_v,) int32;
      is_cat: (F_v,) bool — all VIRTUAL-feature space (== 4 * W only
        when unbundled).
      num_leaves, max_bin, params, max_depth, f_real: static config.
      expand_fn: stored->virtual histogram expansion for bundled
        datasets (same hook as build_tree_device; identity otherwise).
        Subtraction/caching stay in stored space — expansion happens
        only at split evaluation.
      decode_fn: (word_slice, virtual_feat) -> int32 bin column of the
        slice; defaults to a plain word unpack (unbundled).
      cache_hists: False = memory-bounded mode (histogram_pool_size
        exceeded): no (L, S, B, 3) cache — both children's segment
        histograms are computed directly at each split (cost at most
        the parent's row count instead of the smaller child's).
      evaluate_fn: optional (hist3, sum_g, sum_h, cnt) -> SplitInfo
        override, same contract as build_tree_device's: the voting
        learner keeps hist_reduce_fn=identity (LOCAL histograms) and
        does its own selective reduction here.
      sum_psum_fn: reduces the scalar root sums across row shards
        (identity whenever hist_reduce_fn already globalized them).
      hist_reduce_fn: reduction applied to every segment histogram —
        `lax.psum` over the row-shard axis for the data-parallel
        learner (the reference's histogram ReduceScatter sync point,
        data_parallel_tree_learner.cpp:155-157). Called OUTSIDE the
        bucketed lax.switch, so every shard executes the collective in
        lockstep even when their segment buckets differ. Plain f32
        psum: every shard sees the identical reduced histogram, so all
        shards take identical splits (cross-shard consistency); unlike
        the masked builder's Kahan pair_allreduce this does NOT
        guarantee last-ulp equality with the SERIAL partitioned
        builder's summation order.

    Returns the same output dict as build_tree_device (tree arrays +
    original-order row->leaf partition, local rows under shard_map).
    """
    w, n_pad = words.shape
    l = num_leaves
    b = max_bin
    f32 = jnp.float32
    s_pad = 4 * w  # STORED rows in the packed words (== padded F_v
    #                only when unbundled)
    if decode_fn is None:
        def decode_fn(w_sl, feat):
            return unpack_feature(w_sl, feat)
        assert f_real <= s_pad

    if evaluate_fn is None:
        def evaluate_fn(hist3, sum_g, sum_h, cnt):
            return find_best_split(hist3, sum_g, sum_h, cnt,
                                   num_bin_pf, is_cat, feature_mask,
                                   params)

    def scan_leaf(hist3, sum_g, sum_h, cnt):
        return evaluate_fn(expand_fn(hist3), sum_g, sum_h, cnt)

    g_in = grad * inbag
    h_in = hess * inbag
    ghc0 = jnp.stack([g_in, h_in, inbag], axis=0)  # (3, N_pad)

    def leaf_histogram(words_c, ghc_c, begin, cnt):
        return hist_reduce_fn(
            segment_histograms(words_c, ghc_c, begin, cnt, b, s_pad))

    # ---- root ----------------------------------------------------------
    hist_root = leaf_histogram(words, ghc0, jnp.int32(0), jnp.int32(n_pad))
    # root sums from the histogram: feature 0's bins partition the rows
    root_g = sum_psum_fn(jnp.sum(hist_root[0, :, 0]))
    root_h = sum_psum_fn(jnp.sum(hist_root[0, :, 1]))
    root_c = sum_psum_fn(jnp.sum(hist_root[0, :, 2]))
    root_split = scan_leaf(hist_root, root_g, root_h, root_c)

    state = init_split_state(l, root_split, root_c)
    state["words"] = words
    state["ghc"] = ghc0
    state["perm"] = jnp.arange(n_pad, dtype=jnp.int32)  # position -> orig row
    state["pos_leaf"] = jnp.zeros(n_pad, dtype=jnp.int32)
    state["seg_begin"] = jnp.zeros(l, dtype=jnp.int32)
    # FULL row counts (in-bag + oob + pad), not the tree's in-bag counts
    state["seg_cnt"] = jnp.zeros(l, dtype=jnp.int32).at[0].set(n_pad)
    if cache_hists:
        state["hist_cache"] = (jnp.zeros((l, s_pad, b, 3), dtype=f32)
                               .at[0].set(hist_root))

    def body(i, st):
        best_leaf = jnp.argmax(st["best_gain"]).astype(jnp.int32)
        gain = st["best_gain"][best_leaf]
        do = jnp.logical_and(jnp.logical_not(st["done"]), gain > 0.0)

        def no_split(st):
            st = dict(st)
            st["done"] = jnp.asarray(True)
            return st

        def do_split(st):
            st = dict(st)
            st, node, right_id, feat, thr = apply_tree_split(
                st, i, best_leaf, gain, l)

            # ---- physical re-partition (DataPartition::Split),
            # bucketed to the segment's chunk range
            seg_b = st["seg_begin"][best_leaf]
            seg_c = st["seg_cnt"][best_leaf]
            st["words"], st["ghc"], st["perm"], n_left = _partition_segment(
                st["words"], st["ghc"], st["perm"], seg_b, seg_c,
                feat, thr, is_cat[feat], decode_fn)
            st["seg_begin"] = st["seg_begin"].at[right_id].set(seg_b + n_left)
            st["seg_cnt"] = (st["seg_cnt"].at[best_leaf].set(n_left)
                             .at[right_id].set(seg_c - n_left))
            pos = jnp.arange(n_pad, dtype=jnp.int32)
            st["pos_leaf"] = jnp.where(
                (pos >= seg_b + n_left) & (pos < seg_b + seg_c),
                right_id, st["pos_leaf"])

            if cache_hists:
                # ---- smaller-child histogram + parent subtraction
                # smaller side by GLOBAL in-bag count, matching the
                # masked builder (data_parallel_tree_learner.cpp:178-187)
                left_is_small = (st["best_lc"][best_leaf]
                                 <= st["best_rc"][best_leaf])
                small_b = jnp.where(left_is_small, seg_b, seg_b + n_left)
                small_c = jnp.where(left_is_small, n_left, seg_c - n_left)
                hist_small = leaf_histogram(st["words"], st["ghc"],
                                            small_b, small_c)
                hist_large = st["hist_cache"][best_leaf] - hist_small
                hist_left = jnp.where(left_is_small, hist_small, hist_large)
                hist_right = jnp.where(left_is_small, hist_large,
                                       hist_small)
                st["hist_cache"] = (st["hist_cache"]
                                    .at[best_leaf].set(hist_left)
                                    .at[right_id].set(hist_right))
            else:
                # memory-bounded mode: both children's segments scanned
                hist_left = leaf_histogram(st["words"], st["ghc"],
                                           seg_b, n_left)
                hist_right = leaf_histogram(st["words"], st["ghc"],
                                            seg_b + n_left,
                                            seg_c - n_left)

            # ---- children leaf state (LeafSplits::Init after split)
            child_depth = st["leaf_depth"][best_leaf] + 1
            st["leaf_depth"] = (st["leaf_depth"].at[best_leaf].set(child_depth)
                                .at[right_id].set(child_depth))

            lsplit = scan_leaf(hist_left, st["best_lg"][best_leaf],
                               st["best_lh"][best_leaf], st["best_lc"][best_leaf])
            rsplit = scan_leaf(hist_right, st["best_rg"][best_leaf],
                               st["best_rh"][best_leaf], st["best_rc"][best_leaf])

            # max_depth guard (serial_tree_learner.cpp:238-247)
            depth_ok = jnp.logical_or(max_depth < 0, child_depth < max_depth)
            lgain = jnp.where(depth_ok, lsplit.gain, K_MIN_SCORE)
            rgain = jnp.where(depth_ok, rsplit.gain, K_MIN_SCORE)

            st = write_candidate(st, best_leaf, lsplit, lgain)
            st = write_candidate(st, right_id, rsplit, rgain)
            return st

        return jax.lax.cond(do, do_split, no_split, st)

    state = jax.lax.fori_loop(0, l - 1, body, state)
    # original-order row->leaf map: one scatter at tree end
    row_leaf = (jnp.zeros(n_pad, dtype=jnp.int32)
                .at[state["perm"]].set(state["pos_leaf"]))
    return {
        "n_splits": state["n_splits"],
        "row_leaf": row_leaf,
        "split_feature": state["split_feature"],
        "split_threshold_bin": state["split_threshold_bin"],
        "split_gain": state["split_gain"],
        "left_child": state["left_child"],
        "right_child": state["right_child"],
        "leaf_parent": state["leaf_parent"],
        "leaf_value": state["leaf_value"],
        "leaf_count": state["leaf_count"],
        "internal_value": state["internal_value"],
        "internal_count": state["internal_count"],
    }
