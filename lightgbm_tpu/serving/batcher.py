"""Micro-batching queue: coalesce concurrent requests into one dispatch.

A single-row device dispatch and a 256-row dispatch cost nearly the
same wall time (the per-dispatch overhead dominates at serving batch
sizes), so under concurrency the winning shape is: queue requests for
at most `max_wait_ms`, concatenate whatever arrived into ONE padded
device call (CompiledPredictor pads to its row-count buckets), then
slice the result back per request. Classic dynamic batching — the same
design GPU inference servers use — implemented here with a single
worker thread and stdlib primitives only.

Latency contract: a lone request waits at most `max_wait_ms` beyond
its own dispatch; a full batch (`max_batch_rows` queued) dispatches
immediately. Requests of different kinds (predict / raw / leaf) never
share a dispatch — the worker drains the oldest kind first.
"""

import threading
import time
from concurrent.futures import Future

import numpy as np

from ..telemetry import disttrace
from ..utils import faults

KINDS = ("predict", "raw", "leaf")

# EWMA weight of the newest dispatch in the service-time estimate the
# admission controller reads (serving/admission.py): ~last 10 batches
EWMA_ALPHA = 0.2


class DeadlineExceeded(RuntimeError):
    """A request's deadline expired before its batch dispatched; the
    HTTP layer maps this to 504 (no device time was spent on it)."""


class MicroBatcher:
    """Coalesces `submit()`ed row batches into bucketed device
    dispatches against a CompiledPredictor (or anything exposing
    predict / predict_raw / predict_leaf_index)."""

    def __init__(self, predictor, max_batch_rows=None, max_wait_ms=2.0,
                 metrics=None):
        self.predictor = predictor
        self.max_batch_rows = int(max_batch_rows
                                  or getattr(predictor, "max_batch_rows",
                                             4096))
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.metrics = metrics
        # per-server chaos overrides (utils/faults.serving_chaos); the
        # serving server shares its dict here so `wedge_batcher` can
        # target one in-process replica
        self.chaos = None
        # distributed tracing (telemetry/disttrace.py): set by
        # make_server; the worker emits batch-dispatch + kernel spans
        # onto the first member's trace, linking the other members
        self.trace_recorder = None
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue = []    # [(kind, rows, future, t_enqueue, deadline)]
        self._est_service_s = 0.0   # EWMA batch service time (0=unknown)
        self._closed = False
        self._busy = False        # worker is mid-dispatch (quiesce check)
        self._worker = threading.Thread(target=self._run,
                                        name="micro-batcher", daemon=True)
        self._worker.start()

    # ---------------------------------------------------------------- client
    def submit(self, rows, kind="predict", deadline=None):
        """Enqueue one request; returns a concurrent.futures.Future
        resolving to that request's own result rows. `deadline` is an
        ABSOLUTE time.monotonic() instant: a request still queued past
        it fails with DeadlineExceeded before any device time is spent
        on it (the worker drops expired entries as it assembles each
        batch)."""
        if kind not in KINDS:
            raise ValueError(f"kind must be one of {KINDS}, got {kind!r}")
        rows = np.atleast_2d(np.asarray(rows, dtype=np.float32))
        canon = getattr(self.predictor, "_canon", None)
        if canon is not None:
            # canonicalize width HERE so requests that are valid alone
            # (narrow/wide rows) also concatenate with each other
            rows = canon(rows)
        fut = Future()
        # request-trace timestamps (serving/server.py splits latency
        # into queue-wait vs batch-compute from these): t_enqueue here,
        # t_dispatch/t_done stamped by the worker BEFORE it resolves
        # the future, so a woken waiter always sees all three
        fut.t_enqueue = time.monotonic()
        fut.t_dispatch = fut.t_done = fut.scored_by = None
        # the submitting thread's trace context rides the future into
        # the worker: the batch span knows every member it coalesced
        fut.trace_ctx = disttrace.current()
        with self._cond:
            if self._closed:
                raise RuntimeError("MicroBatcher is closed")
            self._queue.append((kind, rows, fut, fut.t_enqueue, deadline))
            self._cond.notify()
        return fut

    def predict(self, rows, kind="predict", timeout=None):
        """Blocking submit: the calling thread rides the next coalesced
        batch."""
        return self.submit(rows, kind).result(timeout=timeout)

    def queue_depth(self):
        with self._lock:
            return len(self._queue)

    def estimated_service_s(self):
        """EWMA of recent batch service (dispatch->done) seconds; 0.0
        until the first dispatch completes. The admission controller
        multiplies this by the queue backlog to estimate wait
        (serving/admission.py)."""
        return self._est_service_s

    def quiescent(self):
        """True when nothing is queued AND the worker is not
        mid-dispatch (the `/quiescez` admin check)."""
        with self._lock:
            return not self._queue and not self._busy

    def swap_predictor(self, predictor):
        """Atomically replace the predictor (hot-swap, fleet/hotswap).
        The worker snapshots the predictor ONCE per coalesced batch, so
        every batch — including one already queued — is scored entirely
        by a single model version; requests enqueued after this call
        ride the new one. Returns the retired predictor."""
        with self._cond:
            old, self.predictor = self.predictor, predictor
        return old

    def close(self, timeout=5.0):
        """Drain and stop the worker. Pending futures still resolve."""
        with self._cond:
            self._closed = True
            self._cond.notify()
        self._worker.join(timeout=timeout)

    # ---------------------------------------------------------------- worker
    def _take_batch(self):
        """Wait for work, give the head request `max_wait_s` to attract
        company, then pull every same-kind request (up to
        max_batch_rows). Returns (kind, [(rows, future)]) or None when
        closed and drained. Runs with the lock held via _cond."""
        with self._cond:
            # chaos: `wedge_batcher` parks the worker in this wait loop
            # even when work is queued (queue grows, admission control
            # must shed); clearing the fault un-wedges without a
            # restart, and close() still drains what queued up
            while not self._closed and (
                    not self._queue
                    or faults.serving_chaos(self.chaos).get(
                        "wedge_batcher")):
                self._cond.wait(timeout=0.05 if self._queue else None)
            if not self._queue:
                return None  # closed and drained
            # the single worker is the only consumer, so the head (and
            # its arrival time) cannot change while we wait for company
            wait_until = self._queue[0][3] + self.max_wait_s
            kind = self._queue[0][0]
            while True:
                rows_queued = sum(r.shape[0]
                                  for k, r, _, _, _ in self._queue
                                  if k == kind)
                remaining = wait_until - time.monotonic()
                if (rows_queued >= self.max_batch_rows or remaining <= 0
                        or self._closed):
                    break
                self._cond.wait(timeout=remaining)
            now = time.monotonic()
            batch, rest, expired, taken = [], [], [], 0
            for item in self._queue:
                k, rows, fut, _, req_deadline = item
                if req_deadline is not None and now > req_deadline:
                    # expired while queued: fail it BEFORE dispatch —
                    # the client already gave up, so device time spent
                    # on it would be pure waste (504 at the HTTP layer)
                    expired.append(fut)
                elif k == kind and taken < self.max_batch_rows:
                    batch.append((rows, fut))
                    taken += rows.shape[0]
                else:
                    rest.append(item)
            self._queue = rest
            self._busy = True   # cleared by _run after futures resolve
        for fut in expired:
            fut.t_dispatch = fut.t_done = time.monotonic()
            fut.set_exception(DeadlineExceeded(
                "deadline expired before dispatch"))
        if not batch:
            with self._lock:
                self._busy = False
            return kind, []
        return kind, batch

    def _emit_trace(self, kind, batch, w_dispatch, dispatch_s,
                    kernel_offset_s, kernel_s, total_rows, status):
        """Batch-dispatch + kernel spans for one coalesced dispatch.
        They attach to the FIRST traced member's trace; every other
        member's trace_id is carried in `links` so the collector can
        stitch the shared dispatch into all of them. Emitted BEFORE
        the futures resolve, while the member roots are still open."""
        rec = self.trace_recorder
        if rec is None or not rec.enabled:
            return
        ctxs = [f.trace_ctx for _, f in batch
                if getattr(f, "trace_ctx", None) is not None]
        if not ctxs:
            return
        head = ctxs[0]
        links = sorted({c.trace_id for c in ctxs[1:]
                        if c.trace_id != head.trace_id}) or None
        span = rec.observe(
            "batch.dispatch", head, w_dispatch, dispatch_s,
            status=status, links=links,
            tags={"kind": kind, "rows": int(total_rows),
                  "requests": len(batch)})
        if kernel_s is not None:
            rec.observe("serve.kernel", head,
                        w_dispatch + kernel_offset_s, kernel_s,
                        status=status,
                        parent=span.span_id if span is not None
                        else None)

    def _run(self):
        while True:
            got = self._take_batch()
            if got is None:
                return
            kind, batch = got
            if not batch:
                continue    # every queued entry had expired
            # ONE predictor snapshot per batch: a concurrent hot-swap
            # (swap_predictor) lands between batches, never inside one —
            # a coalesced dispatch is scored entirely by one model
            pred = self.predictor
            t_dispatch = time.monotonic()
            w_dispatch = time.time()
            t_k0 = t_k1 = None
            try:
                # inside the try: ANY failure (even a concat shape
                # mismatch) must fail this batch's futures, never kill
                # the single worker thread
                parts = [r for r, _ in batch]
                if len({r.shape[1] for r in parts}) > 1:
                    # widths were canonicalized at submit time against
                    # the THEN-current predictor; a swap to a different
                    # feature width can strand mixed widths in one
                    # batch — re-canonicalize against the snapshot
                    canon = getattr(pred, "_canon", None)
                    if canon is not None:
                        parts = [canon(r) for r in parts]
                rows = np.concatenate(parts, axis=0)
                t_k0 = time.monotonic()
                if kind == "leaf":
                    out = pred.predict_leaf_index(rows)
                elif kind == "raw":
                    out = pred.predict_raw(rows)
                else:
                    out = pred.predict(rows)
                t_k1 = time.monotonic()
            except Exception as e:
                # errors are counted per REQUEST by whoever consumes the
                # futures (the HTTP handler) — counting the batch here
                # too would double-book one failure
                t_done = time.monotonic()
                self._emit_trace(
                    kind, batch, w_dispatch, t_done - t_dispatch,
                    None, None,
                    sum(r.shape[0] for r, _ in batch), "error")
                for _, fut in batch:
                    fut.t_dispatch, fut.t_done = t_dispatch, t_done
                    fut.scored_by = pred
                    fut.set_exception(e)
                with self._lock:
                    self._busy = False
                continue
            t_done = time.monotonic()
            dt = t_done - t_dispatch
            self._est_service_s = (
                dt if self._est_service_s == 0.0
                else (1.0 - EWMA_ALPHA) * self._est_service_s
                + EWMA_ALPHA * dt)
            if self.metrics is not None:
                self.metrics.record_batch(rows.shape[0], len(batch))
            self._emit_trace(kind, batch, w_dispatch, dt,
                             t_k0 - t_dispatch, t_k1 - t_k0,
                             rows.shape[0], "ok")
            s = 0
            for r, fut in batch:
                fut.t_dispatch, fut.t_done = t_dispatch, t_done
                # which model scored this request: the handler's
                # monitor intake checks it against the monitors' owner
                # so a hot-swap mid-request cannot shadow-score one
                # model's output against another's reference
                fut.scored_by = pred
                fut.set_result(out[s:s + r.shape[0]])
                s += r.shape[0]
            with self._lock:
                self._busy = False
