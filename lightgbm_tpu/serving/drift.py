"""Serving-side drift & skew monitors.

A standing prediction service rots in two distinct ways and this
module watches both, sampled and bounded (the Booster-accelerator
line of work, arXiv:2011.02022, prices serving throughput tightly
enough that request-path monitoring must cost ~nothing — see the
bench's quality_probe and its <1% bar):

- **Data drift** (`DriftMonitor`): incoming rows stop looking like the
  training data. Sampled requests run through the MODEL'S OWN bin
  mappers (the training profile artifact, io/profile.py, carries the
  bounds), maintaining rolling per-feature bin histograms plus a
  prediction-distribution histogram; per-feature PSI against the
  training baseline is recomputed as the window fills. PSI over the
  usual 0.2 threshold is the classic "investigate this feature"
  signal; `psi_warn` crossings emit ONE structured warning per
  excursion (re-armed when the feature falls back under half the
  threshold).

- **Scoring skew** (`SkewMonitor`): the serving path stops agreeing
  with the reference implementation. Sampled requests are re-scored
  through the host f64 reference path (the same precision contract the
  CompiledPredictor parity tests pin) and any row diverging beyond
  `SKEW_TOL` counts as skew — with a bit-exact serving contract the
  expected count is ZERO, so `skew_warn` defaults to firing on the
  first one.

Both export on `/driftz` (full JSON), `/metricz` (scalar gauges, JSON
and Prometheus exposition) and the structured warning log
(utils/log.py Log.structured). PSI math documented in
docs/Observability.md.

**Cost discipline** (the <1% bar, tools/verify_perf.py): the serving
hot path runs at ~1 us/row, so the monitors' request-path work is an
integer-credit sampling decision plus, for sampled rows, one slice
VIEW appended to a pending buffer. All real work — binning, PSI,
shadow scoring — is deferred to `flush()`, which runs inline once the
buffer passes `flush_rows` (so warnings still surface mid-traffic,
e.g. at `sample_rate=1.0` in tests) and on every reader
(/driftz//metricz scrapes), where one vectorized pass amortizes the
per-call numpy and reference-scorer overhead across the whole batch.
The default sample rates are sized so the steady-state monitor cost
stays under 1% of the raw predict pipe; raise them on low-traffic
services where the absolute cost is irrelevant.
"""

import threading

import numpy as np

from ..io.bin_mapper import NUMERICAL
from ..io.profile import DEFAULT_PROFILE_BINS, group_counts
from ..utils.log import Log

# Laplace pseudo-count added per group on both sides of the PSI
# log-ratio: an empty observed group then reads as "rare", not as an
# infinity (or the huge finite term a bare proportion floor produces
# at small samples)
PSI_SMOOTHING = 0.5
# serving vs host-f64-reference divergence beyond this is skew; the
# serving parity contract is ~1e-16, so 1e-6 is pure headroom
SKEW_TOL = 1e-6

# Default sample fractions (of ROWS, accumulated as integer credit per
# request). Sized against the cost model in the module docstring:
# binning a sampled row costs ~0.7 us (vectorized over all features),
# shadow-scoring one ~3 us plus a per-flush call overhead, against a
# ~1 us/row serving pipe — so the affordable sampled fraction under a
# 1% budget is around one per mille. At 1M rows/day that is still
# ~1000 drift rows and ~100 shadow scores per day, plenty for PSI
# windows and for catching systematic skew (one diverging row already
# warns).
DEFAULT_DRIFT_SAMPLE_RATE = 0.001
DEFAULT_PSI_WARN = 0.2
DEFAULT_SKEW_SAMPLE_RATE = 0.0001
DEFAULT_SKEW_WARN = 1
# PSI needs this many sampled rows PER GROUP before it is signal (and
# never fewer than MIN_PSI_ROWS total): Poisson noise at ~20 rows per
# group keeps a same-distribution PSI well under the 0.2 threshold
MIN_PSI_ROWS = 200
MIN_PSI_ROWS_PER_GROUP = 20
# pending-buffer sizes that trigger an inline flush; big enough to
# amortize per-flush overhead, small enough that warnings stay timely
DRIFT_FLUSH_ROWS = 256
SKEW_FLUSH_ROWS = 32
# drift sampling is BURSTY: credit accumulates across requests until a
# slice this big is affordable, then one contiguous slice is taken —
# same sampled fraction, ~burst x fewer enqueues and pending entries
DRIFT_BURST_ROWS = 8

# 64-bit LCG (Knuth MMIX) for the sampling decisions: one integer
# multiply per request instead of a numpy RNG call keeps the
# no-sample fast path at ~0.2 us
_LCG_MUL = 6364136223846793005
_LCG_ADD = 1442695040888963407
_LCG_MASK = (1 << 64) - 1


def psi(expected_counts, actual_counts, smoothing=PSI_SMOOTHING):
    """Population stability index between two aligned count vectors:
    sum_g (a_g - e_g) * ln(a_g / e_g) over the groups' proportions,
    Laplace-smoothed with `smoothing` pseudo-counts per group.
    0 = identical; > 0.2 is the conventional drift alert. Returns 0.0
    while either side is empty. (docs/Observability.md for the math.)"""
    e = np.asarray(expected_counts, np.float64)
    a = np.asarray(actual_counts, np.float64)
    if e.sum() <= 0 or a.sum() <= 0:
        return 0.0
    g = len(e)
    p = (e + smoothing) / (e.sum() + smoothing * g)
    q = (a + smoothing) / (a.sum() + smoothing * g)
    return float(np.sum((q - p) * np.log(q / p)))


class _PredHistogram:
    """Rolling prediction-distribution histogram. Edges fix lazily:
    transformed binary/multiclass outputs live in [0, 1] (pass
    `value_range=(0, 1)`); otherwise the first `warm_n` samples set
    the range. Caller holds the monitor lock."""

    BINS = 20

    def __init__(self, value_range=None, warm_n=256):
        self.edges = (np.linspace(value_range[0], value_range[1],
                                  self.BINS + 1)
                      if value_range else None)
        self.counts = np.zeros(self.BINS, np.int64)
        self._warm = [] if value_range is None else None
        self._warm_n = int(warm_n)
        self.n = 0
        self.total = 0.0
        self.vmin = np.inf
        self.vmax = -np.inf

    def observe(self, values):
        v = np.asarray(values, np.float64).reshape(-1)
        v = v[np.isfinite(v)]
        if not len(v):
            return
        self.n += len(v)
        self.total += float(v.sum())
        self.vmin = min(self.vmin, float(v.min()))
        self.vmax = max(self.vmax, float(v.max()))
        if self.edges is None:
            self._warm.extend(v.tolist())
            if len(self._warm) < self._warm_n:
                return
            lo, hi = self.vmin, self.vmax
            if hi <= lo:
                hi = lo + 1.0
            span = hi - lo
            self.edges = np.linspace(lo - 0.05 * span, hi + 0.05 * span,
                                     self.BINS + 1)
            v = np.asarray(self._warm)
            self._warm = None
        idx = np.clip(np.searchsorted(self.edges, v, side="right") - 1,
                      0, self.BINS - 1)
        np.add.at(self.counts, idx, 1)

    def snapshot(self):
        out = {"count": int(self.n)}
        if self.n:
            out.update({"mean": round(self.total / self.n, 6),
                        "min": round(self.vmin, 6),
                        "max": round(self.vmax, 6)})
        if self.edges is not None:
            out["edges"] = [round(float(e), 6) for e in self.edges]
            out["counts"] = [int(c) for c in self.counts]
        return out


class DriftMonitor:
    """Rolling per-feature bin histograms + PSI against the training
    profile (module docstring). Thread-safe. `observe` is the only
    request-path call: it draws `sample_rate * n` rows of integer
    credit, appends one contiguous slice view to the pending buffer,
    and returns — binning and PSI run in `flush()` (inline once
    `flush_rows` sampled rows accumulate, and on every reader).

    The flush bins ALL numerical features in one broadcast comparison
    against a per-feature group-edge matrix. The edges are the mapper
    upper bounds at `group_counts` fold boundaries, so
    `#(edges < value)` is EXACTLY `fold(mapper.value_to_bin(value))`
    (searchsorted side='left' counts bounds strictly below the value)
    without 28 per-feature mapper calls. Categorical features take the
    per-feature mapper path (dict lookup; rare in wide numeric data).

    `window_rows` bounds the rolling window: once twice that many rows
    accumulate, all counts halve (exponential forget) so the PSI
    tracks current traffic instead of the process lifetime."""

    def __init__(self, profile, sample_rate=DEFAULT_DRIFT_SAMPLE_RATE,
                 psi_warn=DEFAULT_PSI_WARN,
                 profile_bins=DEFAULT_PROFILE_BINS,
                 window_rows=100_000, pred_range=None, seed=12345,
                 flush_rows=DRIFT_FLUSH_ROWS):
        self.profile = profile
        self.sample_rate = float(sample_rate)
        self.psi_warn = float(psi_warn)
        self.profile_bins = int(profile_bins)
        self.window_rows = int(window_rows)
        self.flush_rows = int(flush_rows)
        self._lcg = int(seed) & _LCG_MASK
        self._credit = 0.0
        self._lock = threading.Lock()
        self._columns = [int(f["column"]) for f in profile.features]
        self._names = [str(f["name"]) for f in profile.features]
        baseline = [group_counts(f["counts"], self.profile_bins)
                    for f in profile.features]
        u_n = profile.num_features
        gmax = max((len(b) for b in baseline), default=1)
        self._gmax = gmax
        self._g = np.asarray([len(b) for b in baseline], np.float64)
        self._mask = np.arange(gmax)[None, :] < self._g[:, None]
        self._base = np.zeros((u_n, gmax), np.float64)
        for u, b in enumerate(baseline):
            self._base[u, :len(b)] = b
        self._counts = np.zeros((u_n, gmax), np.int64)
        # numerical features: group-edge matrix (padded +inf so absent
        # groups never match); categoricals keep their mapper
        self._num_u, self._cat = [], []
        edges = []
        for u, f in enumerate(profile.features):
            g = len(baseline[u])
            if f["bin_type"] == NUMERICAL:
                ub = np.asarray(f["upper_bounds"], np.float64)
                b = max(int(f["num_bin"]), 1)
                row = np.full(gmax - 1, np.inf) if gmax > 1 \
                    else np.zeros(0)
                if g > 1:
                    gi = np.arange(1, g)
                    hi = (gi * b + g - 1) // g - 1   # last bin of gi-1
                    row[:g - 1] = ub[np.minimum(hi, len(ub) - 1)]
                edges.append(row)
                self._num_u.append(u)
            else:
                self._cat.append((u, profile.mapper(u),
                                  int(f["num_bin"]), g))
        self._edges = (np.asarray(edges)
                       if edges else np.zeros((0, max(gmax - 1, 0))))
        self._num_u = np.asarray(self._num_u, np.int64)
        self._cols_arr = np.asarray(self._columns, np.int64)
        self._pending = []          # (rows_view, predictions_or_None)
        self._pending_rows = 0
        self.pred_hist = _PredHistogram(value_range=pred_range)
        self.rows_seen = 0
        self.rows_sampled = 0
        self._psi = np.zeros(u_n)
        self._warned = set()
        self.warnings = []          # bounded list of warning dicts
        self.min_psi_rows = max(MIN_PSI_ROWS,
                                MIN_PSI_ROWS_PER_GROUP * gmax)

    # ------------------------------------------------------------ intake
    def observe(self, rows, predictions=None):
        """One request's rows (N, F raw values; narrower inputs mean
        absent trailing features = NaN) and optionally its served
        predictions (multiclass outputs reduce to the winning-class
        confidence at flush). Request-path cost is the sampling
        decision + a slice view append; array normalization only runs
        on the (rare) sampled branch."""
        shape = getattr(rows, "shape", None)
        if shape is None or len(shape) != 2:
            rows = np.atleast_2d(np.asarray(rows))
            shape = rows.shape
        n = shape[0]
        with self._lock:
            self.rows_seen += n
            self._credit += n * self.sample_rate
            k = int(self._credit)
            if k <= 0 or (k < DRIFT_BURST_ROWS and k < n):
                return              # let credit accumulate to a burst
            k = min(k, n)
            self._credit -= k       # deduct only what is taken
            if k < n:
                self._lcg = (self._lcg * _LCG_MUL + _LCG_ADD) & _LCG_MASK
                start = (self._lcg >> 33) % (n - k + 1)
                # copies, not views: a view would pin the WHOLE request
                # array in the pending buffer until the next flush
                sampled = np.array(rows[start:start + k])
                preds = (None if predictions is None
                         else np.array(predictions[start:start + k]))
            else:
                sampled, preds = np.asarray(rows), predictions
            self._pending.append((sampled, preds))
            self._pending_rows += k
            if self._pending_rows >= self.flush_rows:
                self._flush_locked()

    def flush(self):
        """Run the deferred binning + PSI pass now (readers call this;
        request threads hit it via the flush_rows threshold)."""
        with self._lock:
            self._flush_locked()

    def _flush_locked(self):
        if not self._pending:
            return
        pending, self._pending = self._pending, []
        self._pending_rows = 0
        u_n = len(self._names)
        # group by request width so the column gather runs ONCE per
        # width instead of once per (often single-row) pending entry
        by_width, preds = {}, {}
        for r, p in pending:
            by_width.setdefault(r.shape[1], []).append(r)
            if p is not None:
                p = np.asarray(p)
                preds.setdefault(p.shape[1:], []).append(p)
        mats = []
        for width, parts in by_width.items():
            r = (np.concatenate(parts) if len(parts) > 1 else parts[0])
            v = np.full((len(r), u_n), np.nan)
            ok = self._cols_arr < width
            v[:, ok] = r[:, self._cols_arr[ok]]
            mats.append(v)
        vals = np.concatenate(mats) if len(mats) > 1 else mats[0]
        # the binning rule: NaN (and absent trailing features) -> 0.0
        # -> the zero bin, exactly like training ingestion
        np.copyto(vals, 0.0, where=np.isnan(vals))
        grp = np.zeros(vals.shape, np.int64)
        if len(self._num_u):
            grp[:, self._num_u] = (
                vals[:, self._num_u, None] > self._edges[None]).sum(
                    axis=2, dtype=np.int64)
        for u, mapper, nb, g in self._cat:
            bins = mapper.value_to_bin(vals[:, u]).astype(np.int64)
            if nb > g:
                bins = (bins * g) // nb
            grp[:, u] = np.clip(bins, 0, g - 1)
        flat = (grp + np.arange(u_n, dtype=np.int64)[None, :]
                * self._gmax).ravel()
        self._counts += np.bincount(
            flat, minlength=u_n * self._gmax).reshape(u_n, self._gmax)
        for parts in preds.values():
            p = np.asarray(np.concatenate(parts) if len(parts) > 1
                           else parts[0], np.float64)
            if p.ndim > 1:      # multiclass: winning-class confidence
                p = p[:, 0] if p.shape[1] == 1 else p.max(axis=1)
            self.pred_hist.observe(p)
        self.rows_sampled += len(vals)
        if self.rows_sampled > 2 * self.window_rows:
            self._counts //= 2
            self.rows_sampled //= 2
        self._refresh_psi()

    def _refresh_psi(self):
        """Vectorized per-feature PSI + threshold bookkeeping (lock
        held). One structured warning per excursion over psi_warn; a
        feature re-arms after falling below half the threshold."""
        if self.rows_sampled < self.min_psi_rows or not len(self._psi):
            return
        e, a = self._base, self._counts.astype(np.float64)
        s = PSI_SMOOTHING
        esum, asum = e.sum(axis=1), a.sum(axis=1)
        p = (e + s) / (esum + s * self._g)[:, None]
        q = (a + s) / (asum + s * self._g)[:, None]
        terms = np.where(self._mask,
                         (q - p) * np.log(np.where(self._mask, q / p, 1.0)),
                         0.0)
        vals = terms.sum(axis=1)
        vals[(esum <= 0) | (asum <= 0)] = 0.0
        self._psi = vals
        for u in np.nonzero(vals >= self.psi_warn)[0]:
            name = self._names[u]
            if name in self._warned:
                continue
            self._warned.add(name)
            rec = {"feature": name, "psi": round(float(vals[u]), 4),
                   "threshold": self.psi_warn,
                   "rows_sampled": int(self.rows_sampled)}
            self.warnings.append(rec)
            del self.warnings[:-50]
            Log.structured("Warning", "drift_warn", **rec)
        for u in np.nonzero(vals < 0.5 * self.psi_warn)[0]:
            self._warned.discard(self._names[u])

    # ----------------------------------------------------------- readers
    def psi_by_feature(self):
        with self._lock:
            self._flush_locked()
            return {self._names[u]: round(float(self._psi[u]), 6)
                    for u in range(len(self._names))}

    def gauges(self):
        """Scalar fields for /metricz (JSON and Prometheus)."""
        with self._lock:
            self._flush_locked()
            top = int(np.argmax(self._psi)) if len(self._psi) else 0
            return {
                "drift_rows_seen": int(self.rows_seen),
                "drift_rows_sampled": int(self.rows_sampled),
                "drift_psi_max": round(float(self._psi.max())
                                       if len(self._psi) else 0.0, 6),
                "drift_features_over_warn": int(
                    (self._psi >= self.psi_warn).sum()
                    if self.rows_sampled >= self.min_psi_rows else 0),
                "drift_top_feature": (self._names[top]
                                      if len(self._names) else ""),
            }

    def snapshot(self):
        """The /driftz document."""
        with self._lock:
            self._flush_locked()
            features = {}
            for u, name in enumerate(self._names):
                g = int(self._g[u])
                features[name] = {
                    "psi": round(float(self._psi[u]), 6),
                    "column": self._columns[u],
                    "baseline_rows": int(self._base[u, :g].sum()),
                    "observed_rows": int(self._counts[u, :g].sum()),
                    "baseline_zero_rate": round(
                        self.profile.zero_rate(u), 6),
                }
            psi_max = float(self._psi.max()) if len(self._psi) else 0.0
            return {
                "sample_rate": self.sample_rate,
                "psi_warn": self.psi_warn,
                "profile_bins": self.profile_bins,
                "window_rows": self.window_rows,
                "rows_seen": int(self.rows_seen),
                "rows_sampled": int(self.rows_sampled),
                "min_psi_rows": self.min_psi_rows,
                "psi_max": round(psi_max, 6),
                "features": features,
                "prediction": self.pred_hist.snapshot(),
                "warnings": list(self.warnings),
            }


class SkewMonitor:
    """Shadow-scoring skew detector: sampled rows re-score through the
    host f64 reference path and any row diverging beyond SKEW_TOL from
    the served output counts as skew. `reference_fn(kind, rows)` is
    built by `host_reference_scorer` (a plain GBDT loaded from the
    same model file, device predict forced off).

    Request-path `observe` only enqueues slice views (credit sampling,
    `max_rows_per_check` cap per request); the reference scoring runs
    batched in `flush()` — inline past `flush_rows` pending rows and
    on every reader — one reference call per endpoint kind, which
    amortizes the reference path's fixed per-call cost (~0.2 ms)
    across the whole buffered sample."""

    def __init__(self, reference_fn,
                 sample_rate=DEFAULT_SKEW_SAMPLE_RATE,
                 skew_warn=DEFAULT_SKEW_WARN, tol=SKEW_TOL,
                 max_rows_per_check=16, seed=54321,
                 flush_rows=SKEW_FLUSH_ROWS):
        self.reference_fn = reference_fn
        self.sample_rate = float(sample_rate)
        self.skew_warn = int(skew_warn)
        self.tol = float(tol)
        self.max_rows_per_check = int(max_rows_per_check)
        self.flush_rows = int(flush_rows)
        self._lcg = int(seed) & _LCG_MASK
        self._credit = 0.0
        self._lock = threading.Lock()
        self._pending = []          # (rows_view, served_slice, kind)
        self._pending_rows = 0
        self.rows_checked = 0
        self.skew_count = 0
        self.max_abs_diff = 0.0
        self._warned_at = 0

    def observe(self, rows, served, kind):
        """Enqueue a bounded sample of a request's (rows, served
        output) for shadow scoring. `kind` is the endpoint
        ("predict"/"raw"; leaf indices are already int-exact and
        skipped)."""
        if kind not in ("predict", "raw") or self.sample_rate <= 0.0:
            return
        shape = getattr(rows, "shape", None)
        if shape is None or len(shape) != 2:
            rows = np.atleast_2d(np.asarray(rows))
            shape = rows.shape
        n = shape[0]
        with self._lock:
            self._credit += n * self.sample_rate
            k = int(self._credit)
            if k <= 0:
                return
            k = min(k, n, self.max_rows_per_check)
            self._credit -= k       # deduct only what is taken; cap
            self._credit = min(     # the carry-over so a rate above
                self._credit,       # cap/request-size cannot grow it
                4.0 * self.max_rows_per_check)   # without bound
            self._lcg = (self._lcg * _LCG_MUL + _LCG_ADD) & _LCG_MASK
            start = (self._lcg >> 33) % (n - k + 1)
            # copies, not views (see DriftMonitor.observe)
            self._pending.append((np.array(rows[start:start + k]),
                                  np.array(served[start:start + k]),
                                  kind))
            self._pending_rows += k
            do_flush = self._pending_rows >= self.flush_rows
        if do_flush:
            self.flush()

    def flush(self):
        """Shadow-score everything pending. The reference call runs
        OUTSIDE the lock so a slow reference model never blocks the
        request threads' enqueues."""
        with self._lock:
            batch, self._pending = self._pending, []
            self._pending_rows = 0
        # one reference call per (endpoint, request width) — widths
        # can differ between clients and must not concatenate
        groups = {}
        for r, s, kind in batch:
            groups.setdefault((kind, r.shape[1]), []).append((r, s))
        for (kind, _), part in groups.items():
            self._check(kind,
                        np.concatenate([
                            np.asarray(r, np.float64)
                            for r, _ in part]),
                        np.concatenate([
                            np.asarray(s, np.float64).reshape(
                                len(r), -1)
                            for r, s in part]))

    def _check(self, kind, rows, got):
        try:
            ref = np.asarray(self.reference_fn(kind, rows), np.float64)
        except Exception as e:    # the monitor must never fail serving
            Log.warning("skew monitor reference scoring failed: %s", e)
            return
        ref = ref.reshape(len(rows), -1)
        if ref.shape != got.shape:
            Log.warning("skew monitor shape mismatch: served %s vs "
                        "reference %s", got.shape, ref.shape)
            return
        diff = np.abs(got - ref)
        row_max = diff.max(axis=1) if diff.size else np.zeros(0)
        bad = int((row_max > self.tol).sum())
        with self._lock:
            self.rows_checked += len(rows)
            self.max_abs_diff = max(self.max_abs_diff,
                                    float(row_max.max())
                                    if len(row_max) else 0.0)
            if bad:
                self.skew_count += bad
                if (self.skew_warn > 0
                        and self.skew_count >= self.skew_warn
                        # warn at the first crossing, then once per
                        # doubling — a persistent skew must not flood
                        and self.skew_count >= 2 * self._warned_at):
                    self._warned_at = max(self.skew_count, 1)
                    Log.structured(
                        "Warning", "skew_warn", kind=kind,
                        skew_count=int(self.skew_count),
                        rows_checked=int(self.rows_checked),
                        max_abs_diff=float(self.max_abs_diff),
                        threshold=self.skew_warn, tol=self.tol)

    def gauges(self):
        self.flush()
        with self._lock:
            return {"skew_rows_checked": int(self.rows_checked),
                    "skew_count": int(self.skew_count),
                    "skew_max_abs_diff": float(self.max_abs_diff)}

    def snapshot(self):
        out = self.gauges()
        out.update({"sample_rate": self.sample_rate,
                    "skew_warn": self.skew_warn, "tol": self.tol,
                    "max_rows_per_check": self.max_rows_per_check})
        return out


def host_reference_scorer(model_path):
    """Load the model text format into a plain GBDT and return
    `fn(kind, rows)` scoring on the HOST f64 path (device predict
    forced off) — the serving skew monitor's ground truth."""
    from ..models.gbdt import create_boosting
    booster = create_boosting("gbdt", model_path)
    with open(model_path) as f:
        booster.load_model_from_string(f.read())
    # hard host routing: beats even LIGHTGBM_TPU_DEVICE_PREDICT=force,
    # which a throughput-tuned deployment may export — the reference
    # must never score on the device f32 path it is checking against
    booster.force_host_predict = True
    width = booster.max_feature_idx + 1

    def fn(kind, rows):
        x = np.atleast_2d(np.asarray(rows, np.float64))
        f = x.shape[1]
        if f < width:          # same canonicalization as the predictor
            x = np.pad(x, ((0, 0), (0, width - f)))  # 0.0, like _canon
        elif f > width:
            x = x[:, :width]
        return booster.predict_raw(x) if kind == "raw" \
            else booster.predict(x)

    # warm both paths now: the host predictor's one-time array setup
    # (~1 ms) belongs to startup, not to the first shadow-score flush
    warm = np.zeros((1, width))
    fn("predict", warm)
    fn("raw", warm)
    return fn
