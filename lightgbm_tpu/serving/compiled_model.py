"""CompiledPredictor: a trained ensemble frozen for online serving.

The training-side predict path (models/gbdt.py predict_raw) re-derives
stacked arrays per call and compiles on first use — fine for batch
scoring, wrong for a standing service where the FIRST request must not
pay a trace+compile. This module freezes the model once:

- the ensemble becomes immutable padded SoA device arrays (class-major
  stacked split_feature / threshold / decision_type / left_child /
  right_child / leaf_value, via GBDT._stacked_model_arrays), with the
  same round-toward--inf f32 threshold cast as the training-side device
  predictor (models/gbdt.py f32_safe_thresholds) so f32 traversal
  decisions equal the f64 host reference;
- raw-score, transformed (sigmoid/softmax, gbdt.py predict) and
  leaf-index kernels are jit-compiled once per ROW-COUNT BUCKET
  (powers of two up to max_batch_rows), and warm_up() AOT-compiles
  every bucket a request can hit at load so no request shape ever
  traces at request time (the default warms the traversal/leaf kernel
  all three serving endpoints dispatch; `warm_device_kernels=True`
  extends that to the all-device f32 variants);
- the persistent XLA compile cache (config.setup_compilation_cache) is
  wired in before the first compile, so a warm-process restart loads
  executables from disk instead of recompiling — sub-second startup.

Precision contract: traversal decisions are exact (the f32 threshold
cast preserves every f64 `<=` outcome for f32-representable inputs, and
category ids are exact in f32), so `predict_raw`/`predict` gather the
traversed leaf indices and reduce in f64 ON HOST — bit-identical to
GBDT's host predict path (a (B, T) int32 transfer plus a tiny matmul;
the traversal is the O(depth * B * T) part and stays on device). The
`_device` variants keep the whole pipeline on device in f32 (reduction
on the MXU) for throughput-bound callers that tolerate ~1e-6.

Linear-leaf models (models/linear_leaves.py) freeze their per-leaf
coefficient blocks into COEF_PAD-padded SoA arrays alongside the node
arrays and fuse the per-leaf dot product into the traversal kernels
(_linraw_kernel/_lintransformed_kernel) — one dispatch per request
block, same shape-stability rules, so a linear challenger hot-swaps
behind a constant incumbent with zero cold dispatches. The exact f32
precision keeps the linear reduce on host in f64, bit-identical to
GBDT's host path; bf16 stores coefficients in bfloat16 and the pinned
`accuracy_bound` grows a coefficient-rounding term (see
_pin_accuracy_bound).
"""

import functools
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..config import compile_cache_hits, setup_compilation_cache
from ..models.gbdt import create_boosting, device_traverse, f32_safe_thresholds
from ..models.tree import Tree
from ..utils import common
from ..utils.log import Log

DEFAULT_MAX_BATCH_ROWS = 4096
# serving_precision values (docs/Serving.md): `f32` is the exact
# contract (device f32 traversal + host f64 reduction, bit-identical
# to the reference); `bf16` keeps the traversal DECISIONS exact (f32
# compare against f32-safe thresholds) but gathers leaf values and
# runs the class reduction in bfloat16 on device — the Booster
# accelerator result (arXiv:2011.02022): ensemble throughput lives in
# node layout + reduced value precision, and the value stage is where
# precision can drop without moving a single traversal decision. The
# bf16 path ships a PINNED accuracy bound (`accuracy_bound`, computed
# from the frozen leaf values at load) that the skew monitor adopts
# as its tolerance, so monitoring stays armed and quiet by
# construction.
SERVING_PRECISIONS = ("f32", "bf16")


@jax.jit
def _leaf_kernel(xb, sf, thr, cat, lc, rc, node0, depth):
    """(B, F) f32 rows -> (B, T) int32 leaf indices. `depth` is a
    TRACED operand (fori_loop handles dynamic trip counts), so two
    model generations of different depth share one executable — depth
    must never be a recompile trigger across a hot-swap."""
    node = device_traverse(xb, sf, thr, cat, lc, rc, node0, depth)
    return (~node).astype(jnp.int32)


@jax.jit
def _raw_kernel(xb, sf, thr, cat, lc, rc, lv, node0, cls_onehot, depth):
    """(B, F) f32 rows -> (B, K) f32 raw class sums (MXU reduction)."""
    node = device_traverse(xb, sf, thr, cat, lc, rc, node0, depth)
    t_idx = jnp.arange(sf.shape[0])
    vals = lv[t_idx[None, :], ~node]                        # (B, T)
    return vals @ cls_onehot                                # (B, K)


@functools.partial(jax.jit, static_argnums=(10,))
def _transformed_kernel(xb, sf, thr, cat, lc, rc, lv, node0, cls_onehot,
                        depth, sigmoid):
    """(B, F) f32 rows -> (B, K) f32 transformed predictions
    (gbdt.cpp:622-636 semantics: binary sigmoid / multiclass softmax /
    raw passthrough)."""
    raw = _raw_kernel(xb, sf, thr, cat, lc, rc, lv, node0, cls_onehot,
                      depth)
    if sigmoid > 0 and cls_onehot.shape[1] == 1:
        return 1.0 / (1.0 + jnp.exp(-2.0 * sigmoid * raw))
    if cls_onehot.shape[1] > 1:
        return jax.nn.softmax(raw, axis=1)
    return raw


@jax.jit
def _raw16_kernel(xb, sf, thr, cat, lc, rc, lv16, node0, onehot16, depth):
    """bf16 value stage: EXACT f32 traversal (identical decisions to
    the f32 kernels — thr stays the f32-safe cast), then a bfloat16
    leaf-value gather and a bf16 x bf16 class reduction accumulated in
    f32 on the MXU. Node arrays may ride the compact int16 layout
    (serving_precision docstring at module top)."""
    node = device_traverse(xb, sf, thr, cat, lc, rc, node0, depth)
    t_idx = jnp.arange(sf.shape[0])
    vals = lv16[t_idx[None, :], ~node]                      # (B, T) bf16
    return jax.lax.dot(vals, onehot16,
                       preferred_element_type=jnp.float32)  # (B, K) f32


@functools.partial(jax.jit, static_argnums=(10,))
def _transformed16_kernel(xb, sf, thr, cat, lc, rc, lv16, node0, onehot16,
                          depth, sigmoid):
    """bf16 raw stage + the f32 transform (sigmoid/softmax run on the
    f32 accumulator output, so the transform adds no bf16 error)."""
    raw = _raw16_kernel(xb, sf, thr, cat, lc, rc, lv16, node0, onehot16,
                        depth)
    if sigmoid > 0 and onehot16.shape[1] == 1:
        return 1.0 / (1.0 + jnp.exp(-2.0 * sigmoid * raw))
    if onehot16.shape[1] > 1:
        return jax.nn.softmax(raw, axis=1)
    return raw


def _linear_leaf_values(xb, node, lv, const, coef, cfeat, ccnt):
    """(B, T) per-lane leaf outputs for linear-leaf models, fused with
    the traversal result: gather each (row, tree) lane's leaf model —
    intercept, COEF_PAD coefficient/feature slots, live count — dot the
    row's gathered feature values against the coefficients, and fall
    back to the constant leaf value where the lane's leaf is constant
    (cnt == 0) or a live feature is NaN (missing values have no
    coordinate; Tree._linear_values host semantics). Arithmetic is f32
    throughout; bf16 precision passes bf16-stored value arrays which
    upcast at the gather, so storage rounding is the only bf16 error
    (the pinned accuracy_bound's coefficient term)."""
    leaf = ~node                                             # (B, T)
    b = xb.shape[0]
    t_idx = jnp.arange(lv.shape[0])[None, :]                 # (1, T)
    base = lv[t_idx, leaf].astype(jnp.float32)               # (B, T)
    cst = const[t_idx, leaf].astype(jnp.float32)             # (B, T)
    cn = ccnt[t_idx, leaf]                                   # (B, T)
    j = jnp.arange(coef.shape[2])[None, None, :]             # (1, 1, C)
    co = coef[t_idx[:, :, None], leaf[:, :, None], j] \
        .astype(jnp.float32)                                 # (B, T, C)
    ft = cfeat[t_idx[:, :, None], leaf[:, :, None], j]       # (B, T, C)
    xf = xb[jnp.arange(b)[:, None, None], ft]                # (B, T, C)
    valid = j < cn[:, :, None]
    live_nan = jnp.isnan(xf) & valid
    dot = jnp.sum(jnp.where(valid & ~jnp.isnan(xf), co * xf, 0.0),
                  axis=-1)
    lin = cst + dot
    use_lin = (cn > 0) & ~jnp.any(live_nan, axis=-1)
    return jnp.where(use_lin, lin, base)


@jax.jit
def _linraw_kernel(xb, sf, thr, cat, lc, rc, lv, node0, cls_onehot, depth,
                   const, coef, cfeat, ccnt):
    """(B, F) f32 rows -> (B, K) f32 raw class sums with the per-leaf
    linear dot fused into the same program as the traversal (one
    dispatch per request block, like the constant-leaf _raw_kernel;
    class reduction accumulates f32 on the MXU)."""
    node = device_traverse(xb, sf, thr, cat, lc, rc, node0, depth)
    vals = _linear_leaf_values(xb, node, lv, const, coef, cfeat, ccnt)
    return jax.lax.dot(vals, cls_onehot.astype(jnp.float32),
                       preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnums=(9,))
def _lintransformed_kernel(xb, sf, thr, cat, lc, rc, lv, node0, cls_onehot,
                           sigmoid, depth, const, coef, cfeat, ccnt):
    raw = _linraw_kernel(xb, sf, thr, cat, lc, rc, lv, node0, cls_onehot,
                         depth, const, coef, cfeat, ccnt)
    if sigmoid > 0 and cls_onehot.shape[1] == 1:
        return 1.0 / (1.0 + jnp.exp(-2.0 * sigmoid * raw))
    if cls_onehot.shape[1] > 1:
        return jax.nn.softmax(raw, axis=1)
    return raw


def _bf16_round(arr):
    """Host-side f64 view of an array after a round-trip through
    bfloat16 (the rounding the bf16 leaf gather applies on device)."""
    return np.asarray(jnp.asarray(arr, jnp.bfloat16).astype(jnp.float32),
                      np.float64)


def _compact_int(arr, lo=-32768, hi=32767):
    """int16 copy when every value fits (the compact node layout —
    half the traversal gather bytes), int32 otherwise."""
    a = np.asarray(arr)
    if a.size and (a.min() < lo or a.max() > hi):
        return a.astype(np.int32)
    return a.astype(np.int16)


# Shape-stable padding (hot-swap support, docs/Fleet.md): the tree
# count pads to a multiple of TREE_PAD, so two model GENERATIONS of
# the same training recipe freeze to IDENTICAL kernel shapes — a
# challenger loaded behind the incumbent warms from the in-process jit
# cache (or the persistent disk cache) instead of recompiling, which
# is what keeps p99 flat through a hot-swap. (Depth is a TRACED kernel
# operand, never a compile key — see _leaf_kernel.) Padded trees are a
# frozen root leaf with value 0 and a zero one-hot row: they
# contribute nothing to any class sum, and the leaf-index surface
# slices back to the real tree count. Cost: <= (TREE_PAD-1) extra tree
# lanes of gather work.
TREE_PAD = 16
# the node axis (max nodes/leaves per tree) pads too: two generations
# with the same num_leaves knob can still grow different ACTUAL leaf
# counts, and a one-column difference would force a full recompile
NODE_PAD = 32
# linear-leaf models: every leaf's coefficient block pads to this fixed
# width, so two generations with different realized leaf-model widths
# (or a linear challenger behind a linear incumbent) still freeze to
# identical kernel shapes. Training's `linear_max_features` knob must
# stay <= COEF_PAD (config.py enforces the default; from_model_file
# re-checks loaded models).
COEF_PAD = 8


def _pad_up(n, multiple):
    n = max(int(n), 1)
    return ((n + multiple - 1) // multiple) * multiple


def _pad_rows(arr, pad, fill=0):
    """Append `pad` rows of `fill` along axis 0 (dtype preserved)."""
    a = np.asarray(arr)
    if pad <= 0:
        return a
    return np.concatenate(
        [a, np.full((pad,) + a.shape[1:], fill, a.dtype)])


def _pad_grid(arr, row_pad, col_multiple=NODE_PAD, fill=0):
    """Row padding + column padding to a multiple (the (T, nodes) SoA
    arrays; padded node slots are unreachable — no child edge points
    at them)."""
    a = _pad_rows(arr, row_pad, fill)
    cols = _pad_up(a.shape[1], col_multiple) - a.shape[1]
    if cols <= 0:
        return a
    return np.concatenate(
        [a, np.full((a.shape[0], cols), fill, a.dtype)], axis=1)


class CompiledPredictor:
    """A frozen, pre-compiled view of one trained model.

    Build with `from_booster` (a live GBDT/DART/GOSS) or
    `from_model_file` (the text format). Immutable after construction:
    later training on the source booster never changes served results.
    """

    # set by from_model_file (sidecar auto-discovery); None when frozen
    # from a live booster
    model_path = None
    profile_path = None
    profile = None
    # flipped in __init__ when the booster carries linear-leaf trees
    # (models/linear_leaves.py); class default keeps the empty-model
    # early return consistent
    is_linear = False

    def __init__(self, booster, num_iteration=-1,
                 max_batch_rows=DEFAULT_MAX_BATCH_ROWS, row_buckets=None,
                 warmup=True, warm_device_kernels=False,
                 serving_precision="f32"):
        setup_compilation_cache(getattr(booster, "config", None))
        if serving_precision not in SERVING_PRECISIONS:
            raise ValueError(
                f"serving_precision must be one of {SERVING_PRECISIONS}, "
                f"got {serving_precision!r}")
        n_used = booster._num_used_models(num_iteration)
        self.num_class = max(int(booster.num_class), 1)
        self.sigmoid = float(booster.sigmoid)
        self.num_features = int(booster.max_feature_idx) + 1
        self.num_trees = n_used
        self.feature_names = list(getattr(booster, "feature_names", []))
        self.max_batch_rows = int(max_batch_rows)
        self.serving_precision = serving_precision
        self.accuracy_bound = 0.0
        self.buckets = tuple(sorted(set(
            int(b) for b in (row_buckets or _default_buckets(
                self.max_batch_rows)))))
        self.stats = {"warmup_s": 0.0, "compile_cache_hits": 0,
                      "warm_dispatches": 0, "cold_dispatches": 0,
                      "buckets": list(self.buckets),
                      "serving_precision": serving_precision}
        self._warmed = set()
        if n_used == 0:
            self.depth = 0
            return
        sf, thr, dt, lc, rc, lv, has_split, depth = \
            booster._stacked_model_arrays(n_used)
        self.depth = int(depth)
        # shape-stable padding (TREE_PAD comment above): the kernel
        # shapes depend on the PADDED counts only; depth rides as a
        # traced operand
        t_pad = _pad_up(n_used, TREE_PAD)
        self._depth_arg = np.int32(self.depth)
        pad = t_pad - n_used
        # frozen copies: the booster's cache arrays mutate as training
        # continues; the served model must not. The exact host-reduce
        # arrays stay UNPADDED (the (N, T) leaf gather slices back to
        # real trees); the device SoA arrays pad.
        self._lv64 = np.array(lv, dtype=np.float64)             # (T, L)
        onehot = (np.arange(n_used)[:, None] % self.num_class
                  == np.arange(self.num_class)[None, :])
        self._onehot64 = onehot.astype(np.float64)              # (T, K)
        sf_p = _pad_grid(np.array(sf), pad)
        thr_p = _pad_grid(np.array(thr), pad)
        dt_p = _pad_grid(np.array(dt), pad)
        lc_p = _pad_grid(np.array(lc), pad)
        rc_p = _pad_grid(np.array(rc), pad)
        lv_p = _pad_grid(np.array(lv), pad)          # zero leaf values
        onehot_p = _pad_rows(onehot, pad)            # zero one-hot rows
        node0_np = np.concatenate(
            [np.where(has_split, 0, ~0).astype(np.int32),
             np.full(pad, ~0, np.int32)])            # padded: root leaf
        thr32 = f32_safe_thresholds(thr_p, dt_p)
        self._dev = (
            jnp.asarray(sf_p),
            jnp.asarray(thr32, jnp.float32),
            jnp.asarray(dt_p == Tree.CATEGORICAL),
            jnp.asarray(lc_p),
            jnp.asarray(rc_p),
            jnp.asarray(node0_np),
        )
        # the f32 device value arrays back only the off-endpoint
        # `_device` throughput variants — built lazily on first use so
        # a serving fleet (exact path: host f64 reduce; bf16 path: the
        # bf16 arrays) never pays a second value buffer per model
        self._lv_np = lv_p
        self._onehot_np = onehot_p.astype(np.float32)
        self._lv32 = self._onehot32 = None
        # linear-leaf models (models/linear_leaves.py): freeze the
        # per-leaf coefficient blocks into COEF_PAD-padded SoA arrays
        # alongside the node arrays. Constant models skip all of this —
        # their kernel set and shapes are untouched.
        lin = booster._stacked_linear_arrays(n_used)
        self.is_linear = lin is not None
        if self.is_linear:
            const, coef, cfeat, ccnt = lin
            if coef.shape[2] > COEF_PAD:
                raise ValueError(
                    f"model's widest leaf model has {coef.shape[2]} "
                    f"coefficients but serving pads to COEF_PAD="
                    f"{COEF_PAD}; retrain with linear_max_features <= "
                    f"{COEF_PAD}")
            l_pad = lv_p.shape[1]
            cw = COEF_PAD - coef.shape[2]

            def pad3(a, fill=0):
                a = np.concatenate(
                    [a, np.full((a.shape[0], l_pad - a.shape[1])
                                + a.shape[2:], fill, a.dtype)], axis=1)
                if a.ndim == 3 and cw > 0:
                    a = np.concatenate(
                        [a, np.full(a.shape[:2] + (cw,), fill, a.dtype)],
                        axis=2)
                return _pad_rows(a, pad, fill)

            # host f64 exact-path arrays stay UNPADDED on the tree axis
            # (like _lv64); device arrays pad on every axis
            self._lin_const64 = np.concatenate(
                [const, np.zeros((n_used, l_pad - const.shape[1]))],
                axis=1)
            self._lin_coef64 = pad3(coef)[:n_used]
            self._lin_feat = pad3(cfeat)[:n_used]
            self._lin_cnt = pad3(ccnt)[:n_used]
            store = jnp.bfloat16 if serving_precision == "bf16" else \
                jnp.float32
            self._lin_dev = (
                jnp.asarray(pad3(const), store),
                jnp.asarray(pad3(coef), store),
                jnp.asarray(pad3(cfeat)),
                jnp.asarray(pad3(ccnt)),
            )
        if serving_precision == "bf16":
            # compact node layout (int16 where node/feature ids fit —
            # at serving tree sizes they always do) + bf16 value arrays;
            # thresholds stay the f32-safe cast so every traversal
            # decision is IDENTICAL to the exact path
            self._dev16 = (
                jnp.asarray(_compact_int(sf_p)),
                self._dev[1],
                self._dev[2],
                jnp.asarray(_compact_int(lc_p)),
                jnp.asarray(_compact_int(rc_p)),
                jnp.asarray(_compact_int(node0_np)),
            )
            self._lv16 = jnp.asarray(lv_p, jnp.bfloat16)
            self._onehot16 = jnp.asarray(onehot_p.astype(np.float32),
                                         jnp.bfloat16)   # 0/1: exact
            self.accuracy_bound = self._pin_accuracy_bound(
                n_used, np.array(sf), np.array(thr))
        if warmup:
            self.warm_up(device_kernels=warm_device_kernels)

    def _pin_accuracy_bound(self, n_used, sf=None, thr=None):
        """Worst-case |bf16 output - exact f64 output| over ANY input,
        derived from the frozen leaf values: traversal decisions are
        exact, so the only error sources are the bf16 rounding of each
        gathered leaf value (bounded per tree by its worst-rounded
        leaf) and the f32 accumulation of the class reduction. The
        transform can amplify raw error (binary: dp/draw <= sigmoid/2),
        so the pinned bound covers raw AND transformed outputs. A 2x
        margin absorbs rounding-mode asymmetries. The serving skew
        monitor adopts this as its tolerance (server.build_monitors),
        keeping shadow scoring armed and quiet by construction.

        Linear leaves add a coefficient-rounding term: per tree, the
        worst leaf's |const - bf16(const)| + sum_j |coef_j -
        bf16(coef_j)| * env(feat_j), where env(f) is the model's OWN
        calibration envelope for feature f — twice the largest
        |threshold| any split placed on f (floored at 1.0). Inputs
        inside the envelope are covered by construction; a deployment
        feeding features far outside the range its splits ever tested
        is already out of calibration, and the skew monitor (whose
        tolerance this bound becomes) will surface it."""
        err_t = np.abs(self._lv64 - _bf16_round(self._lv64)).max(axis=1)
        if getattr(self, "is_linear", False):
            env = np.ones(self.num_features, np.float64)
            if sf is not None and sf.size:
                np.maximum.at(env, sf.reshape(-1),
                              2.0 * np.abs(thr.reshape(-1)))
            cerr = (np.abs(self._lin_coef64
                           - _bf16_round(self._lin_coef64))
                    * env[self._lin_feat])
            valid = (np.arange(self._lin_coef64.shape[2])[None, None, :]
                     < self._lin_cnt[:, :, None])
            lin_err_t = (np.abs(self._lin_const64
                                - _bf16_round(self._lin_const64))
                         + np.where(valid, cerr, 0.0).sum(axis=2)
                         ).max(axis=1)
            err_t = np.maximum(err_t, lin_err_t)
        raw_bound = float((err_t @ self._onehot64).max())
        mag_t = np.abs(self._lv64).max(axis=1)
        if getattr(self, "is_linear", False):
            # the f32-accumulation slack scales with the largest value a
            # lane can contribute — for a linear leaf that is the whole
            # envelope-bounded dot, not just the constant fallback
            lin_mag_t = (np.abs(self._lin_const64)
                         + np.where(valid,
                                    np.abs(self._lin_coef64)
                                    * env[self._lin_feat],
                                    0.0).sum(axis=2)).max(axis=1)
            mag_t = np.maximum(mag_t, lin_mag_t)
        mags = float((mag_t @ self._onehot64).max())
        slack = mags * n_used * float(np.finfo(np.float32).eps)
        factor = 1.0
        if self.sigmoid > 0 and self.num_class == 1:
            factor = max(1.0, self.sigmoid / 2.0)
        return 2.0 * factor * (raw_bound + slack)

    # ---------------------------------------------------------- constructors
    @classmethod
    def from_booster(cls, booster, num_iteration=-1, **kw):
        """Freeze a live booster (GBDT/DART/GOSS or a python-API
        Booster) into a CompiledPredictor."""
        gbdt = getattr(booster, "gbdt", booster)  # basic.Booster wraps
        return cls(gbdt, num_iteration=num_iteration, **kw)

    @classmethod
    def from_model_file(cls, path, num_iteration=-1, **kw):
        """Load the text model format and freeze it. Auto-discovers the
        `<model>.profile.json` dataset-profile sidecar (io/profile.py)
        when one sits next to the model: `predictor.profile` then
        carries the training baseline the drift monitor needs, so
        serving gets drift monitoring without an explicit --profile
        flag (and a registry hot-swap rebuilds monitors against the
        NEW model's own baseline)."""
        booster = create_boosting("gbdt", path)
        with open(path) as f:
            booster.load_model_from_string(f.read())
        inst = cls(booster, num_iteration=num_iteration, **kw)
        inst.model_path = os.fspath(path)
        from ..io.profile import DatasetProfile, model_profile_path
        sidecar = model_profile_path(path)
        if os.path.exists(sidecar):
            try:
                inst.profile = DatasetProfile.load(sidecar)
                inst.profile_path = sidecar
            except (OSError, ValueError) as e:
                Log.warning("ignoring unreadable profile sidecar %s: %s",
                            sidecar, e)
        return inst

    # --------------------------------------------------------------- warmup
    def warm_up(self, device_kernels=False):
        """AOT-compile every (kernel, bucket) pair a request can hit so
        no request shape ever traces at request time. The default warms
        the traversal/leaf kernel only — predict, predict_raw AND
        predict_leaf_index all dispatch it (the f64 reduction is host-
        side); `device_kernels=True` additionally warms the all-device
        f32 raw/transformed kernels for callers using the `_device`
        throughput variants. With the persistent compile cache active,
        a warm-process restart loads executables from disk —
        `stats["compile_cache_hits"]` counts how many did."""
        t0 = time.time()
        hits0 = compile_cache_hits()
        from ..telemetry.ledger import LEDGER
        bf16 = self.serving_precision == "bf16"
        for b in self.buckets:
            xb = jnp.zeros((b, self.num_features), jnp.float32)
            # the compile ledger attributes each bucket's lowering(s):
            # /metricz shows which row bucket cost the warmup time
            with LEDGER.label(f"serving_bucket_{b}"):
                jax.block_until_ready(self._dispatch_leaf(xb))
                self._warmed.add(("leaf", b))
                if bf16 and self.is_linear:
                    # linear bf16 endpoints dispatch the fused linear
                    # kernels; the constant bf16 pair is never hit
                    jax.block_until_ready(self._dispatch_linraw(xb))
                    jax.block_until_ready(
                        self._dispatch_lintransformed(xb))
                    self._warmed.update((("linraw", b), ("lintr", b)))
                elif bf16:
                    # predict/predict_raw dispatch the bf16 kernels —
                    # every endpoint's (kernel, bucket) pair pre-warms
                    jax.block_until_ready(self._dispatch_raw16(xb))
                    jax.block_until_ready(self._dispatch_transformed16(xb))
                    self._warmed.update((("raw16", b), ("tr16", b)))
                if device_kernels and self.is_linear and not bf16:
                    jax.block_until_ready(self._dispatch_linraw(xb))
                    jax.block_until_ready(
                        self._dispatch_lintransformed(xb))
                    self._warmed.update((("linraw", b), ("lintr", b)))
                elif device_kernels and not self.is_linear:
                    jax.block_until_ready(self._dispatch_raw32(xb))
                    jax.block_until_ready(self._dispatch_transformed32(xb))
                    self._warmed.update((("raw32", b), ("tr32", b)))
        self.stats["warmup_s"] = round(time.time() - t0, 3)
        self.stats["compile_cache_hits"] = compile_cache_hits() - hits0
        Log.info("CompiledPredictor warm: %d trees, %d buckets (max %d "
                 "rows) in %.2fs (%d persistent-cache hits)",
                 self.num_trees, len(self.buckets), self.max_batch_rows,
                 self.stats["warmup_s"], self.stats["compile_cache_hits"])
        return self

    # ------------------------------------------------------------ dispatch
    def _dispatch_leaf(self, xb):
        sf, thr, cat, lc, rc, node0 = self._dev
        return _leaf_kernel(xb, sf, thr, cat, lc, rc, node0,
                            self._depth_arg)

    def _f32_values(self):
        if self._lv32 is None:
            self._lv32 = jnp.asarray(self._lv_np, jnp.float32)
            self._onehot32 = jnp.asarray(self._onehot_np)
        return self._lv32, self._onehot32

    def _dispatch_raw32(self, xb):
        sf, thr, cat, lc, rc, node0 = self._dev
        lv32, onehot32 = self._f32_values()
        return _raw_kernel(xb, sf, thr, cat, lc, rc, lv32, node0,
                           onehot32, self._depth_arg)

    def _dispatch_transformed32(self, xb):
        sf, thr, cat, lc, rc, node0 = self._dev
        lv32, onehot32 = self._f32_values()
        return _transformed_kernel(xb, sf, thr, cat, lc, rc, lv32,
                                   node0, onehot32,
                                   self._depth_arg, self.sigmoid)

    def _dispatch_raw16(self, xb):
        sf, thr, cat, lc, rc, node0 = self._dev16
        return _raw16_kernel(xb, sf, thr, cat, lc, rc, self._lv16, node0,
                             self._onehot16, self._depth_arg)

    def _dispatch_transformed16(self, xb):
        sf, thr, cat, lc, rc, node0 = self._dev16
        return _transformed16_kernel(xb, sf, thr, cat, lc, rc, self._lv16,
                                     node0, self._onehot16,
                                     self._depth_arg, self.sigmoid)

    # linear-leaf fused kernels: ONE source kernel pair serves both
    # precisions — the f32 ladder passes f32 value arrays, the bf16
    # ladder passes the bf16-stored ones plus the compact node layout
    # (values upcast at the gather; each dtype signature is its own
    # executable, warmed by warm_up). Traversal thresholds are the
    # f32-safe cast either way, so decisions never move.
    def _linear_args(self):
        if self.serving_precision == "bf16":
            return self._dev16, (self._lv16, self._onehot16)
        return self._dev, self._f32_values()

    def _dispatch_linraw(self, xb):
        (sf, thr, cat, lc, rc, node0), (lv, onehot) = self._linear_args()
        const, coef, cfeat, ccnt = self._lin_dev
        return _linraw_kernel(xb, sf, thr, cat, lc, rc, lv, node0, onehot,
                              self._depth_arg, const, coef, cfeat, ccnt)

    def _dispatch_lintransformed(self, xb):
        (sf, thr, cat, lc, rc, node0), (lv, onehot) = self._linear_args()
        const, coef, cfeat, ccnt = self._lin_dev
        return _lintransformed_kernel(
            xb, sf, thr, cat, lc, rc, lv, node0, onehot, self.sigmoid,
            self._depth_arg, const, coef, cfeat, ccnt)

    def _linear_host_values(self, x, leaves):
        """Exact-path value stage for linear models: (N, T) f64 per-tree
        outputs from device-traversed leaf indices, mirroring
        Tree._linear_values BIT-FOR-BIT — same f64 arithmetic, same
        sequential accumulation order over coefficient slots (the
        COEF_PAD padding slots add an exact 0.0, see the comment in
        tree.py), same NaN-fallback semantics."""
        t_idx = np.arange(self.num_trees)[None, :]
        base = self._lv64[t_idx, leaves]                     # (N, T)
        cst = self._lin_const64[t_idx, leaves]
        cn = self._lin_cnt[t_idx, leaves]                    # (N, T)
        co = self._lin_coef64[t_idx[:, :, None], leaves[:, :, None],
                              np.arange(COEF_PAD)[None, None, :]]
        ft = self._lin_feat[t_idx[:, :, None], leaves[:, :, None],
                            np.arange(COEF_PAD)[None, None, :]]
        xf = x.astype(np.float64)[
            np.arange(x.shape[0])[:, None, None], ft]        # (N, T, C)
        valid = (np.arange(COEF_PAD)[None, None, :] < cn[:, :, None])
        live_nan = np.isnan(xf) & valid
        lin = cst.copy()
        for j in range(COEF_PAD):
            lin += np.where(valid[:, :, j] & ~np.isnan(xf[:, :, j]),
                            co[:, :, j] * xf[:, :, j], 0.0)
        return np.where((cn > 0) & ~np.any(live_nan, axis=2), lin, base)

    def _canon(self, x):
        """(N, num_features) f32 view of arbitrary row input: width is
        CANONICALIZED (narrow pads with 0.0 — absent trailing features,
        LibSVM-style; wide truncates — no split reads past
        max_feature_idx) so every dispatch reuses the warmed shapes."""
        x = np.atleast_2d(np.asarray(x, dtype=np.float32))
        if x.ndim != 2:
            raise ValueError(f"rows must be 2-D, got shape {x.shape}")
        f = x.shape[1]
        if f < self.num_features:
            x = np.pad(x, ((0, 0), (0, self.num_features - f)))
        elif f > self.num_features:
            x = x[:, :self.num_features]
        return x

    def _bucket(self, n):
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def _blocks(self, x, dispatch, kernel):
        """Pad-to-bucket dispatch over row blocks; returns the stacked
        host result. Requests beyond max_batch_rows chunk through the
        largest bucket (still zero recompilation)."""
        n = x.shape[0]
        outs = []
        top = self.buckets[-1]
        s = 0
        while s < n:
            xb = x[s:s + top]
            b = self._bucket(xb.shape[0])
            if (kernel, b) not in self._warmed:  # un-warmed kernel/shape
                self.stats["cold_dispatches"] += 1
                self._warmed.add((kernel, b))
            else:
                self.stats["warm_dispatches"] += 1
            pad = b - xb.shape[0]
            if pad:
                xb = np.pad(xb, ((0, pad), (0, 0)))
            outs.append(np.asarray(dispatch(jnp.asarray(xb)))[:b - pad])
            s += top
        return np.concatenate(outs, axis=0)

    # ------------------------------------------------------------- predict
    def predict_leaf_index(self, x):
        """(N, T) int32 leaf indices (predictor.hpp:108-118)."""
        x = self._canon(x)
        if self.num_trees == 0 or x.shape[0] == 0:
            return np.zeros((x.shape[0], self.num_trees), dtype=np.int32)
        # slice the shape-stable tree padding back off (TREE_PAD)
        return self._blocks(x, self._dispatch_leaf,
                            "leaf")[:, :self.num_trees]

    def predict_raw(self, x):
        """(N, K) f64 raw scores. Exact precision: device traversal +
        host f64 reduction, matching GBDT.predict_raw's host path
        bit-for-bit (module docstring). `serving_precision="bf16"`:
        all-device bf16 value stage, within `accuracy_bound` of the
        exact path by construction."""
        x = self._canon(x)
        n = x.shape[0]
        if self.num_trees == 0 or n == 0:
            return np.zeros((n, self.num_class))
        if self.serving_precision == "bf16":
            if self.is_linear:
                return self._blocks(x, self._dispatch_linraw,
                                    "linraw").astype(np.float64)
            return self._blocks(x, self._dispatch_raw16,
                                "raw16").astype(np.float64)
        leaves = self._blocks(x, self._dispatch_leaf,
                              "leaf")[:, :self.num_trees]     # (N, T)
        if self.is_linear:
            vals = self._linear_host_values(x, leaves)       # (N, T) f64
            # GBDT's host path reduces each class with a pairwise
            # np.sum over its tree subset; a BLAS matmul associates
            # differently in the last ulp, so mirror the sum exactly
            cls = np.arange(self.num_trees) % self.num_class
            out = np.empty((x.shape[0], self.num_class))
            for k in range(self.num_class):
                out[:, k] = vals[:, cls == k].sum(axis=1)
            return out
        vals = self._lv64[np.arange(self.num_trees)[None, :], leaves]
        return vals @ self._onehot64                         # (N, K) f64

    def predict(self, x):
        """(N, K) f64 transformed predictions (gbdt.py predict:
        binary sigmoid / multiclass softmax / raw passthrough). The
        bf16 precision transforms on device from the f32 accumulator
        output (`accuracy_bound` covers the transformed value too)."""
        if self.serving_precision == "bf16" and self.num_trees > 0:
            x = self._canon(x)
            if x.shape[0] == 0:
                return np.zeros((0, self.num_class))
            if self.is_linear:
                return self._blocks(x, self._dispatch_lintransformed,
                                    "lintr").astype(np.float64)
            return self._blocks(x, self._dispatch_transformed16,
                                "tr16").astype(np.float64)
        raw = self.predict_raw(x)
        if self.sigmoid > 0 and self.num_class == 1:
            return 1.0 / (1.0 + np.exp(-2.0 * self.sigmoid * raw))
        if self.num_class > 1:
            return common.softmax(raw, axis=1)
        return raw

    def predict_raw_device(self, x):
        """All-device f32 raw scores (MXU reduction): the throughput
        path; ~1e-6 of predict_raw."""
        x = self._canon(x)
        n = x.shape[0]
        if self.num_trees == 0 or n == 0:
            return np.zeros((n, self.num_class))
        if self.is_linear:
            # linear models route the device variants through the fused
            # linear kernels (bf16 predictors: bf16-stored values —
            # `accuracy_bound` applies instead of the ~1e-6 f32 figure)
            return self._blocks(x, self._dispatch_linraw,
                                "linraw").astype(np.float64)
        return self._blocks(x, self._dispatch_raw32,
                            "raw32").astype(np.float64)

    def predict_device(self, x):
        """All-device f32 transformed predictions; ~1e-6 of predict."""
        x = self._canon(x)
        n = x.shape[0]
        if self.num_trees == 0 or n == 0:
            return np.zeros((n, self.num_class))
        if self.is_linear:
            return self._blocks(x, self._dispatch_lintransformed,
                                "lintr").astype(np.float64)
        return self._blocks(x, self._dispatch_transformed32,
                            "tr32").astype(np.float64)

    # --------------------------------------------------------------- info
    def describe(self):
        """JSON-ready model card for `/healthz`."""
        return {
            "num_trees": self.num_trees,
            "num_class": self.num_class,
            "num_features": self.num_features,
            "depth": self.depth,
            "sigmoid": self.sigmoid,
            "max_batch_rows": self.max_batch_rows,
            "buckets": list(self.buckets),
            "serving_precision": self.serving_precision,
            "accuracy_bound": self.accuracy_bound,
            "is_linear": self.is_linear,
            "model_path": self.model_path,
            "has_profile": self.profile is not None,
        }


def _default_buckets(max_batch_rows):
    """Powers of two up to (and including a final bucket covering)
    max_batch_rows: request row counts round up to one of O(log N)
    compiled shapes, <= 2x padded-row overhead."""
    out = []
    b = 1
    while b < max_batch_rows:
        out.append(b)
        b <<= 1
    out.append(max_batch_rows)
    return out
