"""CompiledPredictor: a trained ensemble frozen for online serving.

The training-side predict path (models/gbdt.py predict_raw) re-derives
stacked arrays per call and compiles on first use — fine for batch
scoring, wrong for a standing service where the FIRST request must not
pay a trace+compile. This module freezes the model once:

- the ensemble becomes immutable padded SoA device arrays (class-major
  stacked split_feature / threshold / decision_type / left_child /
  right_child / leaf_value, via GBDT._stacked_model_arrays), with the
  same round-toward--inf f32 threshold cast as the training-side device
  predictor (models/gbdt.py f32_safe_thresholds) so f32 traversal
  decisions equal the f64 host reference;
- raw-score, transformed (sigmoid/softmax, gbdt.py predict) and
  leaf-index kernels are jit-compiled once per ROW-COUNT BUCKET
  (powers of two up to max_batch_rows), and warm_up() AOT-compiles
  every bucket a request can hit at load so no request shape ever
  traces at request time (the default warms the traversal/leaf kernel
  all three serving endpoints dispatch; `warm_device_kernels=True`
  extends that to the all-device f32 variants);
- the persistent XLA compile cache (config.setup_compilation_cache) is
  wired in before the first compile, so a warm-process restart loads
  executables from disk instead of recompiling — sub-second startup.

Precision contract: traversal decisions are exact (the f32 threshold
cast preserves every f64 `<=` outcome for f32-representable inputs, and
category ids are exact in f32), so `predict_raw`/`predict` gather the
traversed leaf indices and reduce in f64 ON HOST — bit-identical to
GBDT's host predict path (a (B, T) int32 transfer plus a tiny matmul;
the traversal is the O(depth * B * T) part and stays on device). The
`_device` variants keep the whole pipeline on device in f32 (reduction
on the MXU) for throughput-bound callers that tolerate ~1e-6.
"""

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..config import compile_cache_hits, setup_compilation_cache
from ..models.gbdt import create_boosting, device_traverse, f32_safe_thresholds
from ..models.tree import Tree
from ..utils import common
from ..utils.log import Log

DEFAULT_MAX_BATCH_ROWS = 4096


@functools.partial(jax.jit, static_argnums=(7,))
def _leaf_kernel(xb, sf, thr, cat, lc, rc, node0, depth):
    """(B, F) f32 rows -> (B, T) int32 leaf indices."""
    node = device_traverse(xb, sf, thr, cat, lc, rc, node0, depth)
    return (~node).astype(jnp.int32)


@functools.partial(jax.jit, static_argnums=(9,))
def _raw_kernel(xb, sf, thr, cat, lc, rc, lv, node0, cls_onehot, depth):
    """(B, F) f32 rows -> (B, K) f32 raw class sums (MXU reduction)."""
    node = device_traverse(xb, sf, thr, cat, lc, rc, node0, depth)
    t_idx = jnp.arange(sf.shape[0])
    vals = lv[t_idx[None, :], ~node]                        # (B, T)
    return vals @ cls_onehot                                # (B, K)


@functools.partial(jax.jit, static_argnums=(9, 10))
def _transformed_kernel(xb, sf, thr, cat, lc, rc, lv, node0, cls_onehot,
                        depth, sigmoid):
    """(B, F) f32 rows -> (B, K) f32 transformed predictions
    (gbdt.cpp:622-636 semantics: binary sigmoid / multiclass softmax /
    raw passthrough)."""
    raw = _raw_kernel(xb, sf, thr, cat, lc, rc, lv, node0, cls_onehot,
                      depth)
    if sigmoid > 0 and cls_onehot.shape[1] == 1:
        return 1.0 / (1.0 + jnp.exp(-2.0 * sigmoid * raw))
    if cls_onehot.shape[1] > 1:
        return jax.nn.softmax(raw, axis=1)
    return raw


class CompiledPredictor:
    """A frozen, pre-compiled view of one trained model.

    Build with `from_booster` (a live GBDT/DART/GOSS) or
    `from_model_file` (the text format). Immutable after construction:
    later training on the source booster never changes served results.
    """

    def __init__(self, booster, num_iteration=-1,
                 max_batch_rows=DEFAULT_MAX_BATCH_ROWS, row_buckets=None,
                 warmup=True, warm_device_kernels=False):
        setup_compilation_cache(getattr(booster, "config", None))
        n_used = booster._num_used_models(num_iteration)
        self.num_class = max(int(booster.num_class), 1)
        self.sigmoid = float(booster.sigmoid)
        self.num_features = int(booster.max_feature_idx) + 1
        self.num_trees = n_used
        self.feature_names = list(getattr(booster, "feature_names", []))
        self.max_batch_rows = int(max_batch_rows)
        self.buckets = tuple(sorted(set(
            int(b) for b in (row_buckets or _default_buckets(
                self.max_batch_rows)))))
        self.stats = {"warmup_s": 0.0, "compile_cache_hits": 0,
                      "warm_dispatches": 0, "cold_dispatches": 0,
                      "buckets": list(self.buckets)}
        self._warmed = set()
        if n_used == 0:
            self.depth = 0
            return
        sf, thr, dt, lc, rc, lv, has_split, depth = \
            booster._stacked_model_arrays(n_used)
        self.depth = int(depth)
        # frozen copies: the booster's cache arrays mutate as training
        # continues; the served model must not
        self._lv64 = np.array(lv, dtype=np.float64)             # (T, L)
        onehot = (np.arange(n_used)[:, None] % self.num_class
                  == np.arange(self.num_class)[None, :])
        self._onehot64 = onehot.astype(np.float64)              # (T, K)
        self._dev = (
            jnp.asarray(np.array(sf)),
            jnp.asarray(f32_safe_thresholds(thr, dt), jnp.float32),
            jnp.asarray(np.array(dt) == Tree.CATEGORICAL),
            jnp.asarray(np.array(lc)),
            jnp.asarray(np.array(rc)),
            jnp.asarray(np.where(has_split, 0, ~0).astype(np.int32)),
        )
        self._lv32 = jnp.asarray(lv, jnp.float32)
        self._onehot32 = jnp.asarray(onehot.astype(np.float32))
        if warmup:
            self.warm_up(device_kernels=warm_device_kernels)

    # ---------------------------------------------------------- constructors
    @classmethod
    def from_booster(cls, booster, num_iteration=-1, **kw):
        """Freeze a live booster (GBDT/DART/GOSS or a python-API
        Booster) into a CompiledPredictor."""
        gbdt = getattr(booster, "gbdt", booster)  # basic.Booster wraps
        return cls(gbdt, num_iteration=num_iteration, **kw)

    @classmethod
    def from_model_file(cls, path, num_iteration=-1, **kw):
        """Load the text model format and freeze it."""
        booster = create_boosting("gbdt", path)
        with open(path) as f:
            booster.load_model_from_string(f.read())
        return cls(booster, num_iteration=num_iteration, **kw)

    # --------------------------------------------------------------- warmup
    def warm_up(self, device_kernels=False):
        """AOT-compile every (kernel, bucket) pair a request can hit so
        no request shape ever traces at request time. The default warms
        the traversal/leaf kernel only — predict, predict_raw AND
        predict_leaf_index all dispatch it (the f64 reduction is host-
        side); `device_kernels=True` additionally warms the all-device
        f32 raw/transformed kernels for callers using the `_device`
        throughput variants. With the persistent compile cache active,
        a warm-process restart loads executables from disk —
        `stats["compile_cache_hits"]` counts how many did."""
        t0 = time.time()
        hits0 = compile_cache_hits()
        from ..telemetry.ledger import LEDGER
        for b in self.buckets:
            xb = jnp.zeros((b, self.num_features), jnp.float32)
            # the compile ledger attributes each bucket's lowering(s):
            # /metricz shows which row bucket cost the warmup time
            with LEDGER.label(f"serving_bucket_{b}"):
                jax.block_until_ready(self._dispatch_leaf(xb))
                self._warmed.add(("leaf", b))
                if device_kernels:
                    jax.block_until_ready(self._dispatch_raw32(xb))
                    jax.block_until_ready(self._dispatch_transformed32(xb))
                    self._warmed.update((("raw32", b), ("tr32", b)))
        self.stats["warmup_s"] = round(time.time() - t0, 3)
        self.stats["compile_cache_hits"] = compile_cache_hits() - hits0
        Log.info("CompiledPredictor warm: %d trees, %d buckets (max %d "
                 "rows) in %.2fs (%d persistent-cache hits)",
                 self.num_trees, len(self.buckets), self.max_batch_rows,
                 self.stats["warmup_s"], self.stats["compile_cache_hits"])
        return self

    # ------------------------------------------------------------ dispatch
    def _dispatch_leaf(self, xb):
        sf, thr, cat, lc, rc, node0 = self._dev
        return _leaf_kernel(xb, sf, thr, cat, lc, rc, node0, self.depth)

    def _dispatch_raw32(self, xb):
        sf, thr, cat, lc, rc, node0 = self._dev
        return _raw_kernel(xb, sf, thr, cat, lc, rc, self._lv32, node0,
                           self._onehot32, self.depth)

    def _dispatch_transformed32(self, xb):
        sf, thr, cat, lc, rc, node0 = self._dev
        return _transformed_kernel(xb, sf, thr, cat, lc, rc, self._lv32,
                                   node0, self._onehot32, self.depth,
                                   self.sigmoid)

    def _canon(self, x):
        """(N, num_features) f32 view of arbitrary row input: width is
        CANONICALIZED (narrow pads with 0.0 — absent trailing features,
        LibSVM-style; wide truncates — no split reads past
        max_feature_idx) so every dispatch reuses the warmed shapes."""
        x = np.atleast_2d(np.asarray(x, dtype=np.float32))
        if x.ndim != 2:
            raise ValueError(f"rows must be 2-D, got shape {x.shape}")
        f = x.shape[1]
        if f < self.num_features:
            x = np.pad(x, ((0, 0), (0, self.num_features - f)))
        elif f > self.num_features:
            x = x[:, :self.num_features]
        return x

    def _bucket(self, n):
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def _blocks(self, x, dispatch, kernel):
        """Pad-to-bucket dispatch over row blocks; returns the stacked
        host result. Requests beyond max_batch_rows chunk through the
        largest bucket (still zero recompilation)."""
        n = x.shape[0]
        outs = []
        top = self.buckets[-1]
        s = 0
        while s < n:
            xb = x[s:s + top]
            b = self._bucket(xb.shape[0])
            if (kernel, b) not in self._warmed:  # un-warmed kernel/shape
                self.stats["cold_dispatches"] += 1
                self._warmed.add((kernel, b))
            else:
                self.stats["warm_dispatches"] += 1
            pad = b - xb.shape[0]
            if pad:
                xb = np.pad(xb, ((0, pad), (0, 0)))
            outs.append(np.asarray(dispatch(jnp.asarray(xb)))[:b - pad])
            s += top
        return np.concatenate(outs, axis=0)

    # ------------------------------------------------------------- predict
    def predict_leaf_index(self, x):
        """(N, T) int32 leaf indices (predictor.hpp:108-118)."""
        x = self._canon(x)
        if self.num_trees == 0 or x.shape[0] == 0:
            return np.zeros((x.shape[0], self.num_trees), dtype=np.int32)
        return self._blocks(x, self._dispatch_leaf, "leaf")

    def predict_raw(self, x):
        """(N, K) f64 raw scores. Device traversal + host f64 reduction:
        matches GBDT.predict_raw's host path exactly (module
        docstring)."""
        x = self._canon(x)
        n = x.shape[0]
        if self.num_trees == 0 or n == 0:
            return np.zeros((n, self.num_class))
        leaves = self._blocks(x, self._dispatch_leaf, "leaf")  # (N, T)
        vals = self._lv64[np.arange(self.num_trees)[None, :], leaves]
        return vals @ self._onehot64                         # (N, K) f64

    def predict(self, x):
        """(N, K) f64 transformed predictions (gbdt.py predict:
        binary sigmoid / multiclass softmax / raw passthrough)."""
        raw = self.predict_raw(x)
        if self.sigmoid > 0 and self.num_class == 1:
            return 1.0 / (1.0 + np.exp(-2.0 * self.sigmoid * raw))
        if self.num_class > 1:
            return common.softmax(raw, axis=1)
        return raw

    def predict_raw_device(self, x):
        """All-device f32 raw scores (MXU reduction): the throughput
        path; ~1e-6 of predict_raw."""
        x = self._canon(x)
        n = x.shape[0]
        if self.num_trees == 0 or n == 0:
            return np.zeros((n, self.num_class))
        return self._blocks(x, self._dispatch_raw32,
                            "raw32").astype(np.float64)

    def predict_device(self, x):
        """All-device f32 transformed predictions; ~1e-6 of predict."""
        x = self._canon(x)
        n = x.shape[0]
        if self.num_trees == 0 or n == 0:
            return np.zeros((n, self.num_class))
        return self._blocks(x, self._dispatch_transformed32,
                            "tr32").astype(np.float64)

    # --------------------------------------------------------------- info
    def describe(self):
        """JSON-ready model card for `/healthz`."""
        return {
            "num_trees": self.num_trees,
            "num_class": self.num_class,
            "num_features": self.num_features,
            "depth": self.depth,
            "sigmoid": self.sigmoid,
            "max_batch_rows": self.max_batch_rows,
            "buckets": list(self.buckets),
        }


def _default_buckets(max_batch_rows):
    """Powers of two up to (and including a final bucket covering)
    max_batch_rows: request row counts round up to one of O(log N)
    compiled shapes, <= 2x padded-row overhead."""
    out = []
    b = 1
    while b < max_batch_rows:
        out.append(b)
        b <<= 1
    out.append(max_batch_rows)
    return out
