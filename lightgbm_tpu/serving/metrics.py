"""Serving-side metrics: counters, batch occupancy, latency ring.

No reference equivalent — the reference predictor is a library call
(predictor.hpp); a standing service needs its own accounting. Built on
the telemetry registry primitives (telemetry/registry.py: the
training-side metrics share the same lock discipline and ring-
percentile semantics — this module used to carry its own copies of
both). All methods are thread-safe (the HTTP handler pool and the
batcher worker update concurrently) and snapshot() is what `/metricz`
serializes (serving/server.py).

Latency percentiles come from a fixed-size ring buffer of the most
recent request latencies: O(1) record, O(ring log ring) on read, and a
bounded-memory view that tracks the CURRENT tail behavior instead of
averaging over the process lifetime.
"""

import time

from ..telemetry.registry import MetricsRegistry

RING_SIZE = 4096


class ServingMetrics:
    """Request/row/batch counters + latency ring for one serving
    process. The legacy attribute surface (`request_count`, ...) is
    kept as properties over the registry instruments."""

    def __init__(self, ring_size=RING_SIZE):
        self.registry = MetricsRegistry()
        self._requests = self.registry.counter("request_count")
        self._rows = self.registry.counter("rows_served")
        self._batches = self.registry.counter("batch_count")
        self._batched_rows = self.registry.counter("batched_rows")
        self._batched_requests = self.registry.counter("batched_requests")
        self._errors = self.registry.counter("error_count")
        # resilience layer (serving/admission.py, docs/Resilience.md):
        # requests refused before dispatch, split by cause
        self._shed = self.registry.counter("shed_count")
        self._deadline_expired = self.registry.counter(
            "deadline_expired_count")
        self._brownout = self.registry.gauge("brownout_active")
        self._latency = self.registry.histogram("latency_ms", ring_size)
        self.started_at = time.time()

    # ------------------------------------------------------------- writers
    def record_request(self, rows, latency_s):
        """One client request completed (rows served, end-to-end
        seconds). The group updates under ONE lock hold (reentrant
        registry lock) so a concurrent /metricz scrape never sees the
        count without its latency sample."""
        with self.registry.lock:
            self._requests.inc()
            self._rows.inc(int(rows))
            self._latency.observe(latency_s * 1e3)

    def record_batch(self, rows, n_requests):
        """One coalesced device dispatch (batcher drain)."""
        with self.registry.lock:
            self._batches.inc()
            self._batched_rows.inc(int(rows))
            self._batched_requests.inc(int(n_requests))

    def record_error(self):
        self._errors.inc()

    def record_shed(self):
        """One request refused by admission control (429/503)."""
        self._shed.inc()

    def record_deadline_expired(self):
        """One request dropped because its deadline passed (504)."""
        self._deadline_expired.inc()

    def set_brownout(self, active):
        """Publish the brownout state (1 = quality monitors disabled
        to save headroom, 0 = full service)."""
        self._brownout.set(1 if active else 0)

    # ------------------------------------------------------------- readers
    @property
    def request_count(self):
        return self._requests.value

    @property
    def rows_served(self):
        return self._rows.value

    @property
    def batch_count(self):
        return self._batches.value

    @property
    def batched_rows(self):
        return self._batched_rows.value

    @property
    def batched_requests(self):
        return self._batched_requests.value

    @property
    def error_count(self):
        return self._errors.value

    @property
    def shed_count(self):
        return self._shed.value

    @property
    def deadline_expired_count(self):
        return self._deadline_expired.value

    def latency_percentiles(self, pcts=(50, 95, 99)):
        """{p: milliseconds} over the ring's recorded window; empty dict
        before the first request (nearest-rank — see
        telemetry/registry.py Histogram.percentiles)."""
        return self._latency.percentiles(pcts)

    def snapshot(self):
        """One JSON-ready dict for `/metricz` (field set unchanged by
        the registry refactor; tests/test_telemetry.py pins parity).
        Reads under one lock hold — a consistent point-in-time view."""
        with self.registry.lock:
            pct = self.latency_percentiles()
            batches = self.batch_count
            occ = self.batched_rows / batches if batches else 0.0
            per_batch = self.batched_requests / batches if batches else 0.0
            snap = {
                "uptime_s": round(time.time() - self.started_at, 3),
                "request_count": self.request_count,
                "rows_served": self.rows_served,
                "error_count": self.error_count,
                "shed_count": self.shed_count,
                "deadline_expired_count": self.deadline_expired_count,
                "brownout_active": self._brownout.value,
                "batch_count": batches,
                "batch_occupancy_rows": round(occ, 3),
                "batch_occupancy_requests": round(per_batch, 3),
                "latency_p50_ms": round(pct.get(50, 0.0), 4),
                "latency_p95_ms": round(pct.get(95, 0.0), 4),
                "latency_p99_ms": round(pct.get(99, 0.0), 4),
                "latency_window": self._latency.window,
            }
        return snap
