"""Serving-side metrics: counters, batch occupancy, latency ring.

No reference equivalent — the reference predictor is a library call
(predictor.hpp); a standing service needs its own accounting. All
methods are thread-safe (the HTTP handler pool and the batcher worker
update concurrently) and snapshot() is what `/metricz` serializes
(serving/server.py).

Latency percentiles come from a fixed-size ring buffer of the most
recent request latencies: O(1) record, O(ring log ring) on read, and a
bounded-memory view that tracks the CURRENT tail behavior instead of
averaging over the process lifetime.
"""

import threading
import time

import numpy as np

RING_SIZE = 4096


class ServingMetrics:
    """Request/row/batch counters + latency ring for one serving
    process."""

    def __init__(self, ring_size=RING_SIZE):
        self._lock = threading.Lock()
        self._ring = np.zeros(int(ring_size), dtype=np.float64)
        self._ring_n = 0          # total latencies ever recorded
        self.started_at = time.time()
        self.request_count = 0
        self.rows_served = 0
        self.batch_count = 0
        self.batched_rows = 0     # rows that went through the batcher
        self.batched_requests = 0
        self.error_count = 0

    # ------------------------------------------------------------- writers
    def record_request(self, rows, latency_s):
        """One client request completed (rows served, end-to-end
        seconds)."""
        with self._lock:
            self.request_count += 1
            self.rows_served += int(rows)
            self._ring[self._ring_n % len(self._ring)] = latency_s * 1e3
            self._ring_n += 1

    def record_batch(self, rows, n_requests):
        """One coalesced device dispatch (batcher drain)."""
        with self._lock:
            self.batch_count += 1
            self.batched_rows += int(rows)
            self.batched_requests += int(n_requests)

    def record_error(self):
        with self._lock:
            self.error_count += 1

    # ------------------------------------------------------------- readers
    def latency_percentiles(self, pcts=(50, 95, 99)):
        """{p: milliseconds} over the ring's recorded window; empty dict
        before the first request."""
        with self._lock:
            n = min(self._ring_n, len(self._ring))
            if n == 0:
                return {}
            window = np.sort(self._ring[:n])
        # nearest-rank: ceil(n*p/100) - 1 (int(n*p/100) would bias one
        # rank high — p50 of 2 samples must be the lower one, and p99
        # of 100 samples rank 98, not the absolute max)
        return {p: float(window[max(0, -(-n * p // 100) - 1)])
                for p in pcts}

    def snapshot(self):
        """One JSON-ready dict for `/metricz`."""
        pct = self.latency_percentiles()
        with self._lock:
            occ = (self.batched_rows / self.batch_count
                   if self.batch_count else 0.0)
            per_batch = (self.batched_requests / self.batch_count
                         if self.batch_count else 0.0)
            return {
                "uptime_s": round(time.time() - self.started_at, 3),
                "request_count": self.request_count,
                "rows_served": self.rows_served,
                "error_count": self.error_count,
                "batch_count": self.batch_count,
                "batch_occupancy_rows": round(occ, 3),
                "batch_occupancy_requests": round(per_batch, 3),
                "latency_p50_ms": round(pct.get(50, 0.0), 4),
                "latency_p95_ms": round(pct.get(95, 0.0), 4),
                "latency_p99_ms": round(pct.get(99, 0.0), 4),
                "latency_window": min(self._ring_n, len(self._ring)),
            }
